"""WordEmbedding application driver.

Reference parity (ref: Applications/WordEmbedding/src/
distributed_wordembedding.cpp:147-457, main.cpp; flags from example/run.bat
and Readme.txt): flag-driven training of skip-gram/CBOW with negative
sampling or hierarchical softmax, optional per-row AdaGrad, vocab build/load
(-read_vocab / -save_vocab), subsampling (-sample), word2vec-format embedding
save (-binary), words/sec logging, and the pipelined block loop
(-is_pipeline) — here a producer thread + native MtQueue prefetching host batches while the
jitted TPU step runs.

Two training paths:

* **fused** (default): embeddings live as device arrays inside one jitted
  step — the TPU-native hot path (the whole reference PS round trip §3.3/§3.4
  collapses into the step's gathers/scatters).
* **PS mode** (``-use_ps=true``): embeddings live in MatrixTables; each data
  block pulls the rows it needs, trains locally, and pushes
  ``(new - old)/num_workers`` deltas — the reference Communicator protocol
  (ref: communicator.cpp:117-155 RequestParameter, :157-249
  AddDeltaParameter), including the AdaGrad g2 tables and the shared
  word-count table driving the lr decay. Multi-process: ranks agree on
  padded union buckets per round and the pull/push run as stacked SPMD
  programs (``_ps_round_meta`` / ``get_rows_local`` / ``add_rows_local``);
  ranks with exhausted corpus shards join rounds with zero deltas.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

import multiverso_tpu.analysis.mvtsan as _mvtsan
from multiverso_tpu import obs
from multiverso_tpu.config import constraints
# module-level (not lazy): -health_port/-metrics_port must be REGISTERED
# before MV_Init parses a pure trainer's argv, or the flags silently
# pass through as unconsumed arguments
from multiverso_tpu.serving import http_health
from multiverso_tpu.models.wordembedding.dictionary import Dictionary
from multiverso_tpu.models.wordembedding.huffman import HuffmanEncoder
from multiverso_tpu.models.wordembedding.pipeline import BatchPipeline, PrefetchPipeline
from multiverso_tpu.models.wordembedding.sampler import AliasSampler, subsample_keep_probs
from multiverso_tpu.models.wordembedding.skipgram import (
    SkipGramConfig,
    init_adagrad_slots,
    init_params,
    make_sorted_superbatch_step,
    make_sorted_train_step,
    make_superbatch_step,
    make_train_step,
)
from multiverso_tpu.utils.configure import (
    MV_DEFINE_bool,
    MV_DEFINE_double,
    MV_DEFINE_int,
    MV_DEFINE_string,
    GetFlag,
)
from multiverso_tpu.utils.log import CHECK, Log

__all__ = ["WEOptions", "WordEmbedding"]

# Flag parity (ref: example/run.bat:1-23, Readme.txt)
MV_DEFINE_int("size", 100, "embedding dimension")
MV_DEFINE_string("train_file", "", "training corpus")
MV_DEFINE_string("read_vocab", "", "load vocab from file")
MV_DEFINE_string("save_vocab", "", "save built vocab to file")
MV_DEFINE_bool("binary", False, "save embeddings in word2vec binary format")
MV_DEFINE_bool("cbow", False, "CBOW instead of skip-gram")
MV_DEFINE_double("alpha", 0.025, "initial learning rate")
MV_DEFINE_int("epoch", 1, "training epochs")
MV_DEFINE_int("window", 5, "context window")
MV_DEFINE_double("sample", 1e-3, "subsampling threshold (0 = off)")
MV_DEFINE_bool("hs", False, "hierarchical softmax instead of NS")
MV_DEFINE_int("negative", 5, "negative samples per positive")
MV_DEFINE_int(
    "threads", 1,
    "parallel batch-producer threads (corpus is sharded per thread, "
    "ref: trainer.cpp per-thread strided blocks)",
)
MV_DEFINE_int("min_count", 5, "drop words rarer than this")
MV_DEFINE_bool("stopwords", False, "filter stopwords")
MV_DEFINE_string("sw_file", "", "stopword list file")
MV_DEFINE_bool("use_adagrad", False, "AdaGrad row updates")
MV_DEFINE_int("data_block_size", 1 << 20, "ids per PS-mode data block")
MV_DEFINE_int("max_preload_data_size", 2, "prefetched batches (pipeline depth)")
MV_DEFINE_bool("is_pipeline", True, "overlap batch generation with compute")
MV_DEFINE_string("output_file", "embeddings.txt", "embedding output path")
MV_DEFINE_int("batch_size", 4096, "pairs per training step (TPU batch)")
MV_DEFINE_int("steps_per_call", 64, "microbatches scanned per device dispatch")
MV_DEFINE_string(
    "scale_mode", "raw",
    "batched-update scaling: raw (default — duplicates sum, word2vec's "
    "sequential semantics; measured BETTER quality on natural-statistics "
    "corpora AND ~5% faster, benchmarks/QUALITY.md) | row_mean "
    "(expected-count duplicate averaging; smoother but suppresses "
    "frequent-word learning) | row_mean_exact (realized counts, device "
    "pipeline only)",
)
MV_DEFINE_bool("use_ps", False, "train through parameter-server tables")
MV_DEFINE_bool(
    "presort", True,
    "host-presorted scatter ids (sorted-scatter device step; ~1.7x on TPU)",
)
MV_DEFINE_bool(
    "device_pipeline", False,
    "fully device-resident pipeline: corpus in HBM, sampling/negatives/"
    "presort on device, zero per-step host traffic (NS skip-gram runs the "
    "tuned sorted-scatter step; CBOW/HS/AdaGrad use the general step)",
)
MV_DEFINE_int(
    "upload_chunk_tokens", 0,
    "device-pipeline corpus upload chunk size in tokens (0 = auto, 16M): "
    "corpora larger than ~1.5 chunks stream in fixed-size chunks with the "
    "next chunk's host->device transfer overlapping the current chunk's "
    "training (double buffering — hides the upload on weak links)",
)
# Fault tolerance (resilience subsystem): crash-consistent auto-checkpoints
# + elastic resume on the host-batch fused path, the device pipeline
# (call-count cursor through the superbatch walk state) AND PS mode
# (drained, quorum-committed round checkpoints incl. the pipelined path's
# in-flight pull window). A run killed at step/call/round K and restarted
# with the same flags resumes from the latest valid checkpoint — params
# (incl. optimizer slots), counters, lr-schedule progress and the data
# cursor all restore, so the result matches an uninterrupted run.
MV_DEFINE_string(
    "checkpoint_dir", "",
    "root for crash-consistent training checkpoints (empty = off); "
    "versions publish atomically as <dir>/ckpt-<step>",
)
MV_DEFINE_int(
    "checkpoint_every_steps", 0,
    "auto-checkpoint every N dispatch steps (fused paths) / N PS rounds "
    "(0 = off)",
)
MV_DEFINE_double(
    "checkpoint_every_seconds", 0.0,
    "auto-checkpoint every N seconds (0 = off; combines with _steps)",
)
MV_DEFINE_int("checkpoint_retain", 3, "checkpoint versions kept by GC")
MV_DEFINE_bool(
    "checkpoint_async", True,
    "write checkpoints off the training thread (snapshot is taken on it)",
)
MV_DEFINE_bool(
    "resume", True,
    "resume from the latest valid checkpoint under -checkpoint_dir",
)
MV_DEFINE_string(
    "walk", "perm",
    "device-pipeline center selection: perm (default — without-replacement "
    "epoch-permutation walk, every kept position visited once per n_valid "
    "draws, the reference ParseSentence every-position-trains guarantee) | "
    "iid (with-replacement uniform draws; ~63% distinct coverage per "
    "epoch, measurably worse quality — benchmarks/QUALITY.md)",
)
# PS comms pipeline (the reference's -is_pipeline Communicator overlap,
# ref: communicator.cpp:117-249 + async_buffer.h, rebuilt for the PS
# table path): see README "PS comms" / DEPLOY.md for the tuning guide.
MV_DEFINE_string(
    "ps_pipeline_depth", "0",
    "PS-mode software pipeline depth: 0 (default) = fully synchronous "
    "rounds, bit-exact with prior releases; d >= 1 overlaps each block's "
    "training with the NEXT d blocks' pulls and the previous block's "
    "push on a comms thread — bounded staleness of exactly d rounds "
    "(block k trains on tables missing pushes k-d..k-1; 1 = the "
    "reference's -is_pipeline semantics). 'auto' starts at depth 1 and "
    "lets the staleness-adaptive controller widen/narrow the effective "
    "depth at drained round boundaries within "
    "[1, -ps_pipeline_depth_max], backing off on SLO burn or a loss "
    "regression (DEPLOY.md \"SLOs and the depth controller\")",
)
MV_DEFINE_int(
    "ps_pipeline_depth_max", 4,
    "-ps_pipeline_depth=auto only: the widest effective depth the "
    "controller may reach — the staleness bound the run is willing to "
    "pay (block k may train on tables missing up to this many rounds' "
    "pushes)",
)
MV_DEFINE_int(
    "ps_depth_decide_rounds", 8,
    "-ps_pipeline_depth=auto only: take one controller decision every "
    "this many PS rounds — each decision reads the window's measured "
    "overlap%% and is agreed pod-wide (allgather-min) before the depth "
    "changes, so every rank's collective sequence stays identical",
)
MV_DEFINE_string(
    "ps_compress", "none",
    "PS push-delta wire compression (pipelined path only): none | "
    "sparse (SparseFilter (idx,val) pairs when >50%% of the block is "
    "zero — lossless) | 1bit (OneBitsFilter sign+scale with per-row "
    "error-feedback residual — 32x smaller, quantized; AdaGrad g2 "
    "deltas always ride sparse, never 1bit). Pack/unpack run as jitted "
    "device programs, so compression never stalls the host",
)
MV_DEFINE_string(
    "ps_pull_packed", "auto",
    "PS pull-direction packing (sparse-pull path only): auto (default — "
    "pack pulls whenever -ps_compress != none, so both wire directions "
    "compress together) | on (always pack) | off (always dense). Packed "
    "pulls move (idx,val) pairs instead of dense row blocks when the "
    "stale set is mostly zeros; lossless (bit-exact vs dense), with an "
    "automatic dense fallback whenever the packed encoding would be "
    "larger. Pod-wide setting: every rank must agree (the pack runs "
    "inside the SPMD pull program)",
)
MV_DEFINE_bool(
    "ps_sparse_pull", True,
    "PS-mode dirty-row tracked pulls (pipelined path only): route the "
    "tables through SparseMatrixTable so repeat pulls move only rows "
    "dirtied since this worker's last pull (bitmap doubled when "
    "pipelining, as the reference does); local fresh rows are served "
    "from the client's row cache — values identical to a full pull",
)
MV_DEFINE_int(
    "table_tier_hbm_mb", 0,
    "total HBM budget (MB, split across the embedding/g2 tables "
    "proportionally to their row counts) for the tiered HBM<->host "
    "MatrixTable: 0 (default) keeps tables fully HBM-resident; > 0 keeps "
    "each full logical table in host RAM with a fixed-budget HBM cache "
    "of hot rows + look-ahead prefetch from the block prep — training "
    "vocabularies far past chip HBM (see DEPLOY.md for sizing). Routes "
    "training through the pipelined PS block loop: implies -use_ps and "
    "-ps_pipeline_depth >= 1, replaces -device_pipeline, and disables "
    "-ps_sparse_pull (the HBM cache subsumes the dirty-row client cache)",
)


@dataclasses.dataclass
class WEOptions:
    size: int = 100
    train_file: str = ""
    read_vocab: str = ""
    save_vocab: str = ""
    binary: bool = False
    cbow: bool = False
    alpha: float = 0.025
    epoch: int = 1
    window: int = 5
    sample: float = 1e-3
    hs: bool = False
    negative: int = 5
    threads: int = 1
    min_count: int = 5
    stopwords: bool = False
    sw_file: str = ""
    use_adagrad: bool = False
    data_block_size: int = 1 << 20
    max_preload_data_size: int = 2
    is_pipeline: bool = True
    output_file: str = "embeddings.txt"
    batch_size: int = 4096
    steps_per_call: int = 64
    scale_mode: str = "raw"
    use_ps: bool = False
    presort: bool = True
    device_pipeline: bool = False
    upload_chunk_tokens: int = 0
    walk: str = "perm"
    ps_pipeline_depth: int = 0
    # derived from -ps_pipeline_depth=auto (from_flags); programmatic
    # callers set it directly. auto starts at depth 1 and the controller
    # adapts within [1, ps_pipeline_depth_max].
    ps_depth_auto: bool = False
    ps_pipeline_depth_max: int = 4
    ps_depth_decide_rounds: int = 8
    ps_compress: str = "none"
    ps_pull_packed: str = "auto"
    ps_sparse_pull: bool = True
    # float so tests/benches can request sub-MB caches; the CLI flag is
    # whole MB
    table_tier_hbm_mb: float = 0
    checkpoint_dir: str = ""
    checkpoint_every_steps: int = 0
    checkpoint_every_seconds: float = 0.0
    checkpoint_retain: int = 3
    checkpoint_async: bool = True
    resume: bool = True
    seed: int = 1

    @classmethod
    def from_flags(cls) -> "WEOptions":
        # seed has no flag; ps_depth_auto/ps_pipeline_depth derive from
        # the one string-valued -ps_pipeline_depth ("auto" or an int)
        derived = ("seed", "ps_depth_auto", "ps_pipeline_depth")
        names = [
            f.name for f in dataclasses.fields(cls) if f.name not in derived
        ]
        kw = {n: GetFlag(n) for n in names}
        raw = str(GetFlag("ps_pipeline_depth")).strip().lower()
        if raw == "auto":
            kw["ps_depth_auto"] = True
            kw["ps_pipeline_depth"] = 1
        else:
            try:
                kw["ps_pipeline_depth"] = int(raw)
            except ValueError:
                CHECK(False,
                      f"-ps_pipeline_depth must be an integer or 'auto', "
                      f"got {raw!r}")
        return cls(**kw)


class _PSCommsStats:
    """Per-run PS comms accounting: per-round pull/train/push wall time,
    overlap %, and pre/post-compression byte counters. Registered as the
    Dashboard "ps_comms" section so ``Dashboard.Display()`` reports the
    pipeline's measured win (and ``to_dict`` feeds the bench leg).
    Thread-safe: the comms thread and the training thread both record."""

    def __init__(self, dim: int):
        import threading

        self._lock = threading.Lock()
        self.dim = dim
        self.rounds = 0
        self.pull_s = 0.0
        self.train_s = 0.0
        self.push_s = 0.0
        self.wall_s = 0.0
        self.pull_rows_dense = 0  # rows a full (non-tracked) pull moves
        self.pull_rows_wire = 0   # rows actually transferred
        self.pull_bytes_wire = 0  # bytes actually moved (packed pulls
        # ship (idx, val) pairs, so bytes can undercut rows * row_bytes)
        self.push_bytes_dense = 0  # pre-compression delta bytes
        self.push_bytes_wire = 0   # bytes actually moved
        # last completed round's timers — the straggler detector's
        # piggyback payload (_ps_round_meta allgathers them per round)
        self.last_train_us = 0.0
        self.last_push_us = 0.0
        from multiverso_tpu.utils.dashboard import Dashboard

        Dashboard.add_section("ps_comms", self.lines, snapshot=self.to_dict)

    def add_pull(self, dt: float, rows_dense: int, rows_wire: int,
                 bytes_wire: Optional[int] = None) -> None:
        if bytes_wire is None:
            bytes_wire = rows_wire * self.dim * 4
        with self._lock:
            self.rounds += 1
            self.pull_s += dt
            self.pull_rows_dense += rows_dense
            self.pull_rows_wire += rows_wire
            self.pull_bytes_wire += bytes_wire
        from multiverso_tpu.utils.dashboard import Dashboard

        # process-global cumulative mirror (this object is per-run)
        Dashboard.counter("ps.pull_bytes_wire").add(bytes_wire)

    def add_train(self, dt: float) -> None:
        with self._lock:
            self.train_s += dt
            self.last_train_us = dt * 1e6

    def add_push(self, dt: float, bytes_dense: int, bytes_wire: int) -> None:
        with self._lock:
            self.push_s += dt
            self.push_bytes_dense += bytes_dense
            self.push_bytes_wire += bytes_wire
            self.last_push_us = dt * 1e6
        from multiverso_tpu.utils.dashboard import Dashboard

        Dashboard.counter("ps.push_bytes_wire").add(bytes_wire)

    def set_wall(self, seconds: float) -> None:
        with self._lock:
            self.wall_s = seconds

    def last_round_timers_us(self) -> tuple:
        """(train_us, push_us) of the most recently completed stages —
        what this rank contributes to the round-meta timer allgather."""
        with self._lock:
            return self.last_train_us, self.last_push_us

    def stage_seconds(self) -> tuple:
        """(pull_s, train_s, push_s, rounds) cumulative snapshot — the
        depth controller diffs two snapshots to get a decision window's
        overlap% (``wall_s`` is only set after the loop, so the run-wide
        ``overlap_pct()`` cannot serve a live decision)."""
        with self._lock:
            return self.pull_s, self.train_s, self.push_s, self.rounds

    @staticmethod
    def _overlap_pct(pull_s: float, train_s: float, push_s: float,
                     wall_s: float) -> float:
        """How much of the serialized stage time the pipeline hid:
        ``(sum(stages) - wall) / sum(stages)``. 0 when the stages ran
        strictly back to back (the sync path's shape), higher the more
        pull/push rode under training."""
        stages = pull_s + train_s + push_s
        if stages <= 0 or wall_s <= 0:
            return 0.0
        return max(0.0, 100.0 * (stages - wall_s) / stages)

    def overlap_pct(self) -> float:
        with self._lock:
            return self._overlap_pct(
                self.pull_s, self.train_s, self.push_s, self.wall_s
            )

    def to_dict(self) -> Dict[str, float]:
        with self._lock:
            # comms + training threads both record: snapshot under the
            # same lock the writers hold (mvlint R9)
            rounds = self.rounds
            r = max(rounds, 1)
            row_b = self.dim * 4
            return {
                "rounds": rounds,
                "pull_ms_per_round": round(1e3 * self.pull_s / r, 3),
                "train_ms_per_round": round(1e3 * self.train_s / r, 3),
                "push_ms_per_round": round(1e3 * self.push_s / r, 3),
                "overlap_pct": round(self._overlap_pct(
                    self.pull_s, self.train_s, self.push_s, self.wall_s
                ), 1),
                "pull_bytes_dense_per_round": round(
                    self.pull_rows_dense * row_b / r, 1
                ),
                "pull_bytes_wire_per_round": round(
                    self.pull_bytes_wire / r, 1
                ),
                "push_bytes_dense_per_round": round(
                    self.push_bytes_dense / r, 1
                ),
                "push_bytes_wire_per_round": round(
                    self.push_bytes_wire / r, 1
                ),
            }

    def lines(self) -> list:
        d = self.to_dict()
        return [
            "[ps_comms] rounds=%d pull=%.2fms train=%.2fms push=%.2fms "
            "per round, overlap=%.1f%%" % (
                d["rounds"], d["pull_ms_per_round"],
                d["train_ms_per_round"], d["push_ms_per_round"],
                d["overlap_pct"],
            ),
            "[ps_comms] pull bytes/round dense=%.0f wire=%.0f; "
            "push bytes/round dense=%.0f wire=%.0f" % (
                d["pull_bytes_dense_per_round"],
                d["pull_bytes_wire_per_round"],
                d["push_bytes_dense_per_round"],
                d["push_bytes_wire_per_round"],
            ),
        ]


class WordEmbedding:
    def __init__(self, options: WEOptions, dictionary: Optional[Dictionary] = None):
        self.opt = options
        from multiverso_tpu.analysis.guards import OrderedLock

        # leaf lock for the PS progress counters (_wc_cum,
        # _ps_global_pairs, _ps_push_entered, _ps_rounds_pushed): the
        # comms pipe thread commits rounds while the training thread
        # reads them for lr/checkpoint/containment (mvlint R9). No calls
        # run under it, so it cannot participate in an R2 inversion.
        self._ps_state_lock = OrderedLock("we._ps_state_lock")
        CHECK(options.train_file or dictionary is not None,
              "need -train_file or a prebuilt dictionary")
        if dictionary is None:
            if options.read_vocab:
                dictionary = Dictionary.load(options.read_vocab)
            else:
                CHECK(not any(p.endswith(".npy")
                              for p in options.train_file.split(";")),
                      "-train_file=<ids>.npy (pre-encoded id stream, e.g. "
                      "from models.wordembedding.synth) requires -read_vocab")
                stop = None
                if options.stopwords and options.sw_file:
                    stop = set(
                        w for line in open(options.sw_file) for w in line.split()
                    )
                dictionary = Dictionary.build(
                    options.train_file.split(";"),
                    min_count=options.min_count,
                    stopwords=stop,
                )
                if options.save_vocab:
                    dictionary.save(options.save_vocab)
        self.dict = dictionary
        V = len(self.dict)
        CHECK(V >= 2, "vocabulary too small")
        self.cfg = SkipGramConfig(
            vocab_size=V,
            dim=options.size,
            negatives=options.negative,
            cbow=options.cbow,
            window=options.window,
            seed=options.seed,
        )
        self.huffman = HuffmanEncoder(self.dict.counts) if options.hs else None
        self.sampler = None if options.hs else AliasSampler(self.dict.counts)
        out_rows = self.huffman.num_inner_nodes if options.hs else V
        self._out_rows = out_rows
        # Tiered tables (-table_tier_hbm_mb > 0): the full logical tables
        # live in host RAM with a fixed-budget HBM cache of hot rows —
        # the config for vocabularies past chip HBM. Training must be
        # block-structured (the working set has to be known before the
        # step), so the run routes through the PIPELINED PS block loop:
        # pulls fault rows in on the comms thread while the previous
        # block trains, and the block-prep look-ahead prefetches the next
        # block's unions on top of that.
        self._tier = options.table_tier_hbm_mb > 0
        # Flag implications live in config/constraints.py (the single
        # source mvlint R12 and the DEPLOY.md constraint table also
        # read) — re-implementing a rewrite inline here is lint drift.
        constraints.apply_implications(options, log=Log.Info)
        # Model parallelism (-num_shards=N + -device_pipeline): the tables
        # must be born row-sharded — materializing the full (V, D) arrays
        # on one device first and re-placing them later would OOM at the
        # exact scale sharding exists for (the reference's headline: a
        # 21M-vocab ~6B-param embedding sharded across servers, ref:
        # Applications/WordEmbedding/README.md:12). Only a DEDICATED shard
        # axis triggers this: on a role-ALL 1-D mesh the table axis
        # doubles as the worker axis and silently sharding every run over
        # it would surprise.
        self._tab = self._rep = None
        if options.device_pipeline:
            from multiverso_tpu.parallel import mesh as mesh_lib
            from multiverso_tpu.runtime import runtime as _runtime

            rt = _runtime()
            mesh = rt.mesh if rt.started else None
            if (
                mesh is not None
                and mesh_lib.SHARD_AXIS in mesh.axis_names
                and int(mesh.shape[mesh_lib.SHARD_AXIS]) > 1
            ):
                self._tab = mesh_lib.table_sharding(mesh, 2)
                self._rep = mesh_lib.replicated_sharding(mesh)
                self._nshards = int(mesh.shape[mesh_lib.SHARD_AXIS])
        if self._tier:
            # the whole point is that (V, D) never materializes as one
            # resident device array: PS-mode training reads/writes through
            # the tiered tables, and params fills from the host tier after
            # training (embeddings()/save_embeddings)
            self.params: Dict[str, jnp.ndarray] = {}
        elif self._tab is not None:
            ns = self._nshards

            def _make_sharded():
                p = init_params(self.cfg)
                if options.hs:
                    p["emb_out"] = jnp.zeros(
                        (out_rows, options.size), jnp.float32
                    )
                if options.use_adagrad:
                    p.update(init_adagrad_slots(self.cfg, out_rows))
                # pad rows to the shard multiple INSIDE the jit: sampler
                # ids are all < V, so pad rows are never gathered or
                # scattered; embeddings() slices them back off
                return {
                    k: jnp.pad(
                        v,
                        ((0, -(-v.shape[0] // ns) * ns - v.shape[0]), (0, 0)),
                    )
                    for k, v in p.items()
                }

            keys = ["emb_in", "emb_out"] + (
                ["g2_in", "g2_out"] if options.use_adagrad else []
            )
            self.params: Dict[str, jnp.ndarray] = jax.jit(
                _make_sharded, out_shardings={k: self._tab for k in keys}
            )()
        else:
            self.params = init_params(self.cfg)
            if options.hs:
                self.params["emb_out"] = jnp.zeros(
                    (out_rows, options.size), jnp.float32
                )
            if options.use_adagrad:
                self.params.update(init_adagrad_slots(self.cfg, out_rows))
        kw = dict(hs=options.hs, use_adagrad=options.use_adagrad)
        if options.presort:
            # sorted-scatter path: scale_mode is baked into the host-side
            # presort arrays, the device step is scale-mode agnostic
            step_fn = make_sorted_train_step(self.cfg, **kw)
            superstep_fn = make_sorted_superbatch_step(self.cfg, **kw)
        else:
            step_fn = make_train_step(self.cfg, scale_mode=options.scale_mode, **kw)
            # superbatch: scan over steps_per_call microbatches in one
            # dispatch (dispatch latency amortization)
            superstep_fn = make_superbatch_step(
                self.cfg, scale_mode=options.scale_mode, **kw
            )
        self._step = jax.jit(step_fn, donate_argnums=(0,))
        self._superstep = jax.jit(superstep_fn, donate_argnums=(0,))
        self.words_trained = 0

    # ------------------------------------------------------------- training

    def _lr(self, progress: float) -> float:
        """word2vec schedule: alpha * (1 - progress), floored at alpha*1e-4
        (the reference's word-count table drives the same decay —
        distributed_wordembedding.cpp:92-127)."""
        return self.opt.alpha * max(1e-4, 1.0 - progress)

    def _run_batch(self, batch: Dict[str, np.ndarray], lr: float) -> jax.Array:
        """Dispatches one step and returns the *device* loss — callers must
        not force it per step (a host sync per step serialises the pipeline
        on the device-dispatch round trip)."""
        o = self.opt
        if o.presort:
            dev = {
                k: jnp.asarray(v)
                for k, v in batch.items()
                if v is not None
            }
            self.params, loss = self._step(self.params, dev, jnp.float32(lr))
            return loss
        ctx = None if batch.get("contexts") is None else jnp.asarray(batch["contexts"])
        if o.hs:
            self.params, loss = self._step(
                self.params,
                jnp.asarray(batch["centers"]),
                jnp.asarray(batch["points"]),
                jnp.asarray(batch["codes"]),
                jnp.asarray(batch["lengths"]),
                ctx,
                jnp.float32(lr),
            )
        else:
            self.params, loss = self._step(
                self.params,
                jnp.asarray(batch["centers"]),
                jnp.asarray(batch["outputs"]),
                ctx,
                jnp.float32(lr),
            )
        return loss

    def _maybe_checkpoint(
        self, ckpt, step: int, epoch: int, batches_in_epoch: int,
        pairs_done: int, restarts: int,
    ) -> None:
        """Policy-gated atomic checkpoint. The host snapshot (device_get)
        happens HERE on the training thread — the next dispatch donates
        these buffers — and only the file write rides the async thread."""

        def build():
            # np.array (copy=True): device_get is zero-copy on CPU
            # backends and the next dispatch donates these buffers
            host = {
                k: np.array(jax.device_get(v))
                for k, v in self.params.items()
            }
            meta = {
                "epoch": epoch,
                "batches_in_epoch": batches_in_epoch,
                "pairs_done": pairs_done,
                "step": step,
                "restarts": restarts,
            }
            from multiverso_tpu.resilience import save_checkpoint

            return lambda: save_checkpoint(
                ckpt.root, step, arrays=host, meta=meta
            )

        ckpt.maybe_save(step, build)

    def _ondevice_maybe_checkpoint(
        self, ckpt, calls: int, seq: int, pairs_done: int,
        legs_done_pairs: int, total_pairs: int, walk_t: int,
        epoch_done: int, accepted_dev, epoch_calls0: int,
        synced_calls: int, ppc: float, key, restarts: int,
    ) -> None:
        """Device-pipeline checkpoint: params + the device-side data
        cursor (leg seq, call count, walk_t, PRNG key) + the projection
        state. The accepted accumulator is READ, not drained — the
        regular sync cadence (and so the lr math) is untouched, which is
        what makes kill+restart bit-identical to an uninterrupted run.
        Snapshot happens on the training thread (the next dispatch
        donates the param buffers); only the file write rides async."""

        def build():
            # np.array (copy=True): on CPU backends device_get returns a
            # ZERO-COPY view of the device buffer, which the next
            # dispatch donates — the async writer would read reused
            # memory through it
            host = {
                k: np.array(jax.device_get(v))
                for k, v in self.params.items()
            }
            host["__prng_key"] = np.array(jax.device_get(key))
            meta = {
                "kind": "device_pipeline",
                "seq": int(seq),
                "calls": int(calls),
                "pairs_done": int(pairs_done),
                "legs_done_pairs": int(legs_done_pairs),
                "total_pairs": int(total_pairs),
                "walk_t": int(walk_t),
                "epoch_done": int(epoch_done),
                "accepted_partial": float(accepted_dev),
                "epoch_calls0": int(epoch_calls0),
                "synced_calls": int(synced_calls),
                "ppc": float(ppc),
                "restarts": int(restarts),
            }
            from multiverso_tpu.resilience import save_checkpoint

            return lambda: save_checkpoint(
                ckpt.root, calls, arrays=host, meta=meta
            )

        ckpt.maybe_save(calls, build)

    # ---------------------------------------------------------- PS mode

    def _ps_setup(self):
        """Create the PS tables (ref: communicator.cpp:17-31
        PrepareParameterTables — input matrix, output matrix, and with
        -use_adagrad the two g2 accumulator tables; plus the word-count
        table that coordinates the global lr decay,
        distributed_wordembedding.cpp:82-127)."""
        from multiverso_tpu.api import MV_CreateTable
        from multiverso_tpu.tables import (
            MatrixTableOption,
            SparseMatrixTableOption,
            TieredMatrixTableOption,
        )

        V, D = self.cfg.vocab_size, self.opt.size
        out_rows = self._out_rows
        scale = 0.5 / D
        # Pipelined PS (-ps_pipeline_depth >= 1) with -ps_sparse_pull:
        # the weight/g2 tables become SparseMatrixTables so repeat pulls
        # move only rows dirtied since this client's last pull; the
        # per-worker bitmap doubles (is_pipeline=True) exactly as the
        # reference does for its prefetch buffer
        # (sparse_matrix_table.cpp:187-190)
        sparse = (
            not self._tier
            and self.opt.ps_pipeline_depth >= 1
            and self.opt.ps_sparse_pull
        )
        # Tiered tables (-table_tier_hbm_mb): the flag is the TOTAL cache
        # budget, split across the weight/g2 tables proportionally to
        # their row counts (every table's rows are D floats wide)
        tier_mb = float(self.opt.table_tier_hbm_mb)
        tier_rows_total = (V + out_rows) * (2 if self.opt.use_adagrad else 1)

        def _mk(**kw):
            if self._tier:
                share = tier_mb * kw["num_row"] / tier_rows_total
                return MV_CreateTable(
                    TieredMatrixTableOption(hbm_mb=share, **kw)
                )
            if sparse:
                return MV_CreateTable(
                    SparseMatrixTableOption(is_pipeline=True, **kw)
                )
            return MV_CreateTable(MatrixTableOption(**kw))

        self._ps_sparse_tables = sparse
        self._t_in = _mk(
            num_row=V, num_col=D, init_uniform=(-scale, scale),
            seed=self.cfg.seed, name="we_emb_in",
        )
        self._t_out = _mk(
            num_row=out_rows, num_col=D, name="we_emb_out",
        )
        # delta-averaging divisor = concurrent delta-pushing clients (ref:
        # communicator.cpp AddDeltaParameter divides by its worker count).
        # One client per PROCESS: mesh worker slices within a process are a
        # single logical client; each process trains its own corpus shard
        # and pushes one averaged delta per round.
        self._num_workers = jax.process_count()
        # AdaGrad g2 accumulator tables (plain += like the reference's —
        # the AdaGrad math runs worker-side on the pulled block; the g2
        # deltas are averaged by the same divisor so identical blocks on
        # every rank reproduce the single-client rounds exactly)
        self._t_g2_in = self._t_g2_out = None
        if self.opt.use_adagrad:
            self._t_g2_in = _mk(num_row=V, num_col=D, name="we_g2_in")
            self._t_g2_out = _mk(
                num_row=out_rows, num_col=D, name="we_g2_out",
            )
        # shared word(pair)-count table driving the lr schedule: one row per
        # client; the global trained-pair count is the table sum, so every
        # rank decays its lr identically (ref: the word-count KV table,
        # distributed_wordembedding.cpp:82-127). Rows pad to this process's
        # worker-axis extent (add_rows_local bucket rule).
        nproc = jax.process_count()
        # int32 rows stay exact (a float32 table would corrupt counts past
        # 2^24), but one int32 row per client would overflow past 2^31
        # cumulative pairs (plausible for multi-epoch 100M+-token runs) and
        # silently corrupt every rank's lr schedule — so each client keeps
        # TWO rows, (lo, hi) base-2^30 limbs of its exact cumulative count,
        # maintained by host-side carry in _wc_push_and_read
        self._t_wc = MV_CreateTable(MatrixTableOption(
            num_row=2 * nproc, num_col=1, dtype="int32", name="we_word_count",
        ))
        self._wc_bucket = max(2, self._t_wc.num_workers // nproc)
        self._wc_row_ids = np.arange(2 * nproc, dtype=np.int32)
        with self._ps_state_lock:
            # exact cumulative count (host int) + failure-domain round
            # accounting (comms thread increments; containment reads
            # after drain): pushes entered vs committed
            self._wc_cum = 0
            self._ps_global_pairs = 0
            self._ps_push_entered = 0
            self._ps_rounds_pushed = 0
        self._ps_restarts = 0
        self._ps_codecs: Dict[str, object] = {}
        self._ps_deadline_s = None
        # client-local row caches for the dirty-row tracked pull: server
        # truth for every row this client has pulled, kept coherent by
        # applying the client's OWN pushed deltas (other clients' pushes
        # arrive via the staleness exchange -> re-pull)
        if self._ps_sparse_tables:
            self._ps_cache = {
                "in": np.zeros((V, D), np.float32),
                "out": np.zeros((out_rows, D), np.float32),
            }
            if self.opt.use_adagrad:
                self._ps_cache["g2_in"] = np.zeros((V, D), np.float32)
                self._ps_cache["g2_out"] = np.zeros((out_rows, D), np.float32)
        # look-ahead prefetch targets (tiered mode): the block-prep
        # thread submits the NEXT block's row unions to each tiered
        # table's prefetch pipe, so rows land in HBM before the pull that
        # needs them
        self._tier_prefetch_tables = (
            [(t, side) for _n, t, side in self._ps_entries()]
            if self._tier else []
        )
        # packed pulls (pull-direction SparseFilter): -ps_pull_packed
        # on/off forces it; auto engages with the push compression flag —
        # lossless either way (bit-exact vs dense, with a size-based
        # dense fallback inside the table)
        pp = str(self.opt.ps_pull_packed).strip().lower()
        CHECK(pp in ("auto", "on", "off"),
              f"-ps_pull_packed must be auto|on|off, got {pp!r}")
        self._ps_pull_packed = self._ps_sparse_tables and (
            pp == "on"
            or (pp == "auto" and self.opt.ps_compress != "none")
        )

    def _wc_push_and_read(self, inc: int) -> int:
        """Add this client's trained-pair increment and read back the global
        count — one collective round every rank joins together (the
        reference's AddWordCount/GetWordCount pair,
        distributed_wordembedding.cpp:92-127).

        The client's exact cumulative count lives on the host; the table
        carries its base-2^30 limbs in rows (2p, 2p+1) = (lo, hi). Each
        push adds the LIMB DELTAS (lo delta may be negative on carry —
        fine for the += updater), so rows never exceed 2^30 and the
        global count stays exact far past int32 (up to 2^61 pairs)."""
        p = jax.process_index()
        mask = (1 << 30) - 1
        with self._ps_state_lock:
            c_old, c_new = self._wc_cum, self._wc_cum + int(inc)
            self._wc_cum = c_new
        lw = self._wc_bucket
        ids = np.full(lw, 2 * p, np.int64)
        deltas = np.zeros((lw, 1), np.int32)
        ids[1] = 2 * p + 1
        deltas[0, 0] = (c_new & mask) - (c_old & mask)
        deltas[1, 0] = (c_new >> 30) - (c_old >> 30)
        self._t_wc.add_rows_local(ids, deltas)
        # row-subset get of exactly the 2*nproc limb rows (baked-id
        # program: multiprocess-safe, no whole-table materialisation —
        # the table's storage may be padded well past the logical rows)
        vals = (
            self._t_wc.get_rows_fixed(self._wc_row_ids)
            .astype(np.int64)
            .reshape(-1)
        )
        return int(vals[0::2].sum() + (vals[1::2].sum() << 30))

    def _ps_round_meta(self, have: int, ni: int, no: int,
                       timers_us=None, round_idx: int = -1):
        """Per-round cross-process agreement (the fix the round-2 CHECK
        sketched): every process contributes its block's union sizes, ranks
        agree on the padded power-of-two bucket, and the round's pull/push
        then runs as ONE identical SPMD program on every rank
        (get_rows_local/add_rows_local stack the per-process buckets along
        the worker axis). Returns (any_rank_has_data, bucket_in,
        bucket_out); one tiny host allgather per round, single-process
        short-circuits.

        ``timers_us`` (pipelined path only): this rank's last-round
        (train_us, push_us) piggyback on the SAME allgather — widened to
        5 int64s, still one collective — and the gathered per-rank round
        timers feed the straggler detector. The sync path never passes
        timers, so its 3-wide wire shape (and bit-exact trace) is
        untouched."""
        if jax.process_count() == 1:
            return have > 0, self._bucket(max(ni, 1)), self._bucket(max(no, 1))
        from jax.experimental import multihost_utils

        if timers_us is None:
            meta = multihost_utils.process_allgather(
                np.asarray([have, ni, no], np.int64)
            ).reshape(-1, 3)
        else:
            meta = multihost_utils.process_allgather(
                np.asarray(
                    [have, ni, no, int(timers_us[0]), int(timers_us[1])],
                    np.int64,
                )
            ).reshape(-1, 5)
            st = getattr(self, "_ps_straggler", None)
            if st is not None:
                # per-rank round timer = train + push (the stages a slow
                # host inflates); runs on the comms thread, bounded work
                st.feed(
                    (meta[:, 3] + meta[:, 4]).astype(np.float64),
                    round_idx,
                )
        return (
            bool(meta[:, 0].any()),
            self._bucket(max(int(meta[:, 1].max()), 1)),
            self._bucket(max(int(meta[:, 2].max()), 1)),
        )

    def _ps_depth_decide(self, round_idx: int, proposal: int) -> int:
        """Pod-wide depth agreement (comms-pipe task): allgather every
        rank's controller proposal and take the MIN — the conservative
        depth every rank can honor. Proposals are computed from
        rank-local windows, so they can disagree; the min keeps the
        widen/narrow collective and the per-rank pull issue sequences
        identical. Single-process short-circuits."""
        if jax.process_count() == 1:
            return int(proposal)
        from jax.experimental import multihost_utils

        got = multihost_utils.process_allgather(
            np.asarray([proposal], np.int64)
        )
        return int(got.min())

    def _ps_depth_decision(self, r: int, ctl, pipe, wd, snap, rounds0: int,
                           t0: float, loss_dev) -> None:
        """One controller decision at a drained round boundary: window
        overlap% from the stage-clock deltas since the last decision, an
        in-loop SLO verdict, a rank-local proposal, then the pod-agreed
        depth (awaiting the decide ticket orders it after every
        previously-submitted pull/push on the FIFO comms pipe — that IS
        the drained boundary). Every decision, hold included, lands in
        the flight recorder as a ``depth_decision`` event."""
        from multiverso_tpu.obs import slo as _slo

        pull_s, train_s, push_s, rounds = self._ps_stats.stage_seconds()
        d_rounds = rounds - rounds0
        old = ctl.depth
        overlap = 0.0
        dec = None
        # d_rounds counts COMMS-THREAD pull completions since the last
        # decision — at a dry tail (this rank out of blocks) or under
        # scheduler skew it can be 0 on one rank while positive on
        # another. The judgment is skippable; the decide collective is
        # NOT: every rank reaches `decide:{r}` at the same pipe position
        # or the next rank's round-meta allgather pairs against this
        # rank's decide allgather and gloo dies on the size mismatch.
        if d_rounds > 0:
            wall = max(time.perf_counter() - t0, 1e-9)
            d_pull = pull_s - snap[0]
            d_train = train_s - snap[1]
            d_push = push_s - snap[2]
            overlap = _PSCommsStats._overlap_pct(
                d_pull, d_train, d_push, wall
            )
            # SLO verdict rides the decision cadence (deterministic
            # rounds, benchable overhead); an unarmed engine costs one
            # empty check
            breached = bool(
                _slo.engine.rules
                and _slo.engine.evaluate(ingest=True)["breached"]
            )
            if loss_dev is not None:
                # device sync only at decision rounds — never per round
                ctl.observe_loss(float(loss_dev))
            dec = ctl.propose(
                overlap_pct=overlap,
                pull_ms=1e3 * d_pull / d_rounds,
                train_ms=1e3 * d_train / d_rounds,
                push_ms=1e3 * d_push / d_rounds,
                slo_breached=breached,
            )
        agreed = self._ps_await(
            pipe.submit(
                lambda rr=r, p=(dec.depth if dec is not None else old): (
                    self._ps_depth_decide(rr, p)
                ),
                tag=f"decide:{r}",
            ),
            r, pipe, wd,
        )
        ctl.depth = agreed
        if dec is not None:
            rec = dec.to_dict()
            reason = dec.reason
        else:
            rec = {
                "action": "hold", "depth": int(agreed),
                "reason": "dry_window", "overlap_pct": 0.0,
                "pull_ms": 0.0, "train_ms": 0.0, "push_ms": 0.0,
                "loss_ema": ctl._loss_ema,
                "best_loss_ema": ctl._best_loss_ema,
                "slo_breached": False,
            }
            reason = "dry_window"
        rec.update(
            round=int(r), old_depth=int(old), agreed_depth=int(agreed),
        )
        self._ps_depth_decisions.append(rec)
        obs.recorder.record("depth_decision", **rec)
        if agreed != old:
            Log.Info(
                "[WordEmbedding] depth controller: %s %d -> %d at round "
                "%d (%s, window overlap %.1f%%)",
                "narrow" if agreed < old else "widen", old, agreed, r,
                reason, overlap,
            )

    @staticmethod
    def _bucket(n: int) -> int:
        """Pad union sizes to power-of-two buckets: bounded recompiles."""
        b = 1024
        while b < n:
            b *= 2
        return b

    # ------------------------------------------- PS mode: pipelined rounds
    #
    # The reference's -is_pipeline Communicator overlap (ref:
    # communicator.cpp:117-249 on its own thread + async_buffer.h double
    # buffering), rebuilt as a software pipeline over the block rounds:
    # while block k trains on device, block k+1..k+d's pulls and block
    # k-1's push run on a comms thread (utils.async_buffer.TaskPipe — one
    # thread, strict submission order, so every rank's collective sequence
    # stays SPMD-lockstep). Staleness contract at -ps_pipeline_depth=d:
    # block k trains on tables missing exactly the last d blocks' deltas
    # (pull k issued before pushes k-d..k-1 land), and the lr schedule
    # reads the global pair count as of round k-d-1 — bounded, documented,
    # and deterministic (every rank derives both from the same collective
    # results, so lr traces still agree rank-to-rank). d=1 is the
    # reference's one-round-stale pipeline; d=0 never reaches this path
    # (bit-exact sync rounds).

    def _ps_block_prep(self, batches: Optional[list]):
        """Host-side prep of one block (no table access — safe on the
        ASyncBuffer prefetch thread): node unions + compact-id remap +
        presort, exactly the sync path's math. ``None`` stays ``None``
        (local corpus exhausted; the rank still joins rounds)."""
        if not batches:
            return None
        from multiverso_tpu.models.wordembedding.skipgram import presort_batch

        o = self.opt
        uin = np.unique(np.concatenate([b["centers"] for b in batches]))
        okey = "points" if o.hs else "outputs"
        uout = np.unique(
            np.concatenate([b[okey].reshape(-1) for b in batches])
        )
        if o.cbow:
            ctx = np.concatenate([b["contexts"].reshape(-1) for b in batches])
            uin = np.unique(np.concatenate([uin, np.maximum(ctx, 0)]))
        remapped = []
        for b in batches:
            rb = {"centers": np.searchsorted(uin, b["centers"]).astype(np.int32)}
            if o.hs:
                rb["points"] = np.searchsorted(uout, b["points"]).astype(np.int32)
                rb["codes"], rb["lengths"] = b["codes"], b["lengths"]
            else:
                rb["outputs"] = np.searchsorted(uout, b["outputs"]).astype(np.int32)
            if o.cbow:
                cx = b["contexts"]
                rb["contexts"] = np.where(
                    cx >= 0, np.searchsorted(uin, np.maximum(cx, 0)), -1
                ).astype(np.int32)
            remapped.append(
                presort_batch(rb, hs=o.hs, cbow=o.cbow, scale_mode=o.scale_mode)
            )
        xs_np = {
            k: np.stack([b[k] for b in remapped])
            for k in remapped[0]
            if remapped[0][k] is not None
        }
        # tiered look-ahead: this prep runs one block AHEAD of training
        # (ASyncBuffer fill thread), so these unions are exactly the rows
        # the pull after next will touch — submit them as prefetch
        # tickets so they fault into the HBM cache under the current
        # block's training (ISSUE 6 tentpole; tickets are advisory and
        # never block the prep thread). They ride the COMMS pipe, not a
        # per-table one: all collective dispatch on one thread
        for table, side in getattr(self, "_tier_prefetch_tables", ()):
            table.prefetch(
                uin if side == "in" else uout,
                pipe=getattr(self, "_tier_prefetch_pipe", None),
            )
        return {
            "nbatches": len(batches), "uin": uin, "uout": uout, "xs": xs_np,
        }

    def _ps_entries(self):
        """(name, table, side) in the FIXED per-round op order — every
        rank must issue the same collective sequence."""
        ent = [("in", self._t_in, "in"), ("out", self._t_out, "out")]
        if self.opt.use_adagrad:
            ent += [
                ("g2_in", self._t_g2_in, "in"),
                ("g2_out", self._t_g2_out, "out"),
            ]
        return ent

    def _ps_pull_round(self, blk, round_idx: int = -1):
        """Comms-thread pull task for one round: cross-rank meta
        agreement, then the (optionally dirty-row tracked) pulls, then
        the local model block assembly — all under the comms thread's
        serialization, so the assembled block deterministically reflects
        every push ordered before this pull and none after (the
        documented d-round staleness). Returns ``None`` when no rank has
        data (the loop's termination signal)."""
        from multiverso_tpu.resilience import chaos
        from multiverso_tpu.utils.dashboard import monitor

        chaos.maybe_hang_collective(round_idx)  # hung-collective drills
        with obs.span("ps.round.pull", round=round_idx):
            return self._ps_pull_round_inner(blk, round_idx, monitor)

    def _ps_pull_round_inner(self, blk, round_idx: int, monitor):
        o = self.opt
        t0 = time.perf_counter()
        have = blk is not None
        ni_u = int(blk["uin"].size) if have else 0
        no_u = int(blk["uout"].size) if have else 0
        timers = (
            self._ps_stats.last_round_timers_us()
            if getattr(self, "_ps_straggler", None) is not None
            else None
        )
        any_data, ni, no = self._ps_round_meta(
            1 if have else 0, ni_u, no_u,
            timers_us=timers, round_idx=round_idx,
        )
        if not any_data:
            return None
        ids_in = np.zeros(ni, np.int64)
        ids_out = np.zeros(no, np.int64)
        if have:
            ids_in[:ni_u] = blk["uin"]
            ids_out[:no_u] = blk["uout"]
        rows_dense = 0
        rows_wire = 0
        bytes_wire = 0
        row_b = self.opt.size * 4
        pulled = {}
        with monitor("ps.pull"):
            for name, table, side in self._ps_entries():
                ids_b = ids_in if side == "in" else ids_out
                n_u = ni_u if side == "in" else no_u
                rows_dense += ids_b.size
                if self._ps_sparse_tables:
                    from multiverso_tpu.updaters import GetOption

                    uids = (
                        (blk["uin"] if side == "in" else blk["uout"])
                        if have
                        else np.zeros(0, np.int64)
                    )
                    stale, rows, wire, nbytes = table.get_stale_rows_local(
                        uids, GetOption(worker_id=table.client_view()),
                        packed=self._ps_pull_packed,
                    )
                    cache = self._ps_cache[name]
                    if stale.size:
                        cache[stale] = rows
                    W = cache[ids_b]  # fancy indexing: already a copy
                    rows_wire += wire
                    bytes_wire += nbytes
                elif self._tier:
                    # tiered pull wire = the block readback (inherent to
                    # the PS protocol) PLUS the host->device rows this
                    # pull FAULTED into the cache (the tier's own
                    # traffic; hits cost no extra transfer)
                    before = table.cache_stats()["faulted_rows"]
                    W = np.asarray(
                        table.get_rows_local(ids_b), np.float32
                    ).copy()
                    faulted = table.cache_stats()["faulted_rows"] - before
                    rows_wire += ids_b.size + faulted
                    bytes_wire += (ids_b.size + faulted) * row_b
                else:
                    W = np.asarray(
                        table.get_rows_local(ids_b), np.float32
                    ).copy()
                    rows_wire += ids_b.size
                    bytes_wire += ids_b.size * row_b
                W[n_u:] = 0.0
                pulled[name] = W
        dt = time.perf_counter() - t0
        self._ps_stats.add_pull(dt, rows_dense, rows_wire, bytes_wire)
        return {
            "blk": blk, "ids_in": ids_in, "ids_out": ids_out,
            "n_in": ni_u, "n_out": no_u, "pulled": pulled,
        }

    def _ps_train_block(self, pull, lr: float):
        """Training-thread leg of one round: device step over the
        assembled block + delta encode (jitted, device-side when
        compressing). Returns ``(payloads, inc, loss_or_None)`` — dry
        ranks produce zero payloads so the push stays lockstep."""
        from multiverso_tpu.models.wordembedding.skipgram import (
            SkipGramConfig,
            make_sorted_superbatch_step,
        )

        o = self.opt
        nw = self._num_workers
        t0 = time.perf_counter()
        ids_in, ids_out = pull["ids_in"], pull["ids_out"]
        ni, no = ids_in.size, ids_out.size
        n_in, n_out = pull["n_in"], pull["n_out"]
        blk = pull["blk"]
        entries = self._ps_entries()
        if blk is None:
            payloads = {}
            for name, _table, side in entries:
                ids_b = ids_in if side == "in" else ids_out
                codec = self._ps_codecs[name]
                if codec.mode == "none":
                    payloads[name] = (
                        "dense", np.zeros((ids_b.size, o.size), np.float32)
                    )
                else:
                    z = jnp.zeros((ids_b.size, o.size), jnp.float32)
                    payloads[name] = codec.encode(z, z, ids_b, 0, float(nw))
            self._ps_stats.add_train(time.perf_counter() - t0)
            return payloads, 0, None
        nb = blk["nbatches"]
        donate = o.ps_compress == "none"
        key = (ni, no, nb, donate)
        step = self._ps_steps.get(key)
        if step is None:
            cfg = SkipGramConfig(
                vocab_size=ni, dim=o.size, negatives=o.negative,
                cbow=o.cbow, window=o.window,
            )
            step = jax.jit(
                make_sorted_superbatch_step(
                    cfg, hs=o.hs, use_adagrad=o.use_adagrad
                ),
                # the compressed encode reads the OLD device params after
                # the step — donation would invalidate them
                donate_argnums=(0,) if donate else (),
            )
            self._ps_steps[key] = step
        name2key = {
            "in": "emb_in", "out": "emb_out",
            "g2_in": "g2_in", "g2_out": "g2_out",
        }
        params = {
            name2key[name]: jnp.asarray(pull["pulled"][name])
            for name, _t, _s in entries
        }
        olds = None if donate else dict(params)
        xs = {k: jnp.asarray(v) for k, v in blk["xs"].items()}
        new_params, loss = step(params, xs, jnp.float32(lr))
        payloads = {}
        for name, _table, side in entries:
            pk = name2key[name]
            ids_b = ids_in if side == "in" else ids_out
            n_u = n_in if side == "in" else n_out
            codec = self._ps_codecs[name]
            if codec.mode == "none":
                d = np.asarray(new_params[pk]) - pull["pulled"][name]
                d[n_u:] = 0.0
                payloads[name] = ("dense", (d / nw).astype(np.float32))
            else:
                payloads[name] = codec.encode(
                    new_params[pk], olds[pk], ids_b, n_u, float(nw)
                )
        self._ps_stats.add_train(time.perf_counter() - t0)
        return payloads, o.batch_size * nb, loss

    def _ps_push_round(self, payloads, ids_in, ids_out, n_in, n_out,
                       inc: int, round_idx: int = -1) -> int:
        """Comms-thread push task: apply every table's (possibly packed)
        averaged delta in the fixed entry order, compensate the local row
        caches with this client's own contribution, then run the shared
        word-count round. Returns the new GLOBAL pair count (the lr
        schedule's deterministic input d+1 rounds later)."""
        from multiverso_tpu.updaters import AddOption
        from multiverso_tpu.utils import quantization as q
        from multiverso_tpu.utils.dashboard import monitor

        t0 = time.perf_counter()
        bytes_dense = 0
        bytes_wire = 0
        # failure-domain accounting: entered vs completed tells the
        # containment path whether the drained boundary is CLEAN (no push
        # died between its first and last table collective)
        with self._ps_state_lock:
            self._ps_push_entered += 1
        with obs.span("ps.round.push", round=round_idx), monitor("ps.push"):
            for name, table, side in self._ps_entries():
                ids_b = ids_in if side == "in" else ids_out
                n_u = n_in if side == "in" else n_out
                pl = payloads[name]
                bytes_dense += ids_b.size * self.opt.size * 4
                bytes_wire += q.payload_nbytes(pl)
                if self._ps_sparse_tables:
                    opt = AddOption(worker_id=table.client_view())
                    if pl[0] == "dense":
                        table.add_rows_local(ids_b, pl[1], opt)
                    else:
                        table.add_rows_local_packed(ids_b, pl, opt)
                    # coherence: the client's cache tracks server truth
                    # for rows only IT pushes; rows other clients touch
                    # come back via the staleness exchange
                    dec = q.decode_payload(pl)
                    if n_u:
                        self._ps_cache[name][ids_b[:n_u]] += dec[:n_u]
                else:
                    if pl[0] == "dense":
                        table.add_rows_local(ids_b, pl[1])
                    else:
                        table.add_rows_local_packed(ids_b, pl)
            new_global = self._wc_push_and_read(inc)
        with self._ps_state_lock:
            self._ps_global_pairs = new_global
            self._ps_rounds_pushed += 1  # round boundary committed
        self._ps_stats.add_push(
            time.perf_counter() - t0, bytes_dense, bytes_wire
        )
        return new_global

    # ------------------------------- PS mode: failure domains + checkpoints
    #
    # Failure-domain hardening (resilience subsystem): the pipelined
    # collectives run behind per-ticket deadlines (-collective_timeout_s)
    # and a peer-liveness watchdog (-heartbeat_deadline_s) — a hung or
    # dead rank raises a structured RankFailure on the training thread,
    # the pipe is poisoned (fail-fast PipelineBroken for later calls) and
    # drain() lands every in-flight push at a consistent round boundary.
    # Checkpoints: -checkpoint_dir/-checkpoint_every_steps count in PS
    # ROUNDS (every rank checkpoints at the SAME round — the save is a
    # two-phase quorum-committed collective). Pipelined checkpoints go
    # through drain() first AND stage each rank's d in-flight pull
    # buffers, so a resumed run replays the exact warm-up the staleness
    # window left in flight — kill + restart == uninterrupted, bit for
    # bit, at any depth.

    class _Resolved:
        """A pre-resolved ticket: what a checkpoint-staged pull (or wc
        count) looks like to the resumed pipeline loop."""

        __slots__ = ("_value",)

        def __init__(self, value):
            self._value = value

        def result(self, timeout=None):
            return self._value

        def wait_result(self, *args, **kwargs):
            return self._value

        def done(self):
            return True

    @staticmethod
    def _set_ready(ready: bool, phase: str) -> None:
        """Alive-vs-ready wiring (ISSUE 7): the training paths flip
        readiness once their tables are created AND any resume landed, so
        ``/readyz`` (and the supervisor's ready-file watch) can tell a
        restoring rank from a wedged one."""
        from multiverso_tpu.serving import http_health

        http_health.set_ready(ready, phase=phase)

    def _ps_tables(self):
        """The PS-mode table set, in creation order (checkpoint identity:
        restore binds by the same order)."""
        tabs = [self._t_in, self._t_out]
        if self.opt.use_adagrad:
            tabs += [self._t_g2_in, self._t_g2_out]
        return tabs + [self._t_wc]

    @staticmethod
    def _pack_pull(out: Dict[str, np.ndarray], i: int, pull) -> None:
        """Flatten one in-flight pull payload into npz-able keys."""
        p = f"pull{i}_"
        if pull is None:  # the termination sentinel (no rank has data)
            out[p + "sentinel"] = np.int64(1)
            return
        out[p + "ids_in"] = pull["ids_in"]
        out[p + "ids_out"] = pull["ids_out"]
        out[p + "n_in"] = np.int64(pull["n_in"])
        out[p + "n_out"] = np.int64(pull["n_out"])
        for name, W in pull["pulled"].items():
            out[p + "pulled_" + name] = W
        blk = pull["blk"]
        if blk is None:  # dry rank: joins rounds with zero deltas
            out[p + "dry"] = np.int64(1)
            return
        out[p + "nbatches"] = np.int64(blk["nbatches"])
        out[p + "uin"] = blk["uin"]
        out[p + "uout"] = blk["uout"]
        for k, v in blk["xs"].items():
            out[p + "xs_" + k] = v

    @staticmethod
    def _unpack_pull(data, i: int):
        p = f"pull{i}_"
        if p + "sentinel" in data:
            return None
        pulled = {
            k[len(p + "pulled_"):]: data[k]
            for k in data.files if k.startswith(p + "pulled_")
        }
        pull = {
            "ids_in": data[p + "ids_in"], "ids_out": data[p + "ids_out"],
            "n_in": int(data[p + "n_in"]), "n_out": int(data[p + "n_out"]),
            "pulled": pulled, "blk": None,
        }
        if p + "dry" not in data:
            pull["blk"] = {
                "nbatches": int(data[p + "nbatches"]),
                "uin": data[p + "uin"], "uout": data[p + "uout"],
                "xs": {
                    k[len(p + "xs_"):]: data[k]
                    for k in data.files if k.startswith(p + "xs_")
                },
            }
        return pull

    def _ps_rank_state_arrays(self, pulls) -> Dict[str, np.ndarray]:
        """This rank's private resume state: the d in-flight pull
        buffers, the sparse-pull client caches + staleness bitmaps, and
        the 1-bit codecs' error-feedback residuals."""
        out: Dict[str, np.ndarray] = {}
        for i, pull in enumerate(pulls):
            self._pack_pull(out, i, pull)
        if self._ps_sparse_tables:
            for name, cache in self._ps_cache.items():
                out["cache_" + name] = cache
            for name, table, _side in self._ps_entries():
                out["bitmap_" + name] = table._up_to_date
        for name, codec in self._ps_codecs.items():
            if getattr(codec, "_residual", None) is not None:
                out["residual_" + name] = np.asarray(codec._residual)
        return out

    def _ps_restore_rank_state(self, data, depth: int):
        """Inverse of ``_ps_rank_state_arrays``; returns the staged pull
        payloads (len == depth)."""
        if self._ps_sparse_tables:
            for name in list(self._ps_cache):
                self._ps_cache[name][...] = data["cache_" + name]
            for name, table, _side in self._ps_entries():
                table._up_to_date[...] = data["bitmap_" + name]
        for name, codec in self._ps_codecs.items():
            key = "residual_" + name
            if key in data.files:
                codec._residual = jnp.array(data[key])
        return [self._unpack_pull(data, i) for i in range(depth)]

    def _ps_save_checkpoint(
        self, round_idx: int, pairs_done: int, *, depth: int,
        pulls=(), gp_history: Optional[Dict[int, int]] = None,
        epoch: int = 0, batches_in_epoch: int = 0,
        extra_rank_meta: Optional[Dict] = None,
    ) -> None:
        """Quorum-committed PS checkpoint at a drained round boundary.
        Every rank calls this at the SAME round (rounds are lockstep);
        tables save collectively, each rank stages its private state as
        ``rank<p>/state.npz`` through the two-phase protocol."""
        from multiverso_tpu.io.checkpoint import save_tables
        from multiverso_tpu.resilience.checkpoint import gc_checkpoints

        o = self.opt
        gp_history = gp_history or {}
        pid = jax.process_index()

        def rank_payload(tmp: str) -> None:
            rdir = os.path.join(tmp, f"rank{pid}")
            os.makedirs(rdir, exist_ok=True)
            np.savez(os.path.join(rdir, "state.npz"),
                     **self._ps_rank_state_arrays(pulls))

        meta = {
            "kind": "ps", "round": int(round_idx), "depth": int(depth),
            "compress": o.ps_compress,
            "sparse_pull": bool(self._ps_sparse_tables),
            "adagrad": bool(o.use_adagrad),
            "tier_hbm_mb": float(o.table_tier_hbm_mb),
            "gp_history": {str(k): int(v) for k, v in gp_history.items()},
        }
        with self._ps_state_lock:
            meta["gp_last"] = int(self._ps_global_pairs)
            wc_cum = int(self._wc_cum)
        rank_meta = {
            "pairs_done": int(pairs_done), "wc_cum": wc_cum,
            "epoch": int(epoch), "batches_in_epoch": int(batches_in_epoch),
            "restarts": int(self._ps_restarts),
        }
        if extra_rank_meta:
            # depth=auto bookkeeping (controller state, staged lr-source
            # map) — per-rank, JSON-safe, ignored by older readers
            rank_meta.update(extra_rank_meta)
        path = os.path.join(o.checkpoint_dir, f"ckpt-{int(round_idx)}")
        save_tables(path, self._ps_tables(), step=round_idx, meta=meta,
                    rank_payload=rank_payload, rank_meta=rank_meta)
        if pid == 0:
            gc_checkpoints(o.checkpoint_dir, o.checkpoint_retain)

    def _ps_maybe_resume(self, depth: int, auto: bool = False):
        """Restore the latest valid PS checkpoint (tables + this rank's
        private state); returns the resume record or None. Collective:
        every rank must call this together.

        ``auto`` (-ps_pipeline_depth=auto): the staged pull window's
        length is whatever the controller had widened to at save time —
        accept the checkpoint's own ``depth`` as the window length
        instead of requiring it to match, and surface the per-rank meta
        so the caller can restore the controller state."""
        from multiverso_tpu.io.checkpoint import restore_tables
        from multiverso_tpu.resilience import latest_valid
        from multiverso_tpu.resilience import stats as _rstats
        from multiverso_tpu.resilience.checkpoint import require_valid

        o = self.opt
        self._ps_restarts = 0
        if not (o.checkpoint_dir and o.resume):
            return None
        path = latest_valid(o.checkpoint_dir)
        if path is None:
            return None
        manifest = require_valid(path)
        meta = manifest.get("meta") or {}
        CHECK(meta.get("kind") == "ps",
              f"checkpoint {path} is not a PS-mode checkpoint "
              "(the fused host-batch and PS paths do not share roots)")
        # world-size-changing resume (elastic): a checkpoint written by N
        # ranks restoring onto N' != N goes down the re-shard path — the
        # staged per-rank pipeline window is meaningless at N', so the
        # depth CHECK below only guards the bit-exact same-world path
        ckpt_world = len(meta.get("ranks") or {})
        elastic = ckpt_world > 0 and ckpt_world != jax.process_count()
        CHECK(elastic or auto or int(meta.get("depth", -1)) == depth,
              f"checkpoint {path} was written at -ps_pipeline_depth="
              f"{meta.get('depth')} but this run uses {depth}: the staged "
              "in-flight pull window would not line up — resume with the "
              "same depth (or -ps_pipeline_depth=auto, which adopts the "
              "checkpoint's window)")
        # the staged rank state (pull payloads, client caches, codec
        # residuals) and the table set are flag-shaped: a silent mismatch
        # would either KeyError on the npz or break the bit-exact resume
        # contract — fail loudly like the fused path's params CHECK
        # tier budgets may differ across resume (the cache refaults on
        # demand), but tiered vs resident may not: a tiered checkpoint
        # stores the logical host-tier table, a resident one the padded
        # device storage
        CHECK((float(meta.get("tier_hbm_mb", 0) or 0) > 0) == self._tier,
              f"checkpoint {path} was written with -table_tier_hbm_mb="
              f"{meta.get('tier_hbm_mb', 0)} but this run uses "
              f"{o.table_tier_hbm_mb}: tiered and resident checkpoints "
              "store different table layouts — resume in the same mode")
        # -use_adagrad shapes the TABLE SET (g2 tables exist or not), so
        # it must match on every path; -ps_compress/-ps_sparse_pull only
        # shape the staged per-rank state (codec residuals, client
        # caches), which the elastic path drops — they may change freely
        # across a world-size change
        flags = [("adagrad", bool(o.use_adagrad))]
        if not elastic:
            flags += [
                ("compress", o.ps_compress),
                ("sparse_pull", bool(self._ps_sparse_tables)),
            ]
        for flag, current in flags:
            CHECK(meta.get(flag) == current,
                  f"checkpoint {path} was written with {flag}="
                  f"{meta.get(flag)} but this run uses {current}: "
                  "-ps_compress/-ps_sparse_pull/-use_adagrad must match "
                  "the saved run to resume")
        if elastic:
            return self._ps_elastic_resume(path, meta)
        restore_tables(path, self._ps_tables())
        pid = jax.process_index()
        rmeta = (meta.get("ranks") or {}).get(str(pid))
        CHECK(rmeta is not None,
              f"checkpoint {path} has no rank {pid} state: it was written "
              "by a different world size — relaunch with the original "
              "process count")
        # auto adopts the saved window length (the controller may have
        # widened past this run's initial depth before the save)
        window = int(meta.get("depth", depth)) if auto else depth
        pulls = []
        if window > 0:
            with np.load(os.path.join(path, f"rank{pid}", "state.npz"),
                         allow_pickle=False) as data:
                pulls = self._ps_restore_rank_state(data, window)
        with self._ps_state_lock:
            self._wc_cum = int(rmeta["wc_cum"])
            self._ps_global_pairs = int(meta.get("gp_last", 0))
        self._ps_restarts = int(rmeta.get("restarts", 0)) + 1
        _rstats.note_restart(self._ps_restarts)
        Log.Info(
            "[WordEmbedding] resumed from %s: PS round %d, %.1fM pairs, "
            "restart #%d",
            path, int(meta["round"]), rmeta["pairs_done"] / 1e6,
            self._ps_restarts,
        )
        return {
            "round": int(meta["round"]),
            "pairs_done": int(rmeta["pairs_done"]),
            "epoch": int(rmeta.get("epoch", 0)),
            "batches_in_epoch": int(rmeta.get("batches_in_epoch", 0)),
            "gp_history": {
                int(k): int(v)
                for k, v in (meta.get("gp_history") or {}).items()
            },
            "pulls": pulls,
            "rank_meta": rmeta,
        }

    def _ps_elastic_resume(self, path: str, meta: Dict):
        """World-size-changing restore: an N-rank quorum checkpoint onto
        N' != N ranks (ISSUE 7 tentpole).

        * tables re-shard host-side (``restore_tables(reshard=True)`` —
          logical values identical, new mesh layout);
        * the word-count limbs merge: the global trained-pair count is the
          sum of every old rank's exact cumulative count, re-partitioned
          into balanced per-client shares on the new world (the global sum
          — the only number the lr schedule reads — is preserved exactly);
        * the per-rank data cursors merge the same way: the new world
          skips the globally-consumed batches/blocks split evenly, so
          training continues from the committed round boundary;
        * the staged in-flight pipeline window (depth >= 1 checkpoints) is
          per-rank state and is DROPPED — the pipeline restarts with an
          empty warm-up at N', seeding the lr history with the restored
          global count. Bit-exactness is therefore not a contract here;
          convergence-equivalence is (pinned in tests/test_elastic.py).
        """
        from multiverso_tpu.io.checkpoint import restore_tables
        from multiverso_tpu.resilience import stats as _rstats

        o = self.opt
        ranks_meta = meta.get("ranks") or {}
        n_old = len(ranks_meta)
        n_new = jax.process_count()
        pid = jax.process_index()
        depth = o.ps_pipeline_depth
        # every table except the word-count table re-shards by value; the
        # wc table's row count is 2*nproc (topology-shaped), so its limbs
        # merge below instead
        restore_tables(path, self._ps_tables()[:-1], reshard=True)
        mask = (1 << 30) - 1
        total = sum(int(rm.get("wc_cum", 0)) for rm in ranks_meta.values())
        shares = [
            total * (q + 1) // n_new - total * q // n_new
            for q in range(n_new)
        ]
        limbs = np.zeros((2 * n_new, 1), np.int32)
        for q, s in enumerate(shares):
            limbs[2 * q, 0] = s & mask
            limbs[2 * q + 1, 0] = s >> 30
        self._t_wc.load_logical(limbs)
        with self._ps_state_lock:
            self._wc_cum = int(shares[pid])
            self._ps_global_pairs = total
        # data cursors: merge, then split evenly over the new world. The
        # block stream is per-rank, so "skip what the old world consumed"
        # becomes "each new rank skips its even share of the globally
        # consumed data" (exact when shards are even; convergence-level
        # otherwise — the committed tables already hold every consumed
        # pair's update either way)
        S = max(1, o.steps_per_call)
        skip_blocks = total // max(1, n_new * o.batch_size * S)
        epoch0 = min(
            (int(rm.get("epoch", 0)) for rm in ranks_meta.values()),
            default=0,
        )
        batches_total = sum(
            int(rm.get("batches_in_epoch", 0)) for rm in ranks_meta.values()
        )
        r = int(meta["round"])
        gp_hist = (
            {k: total for k in range(r - depth - 1, r)} if depth > 0 else {}
        )
        self._ps_restarts = max(
            (int(rm.get("restarts", 0)) for rm in ranks_meta.values()),
            default=0,
        ) + 1
        _rstats.note_restart(self._ps_restarts)
        Log.Info(
            "[WordEmbedding] resumed (elastic N=%d -> N'=%d) from %s: PS "
            "round %d, %.1fM global pairs, restart #%d — tables re-sharded"
            " (writer: %s device(s)), pipeline warm-up reset, cursors "
            "re-partitioned",
            n_old, n_new, path, r, total / 1e6, self._ps_restarts,
            (meta.get("world") or {}).get("devices", "?"),
        )
        return {
            "round": r,
            "pairs_done": int(shares[pid]),
            "epoch": epoch0,
            "batches_in_epoch": batches_total // max(1, n_new),
            "gp_history": gp_hist,
            "pulls": [],
            "elastic": True,
            "skip_blocks": int(skip_blocks),
        }

    def _ps_await(self, ticket, round_idx: int, pipe, wd):
        """Failure-domain-aware ticket wait: bounded by the collective
        deadline + watchdog; transport-looking comms-thread errors are
        promoted to structured RankFailure (and poison the pipe) while
        logic errors propagate unchanged."""
        from multiverso_tpu.resilience import watchdog as wdg

        try:
            return ticket.wait_result(
                self._ps_deadline_s, wd, round_idx=round_idx
            )
        except (wdg.RankFailure, wdg.PipelineBroken):
            raise
        except BaseException as e:
            rf = wdg.classify_collective_error(e, round_idx=round_idx)
            if rf is None:
                raise
            wdg.fd_stats.note_rank_failure(rf.kind)
            pipe.break_pipe(rf)
            raise rf from e

    def _ps_contain_failure(self, pipe, failure, round_idx: int, wd) -> None:
        """Poisoned-pipe containment: mark the pipe broken, drain what
        can still land so surviving state stops at a well-defined round
        boundary, and publish a failure report next to the checkpoints
        (recovery truth stays the last quorum-committed drained
        checkpoint — a lone survivor cannot write a complete table
        snapshot, its peers' shards died with them)."""
        import json

        from multiverso_tpu.resilience import latest_valid

        o = self.opt
        pipe.break_pipe(failure)
        drained = pipe.drain(timeout_s=max(5.0, self._ps_deadline_s or 0.0))
        with self._ps_state_lock:
            committed = self._ps_rounds_pushed
            clean = committed == self._ps_push_entered
        last_ckpt = (
            latest_valid(o.checkpoint_dir) if o.checkpoint_dir else None
        )
        report = {
            "failure": str(failure),
            "kind": getattr(failure, "kind", "unknown"),
            "suspected_rank": getattr(failure, "rank", -1),
            "detected_at_round": int(round_idx),
            "committed_round_boundary": int(committed),
            "boundary_clean": bool(clean),
            "drained": bool(drained),
            "heartbeat_ages_s": (
                {str(k): v for k, v in wd.ages().items()}
                if wd is not None else {}
            ),
            "resume_from": last_ckpt,
        }
        Log.Error(
            "[WordEmbedding] PS rank failure CONTAINED at round %d: %s — "
            "pushes committed through round boundary %d (clean=%s, "
            "drained=%s); resume from %s",
            round_idx, failure, committed, clean, drained,
            last_ckpt or "<no checkpoint>",
        )
        obs.recorder.record(
            "containment", round=int(round_idx),
            failure_kind=getattr(failure, "kind", "unknown"),
            drained=bool(drained), committed_boundary=int(committed),
        )
        if o.checkpoint_dir:
            os.makedirs(o.checkpoint_dir, exist_ok=True)
            path = os.path.join(
                o.checkpoint_dir, f"FAILURE-round{int(round_idx)}.json"
            )
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, path)
            # the flight recorder's last-N-events timeline lands next to
            # the FAILURE report — the ready-made post-mortem the
            # supervisor collects into its recovery log dir
            obs.recorder.dump_for_rank(o.checkpoint_dir)
        # the span trace survives the failure too: dump what the rings
        # hold so the pod-wide merge shows where every thread was
        obs.tracer.maybe_dump_from_flags()
        # armed race-detector runs dump next to it — a race report that
        # coincides with a contained failure is usually the cause
        _mvtsan.maybe_dump_from_flags()

    def _train_ps_pipelined(self, source, total_pairs_est: float,
                            start: float) -> float:
        """Pipelined PS training loop (see the block comment above for
        the staleness contract). Blocks stream across epoch boundaries
        without a per-epoch drain barrier — rounds are just blocks to the
        table protocol, and the lr schedule is driven by the global
        word-count table either way."""
        from collections import deque

        from multiverso_tpu.utils.async_buffer import ASyncBuffer, TaskPipe
        from multiverso_tpu.utils.quantization import DeltaCodec

        o = self.opt
        depth = o.ps_pipeline_depth
        S = max(1, o.steps_per_call)
        V, D = self.cfg.vocab_size, o.size
        out_rows = self._out_rows
        self._ps_stats = _PSCommsStats(D)

        def _codec(name: str, rows: int) -> DeltaCodec:
            mode = o.ps_compress
            if name.startswith("g2") and mode == "1bit":
                # g2 deltas are nonnegative accumulator increments — sign
                # quantization would corrupt them; they ride the lossless
                # sparse filter instead
                mode = "sparse"
            if mode == "1bit":
                return DeltaCodec("1bit", num_row=rows, dim=D)
            return DeltaCodec(mode)

        self._ps_codecs = {
            "in": _codec("in", V), "out": _codec("out", out_rows),
        }
        if o.use_adagrad:
            self._ps_codecs["g2_in"] = _codec("g2_in", V)
            self._ps_codecs["g2_out"] = _codec("g2_out", out_rows)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            total_global = float(
                multihost_utils.process_allgather(
                    np.asarray([total_pairs_est], np.float64)
                ).sum()
            )
        else:
            total_global = float(total_pairs_est)

        def gen_blocks():
            for epoch in range(o.epoch):
                it = source.batches(epoch)
                done = False
                while not done:
                    group = []
                    while len(group) < S:
                        b = next(it, None)
                        if b is None:
                            done = True
                            break
                        group.append(b)
                    if group:
                        yield group
            while True:  # local corpus done: keep joining rounds dry
                yield None

        from multiverso_tpu.resilience import chaos
        from multiverso_tpu.resilience import watchdog as wdg

        self._ps_deadline_s = wdg.collective_timeout_s()
        ckpt_every = (
            o.checkpoint_every_steps if o.checkpoint_dir else 0
        )
        # -ps_pipeline_depth=auto: the staleness-adaptive controller.
        # ``depth`` becomes mutable — widened/narrowed only at pod-agreed
        # decision rounds (``_ps_depth_decide`` min-agreement on the
        # comms pipe), so every rank's pull-issue and collective
        # sequences stay identical. The fixed-depth path below is
        # untouched: ``auto`` gates every behavioral change.
        from multiverso_tpu.obs import slo as _slo
        from multiverso_tpu.obs.controller import DepthController

        auto = bool(o.ps_depth_auto)
        ctl = None
        lr_src_for: Dict[int, int] = {}  # round -> newest pre-pull push
        gp_carry = 0  # last awaited global pair count (lr input)
        decide_every = max(1, o.ps_depth_decide_rounds)
        self._ps_depth_decisions: list = []
        if auto:
            ctl = DepthController(
                min_depth=1, max_depth=max(1, o.ps_pipeline_depth_max),
            )
            ctl.depth = max(1, min(ctl.max_depth, depth))
            depth = ctl.depth
        # elastic resume (collective): restore tables + wc state + this
        # rank's staged in-flight pulls, then advance the block stream to
        # the drained boundary — the resumed loop replays the exact
        # pipeline warm-up the checkpoint left in flight, so kill +
        # restart == uninterrupted bit for bit at any depth
        resume = self._ps_maybe_resume(depth, auto=auto)
        gen = gen_blocks()
        r = 0
        issued = 0
        pairs_done = 0
        pull_tickets: deque = deque()
        push_tickets: Dict[int, object] = {}
        resume_round = -1
        if resume is not None:
            r = resume_round = resume["round"]
            pairs_done = resume["pairs_done"]
            if resume.get("elastic"):
                # world-size-changing resume: the staged pull window was
                # per-rank state of the OLD world — restart the pipeline
                # with an empty warm-up at N' and skip this rank's even
                # share of the globally consumed blocks (auto: the
                # controller restarts fresh at the initial depth too)
                issued = r
                skip = resume["skip_blocks"]
            else:
                # auto adopts the saved window length — the controller
                # may have widened past this run's initial depth
                issued = r + (len(resume["pulls"]) if auto else depth)
                skip = issued
                for pull in resume["pulls"]:  # rounds r..issued-1, in order
                    pull_tickets.append(self._Resolved(pull))
                if auto:
                    rm = resume.get("rank_meta") or {}
                    ctl.load_state_dict(rm.get("depth_controller"))
                    depth = ctl.depth
                    lr_src_for = {
                        int(k): int(v)
                        for k, v in (rm.get("lr_src_for") or {}).items()
                    }
                    gp_carry = int(rm.get("gp_lr_carry", 0))
            for k, gp in resume["gp_history"].items():
                push_tickets[k] = self._Resolved(gp)
            # regenerate-and-discard the consumed blocks: same seed, same
            # grouping, so the next undiscarded block starts the resumed
            # stream (bit-identical when the world size is unchanged)
            for _ in range(skip):
                next(gen)
        self._set_ready(True, "training")  # tables live + resume landed
        wd = wdg.monitor_from_flags()
        # straggler detection (multi-process pipelined rounds): per-rank
        # train+push timers piggyback on the round-meta allgather and a
        # drifting rank raises a `straggler` flight event well before a
        # heartbeat deadline would — the rank is slow, not dead
        self._ps_straggler = (
            _slo.StragglerDetector() if jax.process_count() > 1 else None
        )
        pipe = TaskPipe(name="mv-ps-comms")
        # tiered look-ahead tickets ride the COMMS pipe: every collective
        # dispatch stays on that one thread (concurrent multi-device
        # collective programs from different threads can invert
        # per-device launch order and deadlock XLA's rendezvous) — set
        # BEFORE the prep buffer so its fill thread never races the bind
        self._tier_prefetch_pipe = pipe
        # one-block-ahead prep prefetch (unions/remap/presort are host
        # CPU heavy) — the reference ASyncBuffer reused as designed
        buf = ASyncBuffer(
            lambda: self._ps_block_prep(next(gen)), name="ps.block_prep"
        )
        loss_dev = None
        log_every = o.batch_size * max(64, S * 8)
        loop_t0 = time.perf_counter()
        # decision-window baselines (auto): overlap% is measured per
        # window by diffing the cumulative stage clocks against the
        # training thread's wall — the run-wide overlap_pct() only
        # becomes meaningful after set_wall at the end
        decide_snap = (0.0, 0.0, 0.0)
        decide_rounds0 = 0
        decide_t0 = loop_t0
        try:
            while True:
                chaos.maybe_drop_rank(r)  # failure-domain drills
                if (
                    ckpt_every and r > 0 and r % ckpt_every == 0
                    and r != resume_round
                ):
                    # planned drained checkpoint: land every in-flight
                    # push (consistent boundary: tables hold exactly
                    # rounds < r), then quorum-save tables + the staged
                    # pull window rounds r..r+depth-1. The drain is
                    # bounded by the collective deadline when armed — a
                    # peer dying mid-drain raises instead of hanging.
                    if not pipe.drain(timeout_s=self._ps_deadline_s):
                        raise wdg.RankFailure(
                            "collective_timeout",
                            "pre-checkpoint drain timed out",
                            round_idx=r,
                        )
                    if wd is not None:
                        wd.check()
                    # ticket reads go through the classified await: a
                    # transport error parked on a drained ticket must hit
                    # the containment handler, not escape raw
                    self._ps_save_checkpoint(
                        r, pairs_done,
                        # auto: the staged window length IS the depth a
                        # resume must adopt (a narrow still in flight
                        # can leave window > controller depth)
                        depth=len(pull_tickets) if auto else depth,
                        pulls=[
                            self._ps_await(t, r, pipe, wd)
                            for t in pull_tickets
                        ],
                        gp_history={
                            k: self._ps_await(t, r, pipe, wd)
                            for k, t in push_tickets.items()
                        },
                        extra_rank_meta={
                            "depth_controller": ctl.state_dict(),
                            "lr_src_for": {
                                str(k): int(v)
                                for k, v in lr_src_for.items()
                            },
                            "gp_lr_carry": int(gp_carry),
                        } if auto else None,
                    )
                if (
                    auto and r > 0 and r % decide_every == 0
                    and r != resume_round
                ):
                    self._ps_depth_decision(
                        r, ctl, pipe, wd,
                        decide_snap, decide_rounds0, decide_t0,
                        loss_dev,
                    )
                    depth = ctl.depth
                    ps_s, tr_s, pu_s, rnds = self._ps_stats.stage_seconds()
                    decide_snap = (ps_s, tr_s, pu_s)
                    decide_rounds0 = rnds
                    decide_t0 = time.perf_counter()
                # keep pulls for rounds r..r+depth in flight: pull k+d is
                # submitted BEFORE push k..k+d-1, which is the whole
                # overlap (and the whole staleness)
                while issued <= r + depth:
                    blk = buf.Get()
                    if auto:
                        # newest push ordered before this pull — the lr
                        # source a fixed depth derives as r - depth - 1;
                        # recorded at issue time so depth changes never
                        # skew the schedule
                        lr_src_for[issued] = r - 1
                    pull_tickets.append(
                        pipe.submit(
                            lambda b=blk, rr=issued: self._ps_pull_round(
                                b, rr
                            ),
                            tag=f"pull:{issued}",
                        )
                    )
                    issued += 1
                pull = self._ps_await(pull_tickets.popleft(), r, pipe, wd)
                if pull is None:
                    break
                # deterministic lr: the newest wc round whose completion
                # is ORDERED before this round's pull on the comms thread
                if auto:
                    src = lr_src_for.pop(r, r - depth - 1)
                    # a widen can leave a round with no newly-eligible
                    # push (its predecessor consumed the same source):
                    # the carry keeps the schedule monotone
                    for k in [kk for kk in sorted(push_tickets)
                              if kk <= src]:
                        gp_carry = self._ps_await(
                            push_tickets.pop(k), r, pipe, wd
                        )
                    gp = gp_carry
                elif (r - depth - 1) in push_tickets:
                    # absent only in the warm-up
                    gp = self._ps_await(
                        push_tickets.pop(r - depth - 1), r, pipe, wd
                    )
                else:
                    gp = 0
                lr = self._lr(gp / total_global)
                with obs.span("ps.round.train", round=r):
                    payloads, inc, loss = self._ps_train_block(pull, lr)
                push_tickets[r] = pipe.submit(
                    lambda pl=payloads, p=pull, i=inc, rr=r: (
                        self._ps_push_round(
                            pl, p["ids_in"], p["ids_out"], p["n_in"],
                            p["n_out"], i, rr,
                        )
                    ),
                    tag=f"push:{r}",
                )
                self._ps_lr_trace.append(lr)
                # flight recorder: round boundary (the post-mortem's spine)
                obs.recorder.record("round", round=r, lr=round(lr, 6))
                if loss is not None:
                    loss_dev = loss
                prev = pairs_done
                pairs_done += inc
                if pairs_done // log_every > prev // log_every:
                    rate = pairs_done / max(time.perf_counter() - start, 1e-9)
                    Log.Info(
                        "[WordEmbedding] PS pipelined (d=%d): %.1fM pairs, "
                        "%.0fk pairs/s, lr %.5f, loss %.4f",
                        depth, pairs_done / 1e6, rate / 1e3, lr,
                        float(loss_dev) if loss_dev is not None else 0.0,
                    )
                r += 1
        except (wdg.RankFailure, wdg.PipelineBroken) as failure:
            # a hung/dead peer: contain instead of hanging — poison the
            # pipe, drain what can still land, publish the failure report
            self._ps_contain_failure(pipe, failure, r, wd)
            raise
        finally:
            # drain: the already-submitted trailing pulls run their meta
            # allgathers (every rank submitted the same count), queued
            # pushes complete — collectives stay lockstep even on errors.
            # On a broken pipe the join is best-effort: the worker may be
            # stuck inside a hung collective.
            if wd is not None:
                wd.stop()
            pipe.close(timeout_s=5.0 if pipe.broken is not None else 60.0)
            buf.Stop()
            self._tier_prefetch_pipe = None  # closed: prep must not use it
            self._ps_straggler = None  # meta allgather back to 3-wide
            for table, _side in self._tier_prefetch_tables:
                table.close()  # tear down any table-owned prefetch pipes
        # surface any comms-thread error parked on a drained push ticket
        for rr in sorted(push_tickets):
            push_tickets[rr].result()
        self._ps_stats.set_wall(time.perf_counter() - loop_t0)
        # bench/test surface: where the controller landed (fixed runs
        # report their static depth; decisions list stays empty)
        self._ps_depth_final = depth
        if self._tier:
            # live host-tier arrays, no copy: a tier-scale table must
            # not round-trip HBM or double host RAM just to be written
            # out (training is over — nothing mutates them anymore)
            self.params["emb_in"] = self._t_in.host_array()
            self.params["emb_out"] = self._t_out.host_array()
        else:
            self.params["emb_in"] = jnp.asarray(self._t_in.get())
            self.params["emb_out"] = jnp.asarray(self._t_out.get())
        self.words_trained = pairs_done
        if o.output_file:
            self.save_embeddings(o.output_file, binary=o.binary)
        return float(loss_dev) if loss_dev is not None else 0.0

    def _run_superbatch_ps(self, batches: list, lr: float):
        """One PS block round (ref: the Communicator protocol —
        communicator.cpp:117-155 RequestParameter pulls the block's vocab
        subset, :157-249 AddDeltaParameter re-reads and pushes
        (new - old)/num_workers): pull touched rows into a compact local
        model, run the block's microbatches locally (sorted-scatter
        superstep over remapped ids), push the averaged delta.

        Multi-process: each rank's union pads to a cross-rank-agreed
        bucket (``_ps_round_meta``); the pull/push are the stacked SPMD
        programs ``get_rows_local``/``add_rows_local``. A rank whose
        corpus shard ran dry joins with an empty block (zero deltas) until
        every rank is done — rounds stay lockstep. Returns
        ``(any_rank_had_data, loss_or_None)``."""
        from multiverso_tpu.models.wordembedding.skipgram import (
            SkipGramConfig,
            make_sorted_superbatch_step,
            presort_batch,
        )

        o = self.opt
        # block node sets (ref: data_block SetWeightIE input/output nodes)
        if batches:
            uin = np.unique(np.concatenate([b["centers"] for b in batches]))
            okey = "points" if o.hs else "outputs"
            uout = np.unique(
                np.concatenate([b[okey].reshape(-1) for b in batches])
            )
            if o.cbow:
                ctx = np.concatenate(
                    [b["contexts"].reshape(-1) for b in batches]
                )
                uin = np.unique(np.concatenate([uin, np.maximum(ctx, 0)]))
        else:
            uin = np.zeros(0, np.int64)
            uout = np.zeros(0, np.int64)
        any_data, ni, no = self._ps_round_meta(len(batches), len(uin), len(uout))
        if not any_data:
            return False, None
        # RequestParameter: pull the padded bucket (pad id 0; padding rows
        # zeroed below so the local model matches the pre-bucket semantics)
        ids_in = np.zeros(ni, np.int64)
        ids_in[: len(uin)] = uin
        ids_out = np.zeros(no, np.int64)
        ids_out[: len(uout)] = uout
        # obs: the sync rounds run all three legs on the training thread —
        # the same span names as the pipelined path, so traces compare
        with obs.span("ps.round.pull"):
            Win = np.asarray(
                self._t_in.get_rows_local(ids_in), np.float32
            ).copy()
            Win[len(uin):] = 0.0
            Wout = np.asarray(
                self._t_out.get_rows_local(ids_out), np.float32
            ).copy()
            Wout[len(uout):] = 0.0
            if o.use_adagrad:
                G2in = np.asarray(
                    self._t_g2_in.get_rows_local(ids_in), np.float32
                ).copy()
                G2in[len(uin):] = 0.0
                G2out = np.asarray(
                    self._t_g2_out.get_rows_local(ids_out), np.float32
                ).copy()
                G2out[len(uout):] = 0.0
        if not batches:
            # dry rank: participate in the pull/push collectives only
            zin = np.zeros((ni, o.size), np.float32)
            zout = np.zeros((no, o.size), np.float32)
            with obs.span("ps.round.push"):
                self._t_in.add_rows_local(ids_in, zin)
                self._t_out.add_rows_local(ids_out, zout)
                if o.use_adagrad:
                    self._t_g2_in.add_rows_local(ids_in, zin)
                    self._t_g2_out.add_rows_local(ids_out, zout)
            return True, None
        params = {"emb_in": jnp.asarray(Win), "emb_out": jnp.asarray(Wout)}
        if o.use_adagrad:
            params["g2_in"] = jnp.asarray(G2in)
            params["g2_out"] = jnp.asarray(G2out)
        # remap ids into the compact local vocab + rebuild sort metadata
        remapped = []
        for b in batches:
            rb = {"centers": np.searchsorted(uin, b["centers"]).astype(np.int32)}
            if o.hs:
                rb["points"] = np.searchsorted(uout, b["points"]).astype(np.int32)
                rb["codes"], rb["lengths"] = b["codes"], b["lengths"]
            else:
                rb["outputs"] = np.searchsorted(uout, b["outputs"]).astype(np.int32)
            if o.cbow:
                cx = b["contexts"]
                rb["contexts"] = np.where(
                    cx >= 0, np.searchsorted(uin, np.maximum(cx, 0)), -1
                ).astype(np.int32)
            remapped.append(
                presort_batch(rb, hs=o.hs, cbow=o.cbow, scale_mode=o.scale_mode)
            )
        key = (ni, no, len(batches))
        step = self._ps_steps.get(key)
        if step is None:
            cfg = SkipGramConfig(
                vocab_size=ni, dim=o.size, negatives=o.negative,
                cbow=o.cbow, window=o.window,
            )
            step = jax.jit(
                make_sorted_superbatch_step(
                    cfg, hs=o.hs, use_adagrad=o.use_adagrad
                ),
                donate_argnums=(0,),
            )
            self._ps_steps[key] = step
        xs = {
            k: jnp.asarray(np.stack([b[k] for b in remapped]))
            for k in remapped[0]
            if remapped[0][k] is not None
        }
        with obs.span("ps.round.train"):
            new_params, loss = step(params, xs, jnp.float32(lr))
            # AddDeltaParameter deltas: (new - old) / num_workers
            # (full padded bucket; padding rows start 0 and train
            # nothing, so their delta is exactly 0)
            din = np.asarray(new_params["emb_in"]) - Win
            din[len(uin):] = 0.0
            dout = np.asarray(new_params["emb_out"]) - Wout
            dout[len(uout):] = 0.0
            if o.use_adagrad:
                dg_in = np.asarray(new_params["g2_in"]) - G2in
                dg_in[len(uin):] = 0.0
                dg_out = np.asarray(new_params["g2_out"]) - G2out
                dg_out[len(uout):] = 0.0
        with obs.span("ps.round.push"):
            self._t_in.add_rows_local(ids_in, din / self._num_workers)
            self._t_out.add_rows_local(ids_out, dout / self._num_workers)
            if o.use_adagrad:
                self._t_g2_in.add_rows_local(
                    ids_in, dg_in / self._num_workers
                )
                self._t_g2_out.add_rows_local(
                    ids_out, dg_out / self._num_workers
                )
        return True, loss

    def _train_ps(self, source, total_pairs_est: float, start: float) -> float:
        """PS-mode training loop: block = steps_per_call microbatches.
        ``-ps_pipeline_depth=0`` (default) runs the fully synchronous
        rounds below — bit-exact with prior releases; depth >= 1 branches
        to the software pipeline (``_train_ps_pipelined``)."""
        from multiverso_tpu.resilience import chaos

        o = self.opt
        self._ps_setup()
        self._ps_steps: Dict = {}
        self._ps_lr_trace: list = []  # per-round lr (tests assert ranks agree)
        if o.ps_pipeline_depth >= 1 or o.ps_depth_auto:
            return self._train_ps_pipelined(source, total_pairs_est, start)
        S = max(1, o.steps_per_call)
        loss_dev = None
        pairs_done = 0
        # the lr decays on the GLOBAL trained-pair count from the shared
        # word-count table, so every rank's schedule is identical (ref:
        # distributed_wordembedding.cpp:92-127; round-2 gap item 4)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            total_global = float(
                multihost_utils.process_allgather(
                    np.asarray([total_pairs_est], np.float64)
                ).sum()
            )
        else:
            total_global = float(total_pairs_est)
        log_every = o.batch_size * max(64, S * 8)
        # elastic resume (collective): restore tables + the per-rank data
        # cursor from the latest valid PS checkpoint; batches regenerate
        # deterministically past it, so kill + restart == uninterrupted
        ckpt_every = o.checkpoint_every_steps if o.checkpoint_dir else 0
        resume = self._ps_maybe_resume(depth=0)
        rounds_done = 0
        start_epoch = 0
        resume_skip = 0
        if resume is not None:
            rounds_done = resume["round"]
            pairs_done = resume["pairs_done"]
            start_epoch = resume["epoch"]
            resume_skip = resume["batches_in_epoch"]
            if start_epoch > 0:
                # the pair generator's RNG stream spans epochs: drain the
                # completed epochs so the resumed stream is bit-identical
                for ep in range(start_epoch):
                    for _ in source.batches(ep):
                        pass
        self._set_ready(True, "training")  # tables live + resume landed
        for epoch in range(start_epoch, o.epoch):
            skip = resume_skip if epoch == start_epoch else 0
            it = source.batches(epoch, skip=skip) if skip else source.batches(
                epoch
            )
            batches_in_epoch = skip
            done = False
            while True:
                chaos.maybe_drop_rank(rounds_done)  # failure-domain drills
                group = []
                if not done:
                    while len(group) < S:
                        batch = next(it, None)
                        if batch is None:
                            done = True
                            break
                        group.append(batch)
                with self._ps_state_lock:
                    gp = self._ps_global_pairs
                lr = self._lr(gp / total_global)
                # every rank joins the round while ANY rank has data (dry
                # ranks push zero deltas — lockstep SPMD rounds)
                any_data, loss = self._run_superbatch_ps(group, lr)
                if not any_data:
                    break
                self._ps_lr_trace.append(lr)
                gp_new = self._wc_push_and_read(o.batch_size * len(group))
                with self._ps_state_lock:
                    self._ps_global_pairs = gp_new
                if loss is not None:
                    loss_dev = loss
                prev = pairs_done
                pairs_done += o.batch_size * len(group)
                batches_in_epoch += len(group)
                rounds_done += 1
                if ckpt_every and rounds_done % ckpt_every == 0:
                    # synchronous rounds ARE drained boundaries: every
                    # push landed before this line, on every rank
                    self._ps_save_checkpoint(
                        rounds_done, pairs_done, depth=0, epoch=epoch,
                        batches_in_epoch=batches_in_epoch,
                    )
                if pairs_done // log_every > prev // log_every:
                    rate = pairs_done / max(time.perf_counter() - start, 1e-9)
                    Log.Info(
                        "[WordEmbedding] PS epoch %d: %.1fM pairs, %.0fk pairs/s, "
                        "lr %.5f, loss %.4f",
                        epoch, pairs_done / 1e6, rate / 1e3, lr, float(loss_dev),
                    )
        # the trained model lives in the tables; refresh local params for
        # save_embeddings (ref: SaveEmbedding batched row Gets)
        self.params["emb_in"] = jnp.asarray(self._t_in.get())
        self.params["emb_out"] = jnp.asarray(self._t_out.get())
        self.words_trained = pairs_done
        if o.output_file:
            self.save_embeddings(o.output_file, binary=o.binary)
        return float(loss_dev) if loss_dev is not None else 0.0

    def _train_ondevice(self, ids: np.ndarray, keep: Optional[np.ndarray]) -> float:
        """Fully device-resident training (-device_pipeline): the corpus is
        uploaded once per epoch; sampling, negatives, presort and updates run
        inside one jitted program per superbatch — zero per-step host
        traffic. The TPU-native answer to slow host/link data paths (the
        reference's answer was the pipeline thread; here there is nothing to
        overlap).

        Subsampling runs on HOST, per epoch, by dropping tokens from the
        stream before windowing — word2vec's actual semantics (the reference
        removes subsampled words while loading the sentence, so windows span
        the dropped positions; ref: wordembedding.cpp ParseSentence) — and
        it keeps rejected draws from burning device batch slots (the
        round-2 on-device keep gate cost ~1/3 of all slots on a Zipf corpus
        at -sample=1e-3; see benchmarks/E2E_GAP.md). The compacted corpus is
        padded back to the full corpus length and the valid-position index
        to a fixed size, so every epoch reuses ONE compiled program.

        Mode coverage matches the reference's single training path
        (ref: wordembedding.cpp:57-166): the NS+skip-gram+SGD flagship runs
        the hand-tuned sorted-scatter step; CBOW / HS / AdaGrad route
        through the generic device-resident step (same on-device sampling,
        make_train_step math — slower, correctness-first)."""
        from multiverso_tpu.models.wordembedding.skipgram import (
            build_negative_lut,
            make_ondevice_general_superbatch_step,
            make_ondevice_prepare_fn,
            make_ondevice_statics,
            make_ondevice_superbatch_step,
        )

        o = self.opt
        S = max(1, o.steps_per_call)
        # Model parallelism: the tables were born row-sharded in __init__
        # (-num_shards=N + -device_pipeline); here the training step keeps
        # them sharded (out_shardings) while data/batch tensors replicate
        # — gathers/scatters lower to XLA collectives over ICI, and the
        # sharded tables are the load-bearing axis.
        rep = self._rep
        jit_kw: Dict = dict(donate_argnums=(0,))
        if self._tab is not None:
            jit_kw["out_shardings"] = (
                {k: self._tab for k in self.params}, (rep, rep),
            )
        if o.hs or o.cbow or o.use_adagrad:
            superstep = jax.jit(
                make_ondevice_general_superbatch_step(
                    self.cfg, batch=o.batch_size, steps=S, hs=o.hs,
                    use_adagrad=o.use_adagrad, scale_mode=o.scale_mode,
                ),
                **jit_kw,
            )
        else:
            superstep = jax.jit(
                make_ondevice_superbatch_step(
                    self.cfg, batch=o.batch_size, steps=S,
                    scale_mode=o.scale_mode,
                ),
                **jit_kw,
            )
        flagship = not (o.hs or o.cbow or o.use_adagrad)
        neg_lut = None if o.hs else build_negative_lut(self.sampler.probs)
        start = time.perf_counter()
        t_phase = start

        def _up(x):
            """Async upload (jnp.asarray returns before the transfer
            completes); replicated over the mesh when sharding."""
            a = jnp.asarray(x)
            return jax.device_put(a, rep) if rep is not None else a

        # Chunked double-buffered corpus feed: on weak host->device links
        # (~12 MB/s measured on the tunneled bench host — E2E_GAP.md) a
        # monolithic upload serializes in front of training. Splitting the
        # stream into fixed-size chunks lets chunk i+1's transfer overlap
        # chunk i's training (uploads are async; the next prepare simply
        # waits on its transfer). Each chunk prepares independently —
        # per-chunk subsample redraw and walk permutation; the union of
        # chunk walks still covers every position per epoch.
        CHECK(o.upload_chunk_tokens >= 0,
              "-upload_chunk_tokens must be >= 0 (0 = auto), got %d"
              % o.upload_chunk_tokens)
        chunk_tok = o.upload_chunk_tokens or 16_000_000
        if len(ids) > chunk_tok + chunk_tok // 2:
            nC = -(-len(ids) // chunk_tok)
            L = -(-len(ids) // nC)
            chunks_np = []
            for c in range(nC):
                part = ids[c * L: (c + 1) * L]
                if len(part) < L:  # -1 pads parse as sentence markers
                    part = np.concatenate(
                        [part, np.full(L - len(part), -1, np.int32)]
                    )
                chunks_np.append(np.ascontiguousarray(part))
        else:
            nC = 1
            chunks_np = [ids]
        # first chunk (or the whole corpus) + LUTs/Huffman/keep/p34 uploads
        cur_dev = _up(chunks_np[0])
        statics = make_ondevice_statics(
            self.cfg, neg_lut, batch=o.batch_size, huffman=self.huffman,
        )
        if rep is not None:
            statics = {k: jax.device_put(v, rep) for k, v in statics.items()}
        scale_tables = flagship and o.scale_mode == "row_mean"
        p34_dev = (
            _up(self.sampler.probs.astype(np.float32))
            if scale_tables else None
        )
        keep_dev = _up(keep.astype(np.float32)) if o.sample > 0 else None
        use_walk = o.walk == "perm"
        # flagship sorted step + walk: window-presort the epoch permutation
        # so the step's per-microbatch center argsort disappears (the walk
        # modulus becomes the batch-padded walk_n; the host cursor below
        # mirrors it)
        presort_walk = use_walk and flagship
        prep_kw: Dict = {}
        if rep is not None:
            # every per-epoch dyn leaf (corpus, walk perm, scale tables,
            # the n_valid scalar) replicates across the mesh
            prep_kw["out_shardings"] = rep
        prepare = jax.jit(
            make_ondevice_prepare_fn(
                self.cfg, o.batch_size, subsample=o.sample > 0,
                scale_tables=scale_tables, walk=use_walk,
                presort=presort_walk,
            ),
            **prep_kw,
        )
        prep_key = jax.random.PRNGKey(o.seed ^ 0x5EED5)
        t2 = time.perf_counter()
        Log.Info(
            "[WordEmbedding] device-pipeline startup: setup+uploads %.1fs",
            t2 - t_phase,
        )

        def stream_data(seq: int, buf):
            """Fresh on-device subsample draw -> compacted corpus + data
            pytree for one (epoch, chunk) leg (identical shapes every leg:
            no recompiles; one n_valid scalar readback)."""
            dyn = prepare(
                buf, keep_dev, p34_dev,
                jax.random.fold_in(prep_key, seq),
            )
            return {**statics, **dyn}, int(dyn["n_valid"])

        # epoch target = the host walk's sample count over the COMPACTED
        # stream. Skip-gram: E[2*eff] = window+1 pairs per kept position;
        # CBOW: one window sample per kept position. Rejected draws (context
        # on a marker / off the end — subsampling no longer rejects) are NOT
        # trained samples — progress tracks the step's accepted count,
        # synced at log points.
        per_kept = 1 if o.cbow else (o.window + 1)
        per_call = o.batch_size * S
        key = jax.random.PRNGKey(o.seed)
        loss_dev = None
        pairs_done = 0
        calls = 0
        data, n_valid = stream_data(0, cur_dev)
        Log.Info(
            "[WordEmbedding] device-pipeline startup: first prepare "
            "(incl. compile) +%.1fs (total %.1fs; %d upload chunk(s))",
            time.perf_counter() - t2, time.perf_counter() - start, nC,
        )
        # lr schedule total: exact for nC == 1; with chunks, estimated from
        # chunk 0's kept fraction and refined as each chunk prepares
        total_pairs = max(1, n_valid * per_kept * nC * o.epoch)
        # each host sync (accepted-count drain) costs a full tunnel round
        # trip + pipeline drain (~0.2s measured — benchmarks/E2E_GAP.md):
        # syncing every call caps the loop at 2.0M pairs/s vs 3.0M at an
        # 8-call cadence and 3.16M unsynced, so the drain/log window is
        # floored at 16 calls
        log_every = max(16, (total_pairs // per_call) // 20)
        legs_done_pairs = 0  # exact target sum of completed legs
        # -- elastic resume (resilience subsystem; ROADMAP device-pipeline
        # NEXT): the device-side data cursor is (leg seq, dispatch-call
        # count, walk_t, PRNG key) — everything the on-device superbatch
        # walk state needs to regenerate the exact remaining schedule
        # (prepare() re-derives each leg's subsample draw + permutation
        # from seed + seq). Checkpoints snapshot the cursor WITHOUT
        # draining the pairs accumulator (it is read, not reset), so the
        # sync cadence — and therefore the projected-lr math — is
        # bit-identical with checkpointing on or off: kill at call K +
        # restart == uninterrupted run.
        ckpt = None
        res = None
        restarts = 0
        seq_start = 0
        if o.checkpoint_dir:
            from multiverso_tpu.resilience import (
                AutoCheckpointer,
                latest_valid,
                load_checkpoint,
            )
            from multiverso_tpu.resilience import stats as _rstats

            if o.resume:
                ck_path = latest_valid(o.checkpoint_dir)
                if ck_path is not None:
                    tree, ck_meta = load_checkpoint(ck_path)
                    CHECK(ck_meta.get("kind") == "device_pipeline",
                          f"checkpoint {ck_path} was not written by the "
                          "device pipeline (checkpoint roots are not "
                          "shared across training paths)")
                    key = jnp.asarray(tree.pop("__prng_key"))
                    CHECK(set(tree) == set(self.params),
                          f"checkpoint {ck_path} params {sorted(tree)} do "
                          f"not match this config's {sorted(self.params)} "
                          "(hs/adagrad/size flags must match)")
                    # jnp.array (copy): a zero-copy asarray view of the
                    # npz-backed host memory would be DONATED by the
                    # first dispatch — the device must own fresh buffers
                    put = (
                        (lambda v: jax.device_put(jnp.array(v), self._tab))
                        if self._tab is not None
                        else (lambda v: jnp.array(v))
                    )
                    self.params = {k: put(v) for k, v in tree.items()}
                    res = ck_meta
                    seq_start = int(ck_meta["seq"])
                    calls = int(ck_meta["calls"])
                    pairs_done = int(ck_meta["pairs_done"])
                    legs_done_pairs = int(ck_meta["legs_done_pairs"])
                    restarts = int(ck_meta.get("restarts", 0)) + 1
                    _rstats.note_restart(restarts)
                    Log.Info(
                        "[WordEmbedding] resumed from %s: leg %d, call %d, "
                        "%.1fM pairs, restart #%d",
                        ck_path, seq_start, calls, pairs_done / 1e6,
                        restarts,
                    )
            ckpt = AutoCheckpointer(
                o.checkpoint_dir,
                every_n_steps=o.checkpoint_every_steps,
                retain=o.checkpoint_retain,
                async_=o.checkpoint_async,
            )
        from multiverso_tpu.resilience import chaos

        self._set_ready(True, "training")  # params live + resume landed
        for seq in range(seq_start, o.epoch * nC):
            mid_resume = res is not None and seq == seq_start
            if mid_resume:
                # re-enter THIS leg: its chunk re-uploads and its data
                # pytree re-prepares (deterministic from seed + seq); the
                # startup prepare above was leg 0's
                cur_dev = _up(chunks_np[seq % nC])
                data, n_valid = stream_data(seq, cur_dev)
                total_pairs = int(res["total_pairs"])
            elif seq > 0:
                data, n_valid = stream_data(seq, cur_dev)
                # refine the schedule total with the actual leg target
                total_pairs = max(
                    1,
                    legs_done_pairs
                    + n_valid * per_kept * (o.epoch * nC - seq),
                )
            if nC > 1:
                # double buffer: dispatch the NEXT chunk's upload now so
                # the transfer rides under this leg's training
                nxt = seq + 1
                cur_dev = (
                    _up(chunks_np[nxt % nC]) if nxt < o.epoch * nC else None
                )
            if mid_resume:
                # mid-leg cursor: walk position, accepted accounting and
                # the projection state restore exactly as staged
                walk_t = int(res["walk_t"])
                epoch_target = max(1, n_valid * per_kept)
                epoch_done = int(res["epoch_done"])
                accepted_dev = jnp.float32(res["accepted_partial"])
                epoch_calls0 = int(res["epoch_calls0"])
                synced_calls = int(res["synced_calls"])
                ppc = float(res["ppc"])
                res = None
            else:
                walk_t = 0  # fresh per-leg permutation; cursor restarts
                epoch_target = max(1, n_valid * per_kept)
                epoch_done = 0
                accepted_dev = jnp.float32(0.0)
                epoch_calls0 = calls
                synced_calls = calls
                # accepted pairs per call, refined at each sync; the
                # initial value is the hard upper bound (every slot
                # accepted), so the projection can only over-estimate
                # progress — it forces an early sync, never an overshoot
                # by a whole log window
                ppc = float(per_call)
            est_calls = max(1, epoch_target // per_call)
            max_calls = epoch_calls0 + 20 * est_calls
            while epoch_done < epoch_target and calls < max_calls:
                # smooth lr decay between host syncs: project progress from
                # the measured accepted-rate instead of holding the last
                # synced count
                projected = pairs_done + ppc * (calls - synced_calls)
                lr = self._lr(min(projected, total_pairs) / total_pairs)
                key, sub = jax.random.split(key)
                if use_walk:
                    # host-side cursor: the dispatch consumes per_call
                    # permutation slots; two scalar leaf swaps, no
                    # re-upload. The abstract period is n_valid * per_kept
                    # (the cycle index drives the per-visit offset strata
                    # — one epoch = one pass of the (position x
                    # offset-stratum) grid), but the cursor ships as
                    # bounded (in-cycle offset, cycle) components so no
                    # int32 overflows even for huge single chunks
                    nv = max(n_valid, 1)
                    if presort_walk:
                        # presorted walks run on the batch-padded modulus
                        # (walk_n) — keeps every dispatch window aligned
                        # to the presorted batch grid
                        nv = -(-nv // o.batch_size) * o.batch_size
                    data["walk_t"] = np.int32(walk_t % nv)
                    data["walk_c"] = np.int32((walk_t // nv) % per_kept)
                    walk_t = (walk_t + per_call) % max(nv * per_kept, 1)
                self.params, (loss_dev, acc) = superstep(
                    self.params, data, sub, jnp.float32(lr)
                )
                accepted_dev = accepted_dev + acc
                calls += 1
                proj_epoch = epoch_done + ppc * (calls - synced_calls)
                if calls % log_every == 0 or proj_epoch >= epoch_target:
                    # drain the device accumulator into an exact host count
                    # and reset it: a run-long float32 sum loses integer
                    # precision past 2^24 accepted pairs (one host sync per
                    # window either way)
                    got = int(float(accepted_dev))
                    accepted_dev = jnp.float32(0.0)
                    epoch_done += got
                    pairs_done += got
                    ppc = max(1.0, epoch_done / max(calls - epoch_calls0, 1))
                    synced_calls = calls
                    if calls % log_every == 0:
                        rate = pairs_done / max(time.perf_counter() - start, 1e-9)
                        Log.Info(
                            "[WordEmbedding] device-pipeline: %.1fM pairs, "
                            "%.0fk pairs/s, lr %.5f, loss %.4f",
                            pairs_done / 1e6, rate / 1e3, lr, float(loss_dev),
                        )
                if ckpt is not None:
                    # AFTER the sync block: the staged state is the end of
                    # this call's iteration, so a resumed loop re-enters
                    # exactly where an uninterrupted one would continue
                    self._ondevice_maybe_checkpoint(
                        ckpt, calls, seq, pairs_done, legs_done_pairs,
                        total_pairs, walk_t, epoch_done, accepted_dev,
                        epoch_calls0, synced_calls, ppc, key, restarts,
                    )
                chaos.maybe_kill(calls)
            if calls != synced_calls:  # drain the leg tail (if undrained)
                got = int(float(accepted_dev))
                epoch_done += got
                pairs_done += got
            if calls >= max_calls and epoch_done < epoch_target:
                Log.Error(
                    "[WordEmbedding] device-pipeline hit the %d-call bound at "
                    "%.1fM/%.1fM leg pairs — corpus rejects nearly every "
                    "draw; leg truncated",
                    max_calls, epoch_done / 1e6, epoch_target / 1e6,
                )
            legs_done_pairs += epoch_target
        if ckpt is not None:
            ckpt.close()  # drain the in-flight async save
        jax.block_until_ready(self.params)
        self.words_trained = pairs_done
        rate = self.words_trained / max(time.perf_counter() - start, 1e-9)
        Log.Info(
            "[WordEmbedding] device-pipeline done: %.1fM pairs in %.1fs (%.0fk pairs/s)",
            self.words_trained / 1e6, time.perf_counter() - start, rate / 1e3,
        )
        if o.output_file:
            self.save_embeddings(o.output_file, binary=o.binary)
        return float(loss_dev) if loss_dev is not None else 0.0

    def _run_superbatch(self, batches: list, lr: float) -> jax.Array:
        """One scanned dispatch over a list of identically-shaped batches."""
        o = self.opt
        stack = lambda key: jnp.asarray(np.stack([b[key] for b in batches]))
        if o.presort:
            dev = {
                k: stack(k) for k, v in batches[0].items() if v is not None
            }
            self.params, loss = self._superstep(self.params, dev, jnp.float32(lr))
            return loss
        ctx = (
            None
            if batches[0].get("contexts") is None
            else stack("contexts")
        )
        if o.hs:
            self.params, loss = self._superstep(
                self.params,
                stack("centers"),
                stack("points"),
                stack("codes"),
                stack("lengths"),
                ctx,
                jnp.float32(lr),
            )
        else:
            self.params, loss = self._superstep(
                self.params, stack("centers"), stack("outputs"), ctx, jnp.float32(lr)
            )
        return loss

    def train(self, ids: Optional[np.ndarray] = None) -> float:
        """Train over the corpus; returns the last logged loss."""
        from multiverso_tpu.analysis.guards import register_training_thread

        # this thread owns the training loop: the depth-0 PS sync points
        # dispatch table collectives from it (thread-identity guard, R1)
        register_training_thread()
        # obs: a pure trainer answers /healthz, /readyz and /metrics
        # itself when -health_port is armed (a TableServer in the same
        # process starts its own endpoint through start(); a taken port
        # logs and degrades, it never kills training)
        health = http_health.maybe_start_from_flags(None)
        try:
            return self._train_dispatch(ids)
        finally:
            # the span trace dumps whether training finished or raised —
            # crash traces are the ones worth reading
            obs.tracer.maybe_dump_from_flags()
            _mvtsan.maybe_dump_from_flags()
            if health is not None:
                health.stop()

    def _train_dispatch(self, ids: Optional[np.ndarray] = None) -> float:
        o = self.opt
        # not ready until the chosen path's tables exist and any resume
        # landed (each path flips it back on right before its loop)
        self._set_ready(False, "restoring")
        if ids is None:
            # each path routes by its own suffix: .npy = pre-encoded id
            # stream (synth.py / preprocess output), else tokenized text
            chunks = []
            for p in o.train_file.split(";"):
                if p.endswith(".npy"):
                    chunks.append(np.load(p))
                else:
                    chunks.append(self.dict.encode_corpus([p]))
            ids = np.concatenate(chunks)
        ids = np.ascontiguousarray(ids, np.int32)
        keep = subsample_keep_probs(self.dict.counts, o.sample)
        # Flag validity lives in config/constraints.py (same model the
        # implications, mvlint R12, and the DEPLOY.md table read);
        # CHECK keeps the historical die-on-violation behavior.
        constraints.check_options(
            o, constraints.Env(process_count=jax.process_count()), CHECK
        )
        if o.device_pipeline:
            return self._train_ondevice(ids, keep)
        def make_pipeline(shard_ids, seed):
            return BatchPipeline(
                shard_ids,
                window=o.window,
                batch_size=o.batch_size,
                negatives=o.negative,
                cbow=o.cbow,
                keep_probs=keep,
                sampler=self.sampler,
                huffman=self.huffman,
                seed=seed,
                # PS blocks presort against REMAPPED compact ids inside
                # _run_superbatch_ps; global-id presort here would be wasted
                presort=o.presort and not o.use_ps,
                scale_mode=o.scale_mode,
            )

        nthreads = max(1, int(getattr(o, "threads", 1)))
        if nthreads > 1 and o.is_pipeline and len(ids) > nthreads * o.batch_size:
            # per-thread corpus shards (ref: trainer.cpp:27-54 strided blocks)
            bounds = np.linspace(0, len(ids), nthreads + 1).astype(np.int64)
            pipeline = [
                make_pipeline(ids[bounds[i]: bounds[i + 1]], o.seed + i)
                for i in range(nthreads)
            ]
        else:
            pipeline = make_pipeline(ids, o.seed)
        # E[pairs per word] = 2*E[effective window] = window + 1 (uniform shrink)
        total_pairs_est = max(len(ids) * (o.window + 1) * o.epoch, 1)
        start = time.perf_counter()
        loss_dev = None  # device value; forced only at log points
        pairs_done = 0
        # pipeline mode: producer thread + native MtQueue handoff (the
        # reference's BlockQueue preload — distributed_wordembedding.cpp:33-56)
        source = (
            PrefetchPipeline(pipeline, depth=max(1, o.max_preload_data_size))
            if o.is_pipeline
            else pipeline
        )
        if o.use_ps:
            if o.checkpoint_dir:
                # PS checkpoints count in ROUNDS and must fire at the
                # SAME round on every rank (the save is a collective):
                # only the round counter is rank-identical, wall clocks
                # are not — and the resume cursor needs a deterministic
                # batch order
                CHECK(o.checkpoint_every_seconds == 0,
                      "-checkpoint_every_seconds is unsupported in PS "
                      "mode: ranks must checkpoint at the SAME round "
                      "(use -checkpoint_every_steps = every N rounds)")
                CHECK(nthreads == 1,
                      "-checkpoint_dir in PS mode requires -threads=1: "
                      "the resume data cursor needs a deterministic "
                      "batch order")
            return self._train_ps(source, total_pairs_est, start)
        S = max(1, o.steps_per_call)
        log_every = o.batch_size * max(64, S * 8)
        # -- elastic resume (resilience subsystem): restore params +
        # optimizer slots + step counter + lr progress + data cursor from
        # the latest VALID checkpoint, then replay the epoch tail. Batches
        # regenerate deterministically (same seed, skip= cursor), so a
        # kill-at-step-K + restart run is step-for-step identical to an
        # uninterrupted one.
        ckpt = None
        start_epoch = 0
        resume_skip = 0
        step = 0
        restarts = 0
        if o.checkpoint_dir:
            from multiverso_tpu.resilience import (
                AutoCheckpointer,
                latest_valid,
                load_checkpoint,
            )
            from multiverso_tpu.resilience import stats as _rstats

            CHECK(jax.process_count() == 1,
                  "-checkpoint_dir requires a single process (fused params "
                  "are rank-local; multi-process training goes through "
                  "-use_ps + io.save_tables)")
            CHECK(nthreads == 1,
                  "-checkpoint_dir requires -threads=1: the resume data "
                  "cursor needs a deterministic batch order")
            if o.resume:
                path = latest_valid(o.checkpoint_dir)
                if path is not None:
                    tree, meta = load_checkpoint(path)
                    CHECK(set(tree) == set(self.params),
                          f"checkpoint {path} params {sorted(tree)} do not "
                          f"match this config's {sorted(self.params)} "
                          "(hs/adagrad/size flags must match the saved run)")
                    # jnp.array (copy): the donated first dispatch must
                    # not alias the npz-backed host memory
                    self.params = {k: jnp.array(v) for k, v in tree.items()}
                    start_epoch = int(meta["epoch"])
                    resume_skip = int(meta["batches_in_epoch"])
                    pairs_done = int(meta["pairs_done"])
                    step = int(meta["step"])
                    restarts = int(meta.get("restarts", 0)) + 1
                    _rstats.note_restart(restarts)
                    Log.Info(
                        "[WordEmbedding] resumed from %s: step %d, epoch %d, "
                        "batch %d, %.1fM pairs, restart #%d",
                        path, step, start_epoch, resume_skip,
                        pairs_done / 1e6, restarts,
                    )
            ckpt = AutoCheckpointer(
                o.checkpoint_dir,
                every_n_steps=o.checkpoint_every_steps,
                every_n_seconds=o.checkpoint_every_seconds,
                retain=o.checkpoint_retain,
                async_=o.checkpoint_async,
            )
        from multiverso_tpu.resilience import chaos

        if start_epoch > 0:
            # the pair generator's RNG stream (negative draws, presort
            # seeds) spans epochs; regenerate-and-discard the completed
            # epochs so the resumed stream is bit-identical to an
            # uninterrupted run's (host-only work, no device steps)
            Log.Info(
                "[WordEmbedding] resume: advancing the batch stream through "
                "%d completed epoch(s)", start_epoch,
            )
            for ep in range(start_epoch):
                for _ in source.batches(ep):
                    pass
        self._set_ready(True, "training")  # params live + resume landed
        try:
            for epoch in range(start_epoch, o.epoch):
                skip = resume_skip if epoch == start_epoch else 0
                it = source.batches(epoch, skip=skip)
                batches_in_epoch = skip
                done = False
                while not done:
                    # pack up to S microbatches into one scanned dispatch
                    group = []
                    while len(group) < S:
                        batch = next(it, None)
                        if batch is None:
                            done = True
                            break
                        group.append(batch)
                    if not group:
                        break
                    lr = self._lr(pairs_done / total_pairs_est)
                    if len(group) == S:
                        loss_dev = self._run_superbatch(group, lr)
                    else:  # epoch tail: step singly, avoids a per-length recompile
                        for b in group:
                            loss_dev = self._run_batch(b, lr)
                    prev = pairs_done
                    pairs_done += o.batch_size * len(group)
                    batches_in_epoch += len(group)
                    step += 1
                    if ckpt is not None:
                        self._maybe_checkpoint(
                            ckpt, step, epoch, batches_in_epoch, pairs_done,
                            restarts,
                        )
                    chaos.maybe_kill(step)
                    if pairs_done // log_every > prev // log_every:
                        rate = pairs_done / max(time.perf_counter() - start, 1e-9)
                        Log.Info(
                            "[WordEmbedding] epoch %d: %.1fM pairs, %.0fk pairs/s, "
                            "lr %.5f, loss %.4f",
                            epoch, pairs_done / 1e6, rate / 1e3, lr, float(loss_dev),
                        )
        finally:
            if ckpt is not None:
                ckpt.close()  # drain the in-flight async save (even on a
                # raise-mode chaos kill: the test's restart must see it)
        jax.block_until_ready(self.params)
        last_loss = float(loss_dev) if loss_dev is not None else 0.0
        self.words_trained = pairs_done
        rate = pairs_done / max(time.perf_counter() - start, 1e-9)
        Log.Info(
            "[WordEmbedding] done: %.1fM pairs in %.1fs (%.0fk pairs/s)",
            pairs_done / 1e6, time.perf_counter() - start, rate / 1e3,
        )
        if o.output_file:
            self.save_embeddings(o.output_file, binary=o.binary)
        return last_loss

    # ------------------------------------------------------------- output

    def embeddings(self) -> np.ndarray:
        # [:V] slices off shard-padding rows (sharded device pipeline pads
        # the row dim to a multiple of the shard axis)
        return np.asarray(self.params["emb_in"])[: self.cfg.vocab_size]

    def save_embeddings(self, path: str, binary: bool = False) -> None:
        """word2vec format (ref: distributed_wordembedding.cpp:263-306
        SaveEmbedding, text and -binary variants). Multi-process: ONE rank
        writes the file instead of racing them over one path (gate BEFORE
        the device->host materialisation: non-writers skip the copy). The
        identical-on-every-rank property only holds for PS mode (shared
        tables); fused-path params are rank-local, so a rank-0-only write
        would silently drop other ranks' training — fail loudly there."""
        if jax.process_count() > 1:
            CHECK(self.opt.use_ps,
                  "multi-process save_embeddings requires -use_ps (fused "
                  "params are rank-local; only the shared tables give "
                  "every rank identical embeddings to checkpoint)")
            if jax.process_index() != 0:
                return
        emb = self.embeddings()
        V, D = emb.shape
        with open(path, "wb") as f:
            f.write(f"{V} {D}\n".encode())
            for w, row in zip(self.dict.words, emb):
                if binary:
                    f.write((w + " ").encode())
                    f.write(row.astype(np.float32).tobytes())
                    f.write(b"\n")
                else:
                    f.write(
                        (w + " " + " ".join(f"{v:.6f}" for v in row) + "\n").encode()
                    )
        Log.Info("[WordEmbedding] saved %dx%d embeddings to %s", V, D, path)
