"""CLI entry — reference main.cpp parity
(ref: Applications/WordEmbedding/src/main.cpp; flags per example/run.bat).

Usage: python -m multiverso_tpu.models.wordembedding -train_file=corpus.txt \
       -size=100 -window=5 -negative=5 -epoch=1 [-cbow=true] [-hs=true] ...
"""

import sys

import multiverso_tpu as mv
from multiverso_tpu.models.wordembedding.app import WEOptions, WordEmbedding
from multiverso_tpu.utils.log import Log


def main(argv):
    mv.MV_Init(argv)
    opt = WEOptions.from_flags()
    if not opt.train_file:
        Log.Error(
            "usage: python -m multiverso_tpu.models.wordembedding "
            "-train_file=<corpus> [-size=100 -window=5 ...]"
        )
        return 1
    we = WordEmbedding(opt)
    we.train()
    mv.MV_ShutDown()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
