"""Distributed word2vec (WordEmbedding application).

TPU-first rebuild of Applications/WordEmbedding (ref: SURVEY.md §2.7): the
reference trains per-window scalar loops over locally-cached rows
(ref: Applications/WordEmbedding/src/wordembedding.cpp:57-166); here training
is a batched jitted SPMD step — row gathers from sharded embedding tables,
one MXU matmul per batch for the dot products, closed-form gradients, and
scatter-add updates.
"""

from multiverso_tpu.models.wordembedding.skipgram import (
    SkipGramConfig,
    init_params,
    loss_fn,
    make_sgd_step,
)

__all__ = ["SkipGramConfig", "init_params", "loss_fn", "make_sgd_step"]
