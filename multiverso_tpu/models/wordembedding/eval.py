"""Embedding quality evaluation: analogy task + nearest neighbours.

The reference's quality bar is analogy / WS-353 parity plots
(ref: Applications/WordEmbedding/README.md:16, example/imges/). This module
implements the standard word2vec analogy protocol (a:b :: c:?d by cosine over
unit-normalised vectors, excluding the query words) and similarity
correlation for WS-353-style files.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "load_word2vec_text",
    "analogy_accuracy",
    "similarity_spearman",
    "nearest",
    "cosine_topk",
]


def load_word2vec_text(path: str) -> Tuple[List[str], np.ndarray]:
    with open(path, "rb") as f:
        header = f.readline().split()
        V, D = int(header[0]), int(header[1])
        words, rows = [], []
        for _ in range(V):
            parts = f.readline().decode("utf-8", "replace").rstrip("\n").split(" ")
            words.append(parts[0])
            rows.append(np.asarray([float(x) for x in parts[1 : D + 1]], np.float32))
    return words, np.stack(rows)


def _normalize(emb: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(emb, axis=1, keepdims=True)
    return emb / np.maximum(norms, 1e-12)


def analogy_accuracy(
    words: List[str],
    emb: np.ndarray,
    questions: List[Tuple[str, str, str, str]],
    batch: int = 512,
) -> Tuple[float, int]:
    """word2vec analogy protocol: argmax cosine(b - a + c), excluding a/b/c.
    Returns (accuracy, evaluated_count); questions with OOV words are skipped
    (the reference does the same)."""
    w2i = {w: i for i, w in enumerate(words)}
    emb_n = _normalize(emb)
    idx = [
        (w2i[a], w2i[b], w2i[c], w2i[d])
        for a, b, c, d in questions
        if a in w2i and b in w2i and c in w2i and d in w2i
    ]
    if not idx:
        return 0.0, 0
    correct = 0
    arr = np.asarray(idx, np.int64)
    for s in range(0, len(arr), batch):
        chunk = arr[s : s + batch]
        a, b, c, d = chunk.T
        query = emb_n[b] - emb_n[a] + emb_n[c]
        query = query / np.maximum(np.linalg.norm(query, axis=1, keepdims=True), 1e-12)
        sims = query @ emb_n.T  # (chunk, V)
        rows = np.arange(len(chunk))
        sims[rows, a] = -np.inf
        sims[rows, b] = -np.inf
        sims[rows, c] = -np.inf
        correct += int((np.argmax(sims, axis=1) == d).sum())
    return correct / len(arr), len(arr)


def similarity_spearman(
    words: List[str], emb: np.ndarray, pairs: List[Tuple[str, str, float]]
) -> Tuple[float, int]:
    """Spearman rank correlation of cosine similarity vs human scores
    (WS-353 protocol)."""
    w2i = {w: i for i, w in enumerate(words)}
    emb_n = _normalize(emb)
    xs, ys = [], []
    for a, b, score in pairs:
        if a in w2i and b in w2i:
            xs.append(float(emb_n[w2i[a]] @ emb_n[w2i[b]]))
            ys.append(float(score))
    if len(xs) < 2:
        return 0.0, 0

    def _ranks(v):
        # average ranks for ties (scipy rankdata semantics) — human scores
        # have many exact ties, and arbitrary tie-breaking would make rho
        # depend on pair order in the file
        v = np.asarray(v)
        order = np.argsort(v, kind="stable")
        ranks = np.empty(len(v))
        ranks[order] = np.arange(len(v), dtype=np.float64)
        sv = v[order]
        i = 0
        while i < len(sv):
            j = i
            while j + 1 < len(sv) and sv[j + 1] == sv[i]:
                j += 1
            if j > i:
                ranks[order[i : j + 1]] = (i + j) / 2.0
            i = j + 1
        return ranks

    rx, ry = _ranks(np.asarray(xs)), _ranks(np.asarray(ys))
    rho = np.corrcoef(rx, ry)[0, 1]
    return float(rho), len(xs)


def cosine_topk(
    emb: np.ndarray, queries: np.ndarray, k: int = 10
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched cosine top-k: (Q, D) query vectors against (V, D) rows ->
    (ids (Q, k), scores (Q, k)), descending. ONE scoring definition:
    ``nearest`` reuses it, and it is the numpy golden the serving
    subsystem's jitted top-k route (serving/server.py) is tested
    against — the two must not drift."""
    emb_n = _normalize(np.asarray(emb, np.float32))
    q_n = _normalize(np.asarray(queries, np.float32).reshape(-1, emb.shape[1]))
    sims = q_n @ emb_n.T  # (Q, V)
    top = np.argsort(-sims, axis=1, kind="stable")[:, :k]
    return top, np.take_along_axis(sims, top, axis=1)


def nearest(
    words: List[str], emb: np.ndarray, query: str, k: int = 10
) -> List[Tuple[str, float]]:
    w2i = {w: i for i, w in enumerate(words)}
    if query not in w2i:
        return []
    qi = w2i[query]
    # k+1 through the shared scorer, then drop the query row itself
    top, scores = cosine_topk(emb, emb[qi : qi + 1], k + 1)
    out = [
        (words[i], float(s))
        for i, s in zip(top[0], scores[0])
        if i != qi
    ]
    return out[:k]
