"""Natural-shaped synthetic corpus: log-linear topic model, no planted windows.

Round-2 VERDICT item 2: the planted-analogy corpus (synth.py) grades its own
exam — every analogy window is literally constructed around the quadruple
structure. This generator produces a harder, *natural-shaped* corpus whose
co-occurrence statistics EMERGE from a latent-variable language model
instead of being planted per window (the reference's bar is analogy /
WS-353 parity against an independently trained word2vec on real text —
ref: Applications/WordEmbedding/README.md:16; the benchmark image has zero
egress, so real text is unavailable and emergent-structure synthesis is
the honest substitute):

* every word ``w`` carries a latent vector ``z_w``; a subset lies on a
  compositional grid ``z = u_base + v_mod`` (the analogy probe set), the
  rest are free Gaussians;
* each sentence draws a topic ``t`` (one of ``n_topics`` Gaussian
  prototypes) and samples words from the log-linear mixture
  ``p_t(w) ∝ unigram(w) · exp(alpha · z_w · t)`` — the classic
  topic/log-linear generative family behind PMI-factorisation analyses of
  word2vec (SGNS approximately factorises PMI, and under Gaussian topics
  PMI(w,c) grows with ``z_w · z_c``), so trained embeddings recover the
  latent geometry iff training works;
* the unigram envelope is Zipf-Mandelbrot (same shape as synth.py /
  the bench's skewed batches), sentences end in ``-1`` markers.

Nothing in the token stream mentions the questions: analogy quadruples and
graded similarity pairs are derived from the latent geometry afterward, and
the quality bar in bench.py is PARITY against an independently implemented
SGNS trainer (benchmarks/torch_sgns.py) on the same corpus — not a score
the generator can hand to itself.

Generation is vectorized numpy, chunked (per-topic inverse-CDF tables,
grouped draws): ~100M tokens in a few minutes on one core.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np

from multiverso_tpu.models.wordembedding.dictionary import Dictionary
from multiverso_tpu.models.wordembedding.synth import zipf_probs

__all__ = ["NaturalConfig", "generate_natural"]


@dataclasses.dataclass
class NaturalConfig:
    tokens: int = 100_000_000
    vocab_size: int = 50_000
    latent_dim: int = 16
    n_topics: int = 256        # quantized topic prototypes
    n_bases: int = 40          # compositional grid: bases x mods words
    n_mods: int = 25
    # signal strength: random unit vectors in D dims have |z.t| ~ 1/sqrt(D),
    # and the emergent PMI spread scales as alpha^2/D — alpha=8 at D=16
    # gives word2vec-learnable structure (tuned empirically; alpha<=4 is
    # noise-dominated, benchmarks/QUALITY.md)
    alpha: float = 8.0
    sent_len: int = 20         # tokens per sentence incl. the -1 marker
    zipf_s: float = 1.05
    zipf_q: float = 2.7
    n_questions: int = 2000
    n_sim_pairs: int = 2000
    seed: int = 3

    @property
    def n_grid(self) -> int:
        return self.n_bases * self.n_mods


def _latents(cfg: NaturalConfig, rng: np.random.RandomState):
    """Latent vectors per vocab id + the grid id placement.

    Grid words are spread across the frequency ranks (not parked in the
    rare tail) so the probe words get enough occurrences to train."""
    D = cfg.latent_dim
    z = rng.randn(cfg.vocab_size, D)
    # compositional grid: z = u_base + v_mod (+ small noise), placed at
    # evenly spaced ranks within the frequent 40% of the vocabulary (the
    # probe words need enough occurrences to train)
    u = rng.randn(cfg.n_bases, D) * 0.75
    v = rng.randn(cfg.n_mods, D) * 0.75
    grid_ids = np.unique(
        np.linspace(50, int(cfg.vocab_size * 0.4), cfg.n_grid).astype(np.int64)
    )
    assert len(grid_ids) == cfg.n_grid, "vocab too small for the grid"
    a = np.repeat(np.arange(cfg.n_bases), cfg.n_mods)
    b = np.tile(np.arange(cfg.n_mods), cfg.n_bases)
    z[grid_ids] = u[a] + v[b] + rng.randn(cfg.n_grid, D) * 0.05
    # ONE global scale (mean norm -> 1): per-word normalisation would break
    # the additive grid structure the analogy probes measure — a uniform
    # scaling preserves it while keeping alpha's meaning stable across dims
    z /= max(float(np.linalg.norm(z, axis=1).mean()), 1e-9)
    return z, grid_ids, a, b


def generate_natural(
    cfg: NaturalConfig,
) -> Tuple[
    np.ndarray,
    Dictionary,
    List[Tuple[str, str, str, str]],
    List[Tuple[str, str, float]],
]:
    """Returns (ids with -1 markers, Dictionary, analogy questions,
    graded similarity pairs)."""
    rng = np.random.RandomState(cfg.seed)
    V = cfg.vocab_size
    z, grid_ids, ga, gb = _latents(cfg, rng)
    uni = zipf_probs(V, cfg.zipf_s, cfg.zipf_q)
    topics = rng.randn(cfg.n_topics, cfg.latent_dim)
    topics /= np.maximum(np.linalg.norm(topics, axis=1, keepdims=True), 1e-9)
    # per-topic inverse-CDF tables: p_t(w) ∝ uni(w) * exp(alpha z_w . t)
    logits = cfg.alpha * (z @ topics.T)  # (V, T)
    logits -= logits.max(axis=0, keepdims=True)
    pk = uni[:, None] * np.exp(logits)
    pk /= pk.sum(axis=0, keepdims=True)
    cdfs = np.cumsum(pk.T, axis=1)  # (T, V)
    cdfs[:, -1] = 1.0

    L = cfg.sent_len - 1  # live tokens per sentence
    n_sent = max(1, cfg.tokens // cfg.sent_len)
    chunk_sents = max(1, 5_000_000 // cfg.sent_len)
    out = []
    for s0 in range(0, n_sent, chunk_sents):
        ns = min(chunk_sents, n_sent - s0)
        topic_of = rng.randint(0, cfg.n_topics, ns)
        rows = np.empty((ns, cfg.sent_len), np.int32)
        rows[:, -1] = -1
        u01 = rng.random_sample((ns, L))
        # grouped per-topic draws: one searchsorted per topic present
        order = np.argsort(topic_of, kind="stable")
        sorted_topics = topic_of[order]
        bounds = np.searchsorted(
            sorted_topics, np.arange(cfg.n_topics + 1), side="left"
        )
        drawn = np.empty((ns, L), np.int32)
        for t in range(cfg.n_topics):
            lo, hi = bounds[t], bounds[t + 1]
            if lo == hi:
                continue
            sel = order[lo:hi]
            drawn[sel] = np.searchsorted(
                cdfs[t], u01[sel].reshape(-1)
            ).reshape(hi - lo, L).astype(np.int32)
        rows[:, :-1] = drawn
        out.append(rows.reshape(-1))
    ids = np.concatenate(out)

    # frequency re-rank to the dictionary convention (descending counts)
    counts = np.bincount(ids[ids >= 0], minlength=V)
    order = np.argsort(-counts, kind="stable")
    order = order[counts[order] > 0]
    remap = np.full(V, -1, np.int32)
    remap[order] = np.arange(len(order), dtype=np.int32)
    ids = np.where(ids >= 0, remap[np.maximum(ids, 0)], ids).astype(np.int32)

    names = np.array([f"f{r}" for r in range(V)], dtype=object)
    names[grid_ids] = [f"g{a}_{b}" for a, b in zip(ga, gb)]
    d = Dictionary()
    d.words = [str(names[o]) for o in order]
    d.word2id = {w: i for i, w in enumerate(d.words)}
    d.counts = counts[order].astype(np.int64)

    qrng = np.random.RandomState(cfg.seed + 11)
    questions = _grid_questions(cfg, qrng)
    sims = _sim_pairs(cfg, qrng, z, order, counts, names)
    return ids, d, questions, sims


def _grid_questions(cfg, rng) -> List[Tuple[str, str, str, str]]:
    """Quadruples from the compositional grid: g(a1,b1):g(a1,b2) ::
    g(a2,b1):g(a2,b2). Derived from the latent geometry, never mentioned
    in the token stream."""
    qs = []
    for _ in range(cfg.n_questions):
        a1, a2 = rng.choice(cfg.n_bases, 2, replace=False)
        b1, b2 = rng.choice(cfg.n_mods, 2, replace=False)
        qs.append((f"g{a1}_{b1}", f"g{a1}_{b2}", f"g{a2}_{b1}", f"g{a2}_{b2}"))
    return qs


def _sim_pairs(cfg, rng, z, order, counts, names) -> List[Tuple[str, str, float]]:
    """WS-353-shaped graded pairs: gold score = latent cosine (scaled to
    0..10), sampled among reasonably frequent words so both trainers see
    enough occurrences to have an opinion."""
    # candidates: the most frequent ~40% of the REALIZED ranking
    top = order[: max(1000, int(len(order) * 0.4))]
    pairs = []
    zn = z / np.maximum(np.linalg.norm(z, axis=1, keepdims=True), 1e-9)
    for _ in range(cfg.n_sim_pairs):
        i, j = rng.choice(len(top), 2, replace=False)
        wi, wj = top[i], top[j]
        score = float(zn[wi] @ zn[wj])  # gold = latent cosine
        pairs.append((str(names[wi]), str(names[wj]), round(5.0 * (score + 1.0), 4)))
    return pairs
