"""Huffman encoder for hierarchical softmax.

Reference semantics (ref: Applications/WordEmbedding/src/huffman_encoder.h:
32-58, huffman_encoder.cpp): build a Huffman tree over word frequencies; per
word store its code (left/right bits) and point (inner-node id path). The
output-embedding table for HS has ``vocab_size - 1`` inner-node rows.

TPU packaging: codes/points padded to ``max_code_length`` int32 arrays with a
length vector, ready for fixed-shape batched HS training (mask = position <
length).
"""

from __future__ import annotations

import heapq
from typing import List, Tuple

import numpy as np

from multiverso_tpu.utils.log import CHECK

__all__ = ["HuffmanEncoder"]


class HuffmanEncoder:
    def __init__(self, counts: np.ndarray):
        """counts: per-word frequency (descending-id order not required)."""
        V = int(len(counts))
        CHECK(V >= 2, "huffman needs at least 2 words")
        self.vocab_size = V
        # heap of (count, tiebreak, node_id); leaves 0..V-1, inner V..2V-2
        heap: List[Tuple[int, int, int]] = [
            (int(c), i, i) for i, c in enumerate(counts)
        ]
        heapq.heapify(heap)
        parent = np.zeros(2 * V - 1, np.int32)
        binary = np.zeros(2 * V - 1, np.int8)
        next_inner = V
        while len(heap) > 1:
            c1, _, n1 = heapq.heappop(heap)
            c2, _, n2 = heapq.heappop(heap)
            parent[n1] = next_inner
            parent[n2] = next_inner
            binary[n2] = 1
            heapq.heappush(heap, (c1 + c2, next_inner, next_inner))
            next_inner += 1
        root = next_inner - 1

        codes: List[List[int]] = []
        points: List[List[int]] = []
        for w in range(V):
            code, point = [], []
            node = w
            while node != root:
                code.append(int(binary[node]))
                node = int(parent[node])
                # inner node id relative to the inner-node table [0, V-1)
                point.append(node - V)
            code.reverse()
            point.reverse()
            codes.append(code)
            points.append(point)
        self.max_code_length = max(len(c) for c in codes)
        L = self.max_code_length
        self.codes = np.zeros((V, L), np.int8)
        self.points = np.zeros((V, L), np.int32)
        self.lengths = np.zeros(V, np.int32)
        for w in range(V):
            l = len(codes[w])
            self.lengths[w] = l
            self.codes[w, :l] = codes[w]
            self.points[w, :l] = points[w]

    @property
    def num_inner_nodes(self) -> int:
        """Rows of the HS output table (ref: vocab_size - 1 inner nodes)."""
        return self.vocab_size - 1

    def paths_for(self, word_ids: np.ndarray):
        """(points (N, L), codes (N, L), lengths (N,)) for a word-id batch."""
        ids = np.asarray(word_ids, np.int32)
        return self.points[ids], self.codes[ids], self.lengths[ids]
