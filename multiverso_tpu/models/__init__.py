"""Model/application layer: the reference's two applications rebuilt TPU-first
(WordEmbedding — SURVEY.md §2.7; LogisticRegression — SURVEY.md §2.7)."""
