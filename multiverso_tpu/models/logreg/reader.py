"""Sample readers: text (default/weight) and binary (bsparse) formats, with a
background prefetch thread.

Reference semantics (ref: Applications/LogisticRegression/src/reader.h:20-150,
reader.cpp; formats documented in configure.h:56-68):

* **default** text — one sample per line:
  sparse (libsvm): ``label key:value key:value ...``;
  dense: ``label value value ...``
* **weight** text — first column is ``label:weight``; rest like default.
* **bsparse** binary — per sample: ``count(u64) label(i32) weight(f64)
  key(u64) ...`` (keys only; values implicitly 1).

The reference runs parsers on a background thread into a ring buffer of
``Sample*`` and emits per-sync-chunk key bitmaps for sparse pulls; here a
daemon thread parses ahead into a bounded queue (``read_buffer_size``), and
minibatches come out as fixed-shape padded numpy arrays ready for the jitted
step (padding keys are 0 with value 0 — a no-op against weights). Each
batch also carries the **touched-keys set** (the reference's SparseBlock<bool>
bitmap) for sparse PS pulls.
"""

from __future__ import annotations

import queue
import struct
import threading
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from multiverso_tpu.io.streams import StreamFactory, TextReader
from multiverso_tpu.utils.log import CHECK, Log

__all__ = ["Sample", "SampleReader", "make_reader"]


class Sample:
    """One parsed sample (ref: data_type.h Sample<EleType>)."""

    __slots__ = ("label", "weight", "keys", "values", "dense")

    def __init__(self, label, weight=1.0, keys=None, values=None, dense=None):
        self.label = int(label)
        self.weight = float(weight)
        self.keys = keys
        self.values = values
        self.dense = dense


def _parse_default_line(line: str, sparse: bool, with_weight: bool) -> Optional[Sample]:
    parts = line.split()
    if not parts:
        return None
    if with_weight:
        lab, _, w = parts[0].partition(":")
        label, weight = int(lab), float(w or 1.0)
    else:
        label, weight = int(float(parts[0])), 1.0
    rest = parts[1:]
    if sparse:
        keys, values = [], []
        for tok in rest:
            k, _, v = tok.partition(":")
            keys.append(int(k))
            values.append(float(v) if v else 1.0)
        return Sample(label, weight, np.asarray(keys, np.int64),
                      np.asarray(values, np.float32))
    return Sample(label, weight, dense=np.asarray([float(t) for t in rest], np.float32))


def _iter_bsparse(uri: str) -> Iterator[Sample]:
    stream = StreamFactory.GetStream(uri, "r")
    header = struct.Struct("<qid")  # count(u64) label(i32) weight(f64)
    while True:
        head = stream.Read(header.size)
        if len(head) < header.size:
            break
        count, label, weight = header.unpack(head)
        raw = stream.Read(8 * count)
        if len(raw) < 8 * count:
            Log.Error("bsparse: truncated sample, stopping")
            break
        keys = np.frombuffer(raw, dtype="<i8").astype(np.int64)
        yield Sample(label, weight, keys, np.ones(count, np.float32))
    stream.Close()


class SampleReader:
    """Background-thread sample parser + fixed-shape batcher."""

    def __init__(self, config):
        self.config = config
        self.sparse = bool(config.sparse)
        self.reader_type = config.reader_type
        CHECK(
            self.reader_type in ("default", "weight", "bsparse"),
            f"unknown reader_type {config.reader_type!r}",
        )
        if self.reader_type == "bsparse":
            CHECK(self.sparse, "bsparse reader requires sparse=true")
        self.files = [f for f in str(config.train_file).split(";") if f]
        self._truncation_warned = False
        # _batch_of runs on the async produce thread AND foreground
        # iter_batches: warn-once is a check-then-set (mvlint R9)
        self._warn_lock = threading.Lock()

    # -- sample iteration -------------------------------------------------

    _CHUNK = 4 << 20  # native-parse chunk size (bytes)

    def _iter_file_native(self, uri: str, with_weight: bool) -> Iterator[Sample]:
        """Chunked native C++ parse (textparse.cpp): CSR arrays per chunk,
        zero per-token Python string work."""
        from multiverso_tpu.native.textparse import parse_sparse_chunk

        stream = StreamFactory.GetStream(uri, "r")
        tail = b""
        try:
            while True:
                data = stream.Read(self._CHUNK)
                buf = tail + data
                if not buf:
                    break
                if not data and not buf.endswith(b"\n"):
                    buf += b"\n"  # final unterminated line
                # buffers are sized from the chunk, so one call parses every
                # complete line; consumed < len(buf) only leaves the
                # incomplete trailing line for the next read
                labels, weights, offsets, keys, values, consumed = (
                    parse_sparse_chunk(buf, with_weight)
                )
                for i in range(len(labels)):
                    a, b = offsets[i], offsets[i + 1]
                    yield Sample(labels[i], weights[i], keys[a:b], values[a:b])
                tail = buf[consumed:]
                if not data:
                    if tail:
                        Log.Error(
                            "[SampleReader] %d unparsed trailing bytes dropped",
                            len(tail),
                        )
                    break
        finally:
            stream.Close()

    def _iter_file(self, uri: str) -> Iterator[Sample]:
        if self.reader_type == "bsparse":
            yield from _iter_bsparse(uri)
            return
        with_weight = self.reader_type == "weight"
        if self.sparse:
            from multiverso_tpu.native.textparse import have_native_textparse

            if have_native_textparse():
                yield from self._iter_file_native(uri, with_weight)
                return
        reader = TextReader(uri)
        for line in reader:
            s = _parse_default_line(line, self.sparse, with_weight)
            if s is not None:
                yield s
        reader.Close()

    def iter_samples(self, files: Optional[List[str]] = None) -> Iterator[Sample]:
        for uri in files or self.files:
            yield from self._iter_file(uri)

    # -- batching ---------------------------------------------------------

    def _batch_of(self, samples: List[Sample], max_keys: int):
        B = len(samples)
        y = np.asarray([s.label for s in samples], np.int32)
        w = np.asarray([s.weight for s in samples], np.float32)
        if not self.sparse:
            X = np.stack([s.dense for s in samples]).astype(np.float32)
            return {"X": X, "y": y, "weight": w}
        # int64: bsparse feature keys are raw 64-bit hashes (hashed FTRL);
        # dense-dimension models narrow to int32 themselves
        idx = np.zeros((B, max_keys), np.int64)
        val = np.zeros((B, max_keys), np.float32)
        touched = set()
        for i, s in enumerate(samples):
            k = min(len(s.keys), max_keys)
            if len(s.keys) > max_keys:
                with self._warn_lock:
                    if not self._truncation_warned:
                        Log.Error(
                            "[SampleReader] sample has %d features, "
                            "truncating to max_sparse_features=%d (raise "
                            "it in the config)",
                            len(s.keys), max_keys,
                        )
                        self._truncation_warned = True
            idx[i, :k] = s.keys[:k]
            val[i, :k] = s.values[:k]
            touched.update(s.keys[:k].tolist())
        return {
            "idx": idx,
            "val": val,
            "y": y,
            "weight": w,
            "keys": np.asarray(sorted(touched), np.int64),
        }

    def iter_batches(
        self,
        batch_size: Optional[int] = None,
        max_keys: Optional[int] = None,
        files: Optional[List[str]] = None,
        drop_remainder: bool = False,
    ) -> Iterator[dict]:
        """Foreground batching (deterministic, used by tests)."""
        batch_size = batch_size or self.config.minibatch_size
        if max_keys is None:
            max_keys = getattr(self.config, "max_sparse_features", 128)
        pending: List[Sample] = []
        for s in self.iter_samples(files):
            pending.append(s)
            if len(pending) == batch_size:
                yield self._batch_of(pending, max_keys)
                pending = []
        if pending and not drop_remainder:
            yield self._batch_of(pending, max_keys)

    def async_batches(self, **kw) -> Iterator[dict]:
        """Background-thread prefetch into a bounded queue
        (ref reader.h ring buffer; capacity = read_buffer_size samples)."""
        cap = max(2, self.config.read_buffer_size // max(self.config.minibatch_size, 1))
        q: queue.Queue = queue.Queue(maxsize=cap)
        DONE = object()

        stop = threading.Event()

        def produce():
            try:
                for b in self.iter_batches(**kw):
                    if stop.is_set():
                        return
                    q.put(b)
            finally:
                q.put(DONE)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    return
                yield item
        finally:
            # join the producer on EVERY exit path (mvlint R4): a consumer
            # abandoning this generator used to leak a live fill thread,
            # possibly blocked forever on a full queue — drain until it
            # lands its DONE and exits. BOUNDED: if the producer is stuck
            # inside iter_batches itself (I/O, not the queue), draining
            # cannot free it — give up after the deadline and abandon the
            # daemon thread (stop is set, it dies with the process)
            # rather than hang the consumer's generator close.
            stop.set()
            deadline = time.monotonic() + 5.0
            while t.is_alive() and time.monotonic() < deadline:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)


def make_reader(config) -> SampleReader:
    return SampleReader(config)
