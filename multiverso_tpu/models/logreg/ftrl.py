"""FTRL-proximal model on sparse data.

Reference semantics (ref: Applications/LogisticRegression/src/util/
ftrl_sparse_table.h:12-88, data_type.h:14-53, objective/ftrl_objective.h):
per-feature state (z, n); prediction uses the closed-form FTRL weight

    w_i = 0                                   if |z_i| <= lambda1
        = -(z_i - sign(z_i)*lambda1) /
          ((beta + sqrt(n_i))/alpha + lambda2)  otherwise

and the update for gradient g_i is

    sigma = (sqrt(n_i + g_i^2) - sqrt(n_i)) / alpha
    dz_i  = g_i - sigma * w_i ;  dn_i = g_i^2

pushed as (dz, dn) pairs that servers accumulate with ``+=`` (the reference's
FTRL gradient wire format — data_type.h:34-53).

TPU layout: the reference stores (z, n) in a hopscotch hash keyed by feature
id (ref: util/hopscotch_hash.h). Two stores, chosen by ``input_size``:

* ``input_size > 0`` — dense (input_size, 2) row-sharded MatrixTable: O(1)
  row addressing, MXU-friendly, sparse pushes touch only the batch's rows.
* ``input_size == 0`` — **unbounded hashed u64 keys** (the reference's CTR
  deployment shape: bsparse readers emit raw 64-bit feature hashes with no
  dimension bound — reader.h bsparse format, LogisticRegression/README.md:5).
  State lives in a KV table with ``val_dim=2``: a native batched hash index
  (native/kv_index.cpp — the hopscotch analog) resolves each minibatch's
  keys to dense HBM slots in one call; values grow by capacity doubling.

Documented deviation: within a minibatch, per-feature gradients are
aggregated before the state update (batched FTRL) instead of strictly
per-sample sequential application.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp

from multiverso_tpu.utils.log import CHECK

__all__ = ["FTRLModel"]


class FTRLModel:
    def __init__(self, config):
        self.config = config
        CHECK(config.sparse, "FTRL requires sparse input")
        CHECK(config.output_size == 1, "FTRL is binary (output_size=1)")
        self.F = int(config.input_size)
        self.hashed = self.F == 0  # unbounded u64 feature keys
        self.alpha = float(config.alpha)
        self.beta = float(config.beta)
        self.l1 = float(config.lambda1)
        self.l2 = float(config.lambda2)
        self.use_ps = bool(config.use_ps)
        self.kv = None
        self.collective_rounds = False  # set for hashed mode below
        self.collective_predict = False
        if self.hashed:
            from multiverso_tpu.runtime import runtime
            from multiverso_tpu.tables import KVTableOption, create_table

            CHECK(runtime().started,
                  "input_size=0 (hashed FTRL) requires MV_Init first")
            # multi-process: per-rank batches ride KVTable's lockstep
            # get_local/add_local rounds (the index stays identical on
            # every rank via the per-round key-union sync) — the
            # reference's hash-sharded FTRL deployment shape
            # (ftrl_sparse_table.h:12-88 over hopscotch servers)
            self.kv = create_table(KVTableOption(
                val_dim=2, init_capacity=1 << 16, name="ftrl_zn_kv",
                cache_local=False,  # unbounded keys: no host raw() mirror
            ))
            self.table = None
            self.collective_rounds = True   # every batch is a KV round
            self.collective_predict = True  # test gathers are rounds too
        elif self.use_ps:
            from multiverso_tpu.runtime import runtime
            from multiverso_tpu.tables import MatrixTableOption, create_table

            CHECK(runtime().started, "use_ps=true requires MV_Init first")
            # per-batch gathers/pushes are per-rank row sets; the lockstep
            # bucket protocol (see app._run_superbatch_ps) is not wired into
            # the LogReg batch loop yet — fail loudly instead of deadlocking
            CHECK(jax.process_count() == 1,
                  "dense FTRL use_ps is single-process for now: per-batch "
                  "row sets are not lockstep across ranks (WordEmbedding's "
                  "-use_ps implements the cross-process bucket protocol)")
            self.table = create_table(
                MatrixTableOption(num_row=self.F, num_col=2, name="ftrl_zn")
            )
        else:
            self.table = None
            self._zn = jnp.zeros((self.F, 2), jnp.float32)
        self._step = jax.jit(self._batch_update)
        self._predict = jax.jit(self._predict_impl)

    # -- math -------------------------------------------------------------

    def _w_from_zn(self, z, n):
        shrunk = jnp.sign(z) * self.l1 - z
        denom = (self.beta + jnp.sqrt(n)) / self.alpha + self.l2
        return jnp.where(jnp.abs(z) <= self.l1, 0.0, shrunk / denom)

    def _predict_impl(self, zn_rows, val):
        """zn_rows: (B, k, 2) gathered state; val: (B, k)."""
        w = self._w_from_zn(zn_rows[..., 0], zn_rows[..., 1])
        return jax.nn.sigmoid(jnp.sum(w * val, axis=1))

    def _batch_update(self, zn_rows, val, y):
        """Returns (loss, (dz, dn)) per (B, k) feature slot."""
        z, n = zn_rows[..., 0], zn_rows[..., 1]
        w = self._w_from_zn(z, n)
        p = jax.nn.sigmoid(jnp.sum(w * val, axis=1))  # (B,)
        target = (y == 1).astype(p.dtype)
        eps = 1e-12
        loss = -jnp.mean(target * jnp.log(p + eps) + (1 - target) * jnp.log(1 - p + eps))
        g = (p - target)[:, None] * val  # (B, k) per-slot gradient
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / self.alpha
        dz = g - sigma * w
        dn = g * g
        return loss, dz, dn

    # -- state access -----------------------------------------------------

    def _gather_rows(self, idx: np.ndarray) -> jnp.ndarray:
        flat = idx.reshape(-1)
        if self.kv is not None:
            if jax.process_count() > 1:  # lockstep per-rank round
                rows = self.kv.get_local(flat)
            else:
                rows = self.kv.get(flat)  # unknown keys read (0,0) = fresh
        elif self.table is not None:
            rows = self.table.get_rows(flat)
        else:
            rows = np.asarray(self._zn)[flat]
        return jnp.asarray(rows).reshape(idx.shape + (2,))

    def _push(self, idx: np.ndarray, dz: np.ndarray, dn: np.ndarray) -> None:
        flat = idx.reshape(-1)
        deltas = np.stack([np.asarray(dz).reshape(-1), np.asarray(dn).reshape(-1)], axis=1)
        if self.kv is not None:
            # batch padding slots carry exactly (0, 0): drop all-zero deltas
            # so the pad key (0) never materialises as a spurious KV entry
            # in hashed_weights()/saved models (+= 0 is a no-op anyway; a
            # genuine hash-0 feature with a real gradient still lands)
            live = deltas.any(axis=1)
            if not live.all():
                flat, deltas = flat[live], deltas[live]
            if jax.process_count() > 1:  # lockstep per-rank round
                self.kv.add_local(flat, deltas)
            elif len(flat):
                self.kv.add(flat, deltas)  # += accumulate, dups allowed
        elif self.table is not None:
            self.table.add_rows(flat, deltas)  # += accumulate, dups allowed
        else:
            self._zn = self._zn.at[flat].add(jnp.asarray(deltas))

    def join_round(self) -> bool:
        """Dry-rank participation in one cross-process training round
        (hashed multi-process only): joins the gather and push collectives
        with empty batches. Returns True if any rank still had data (the
        caller keeps joining), False when the round was globally dry."""
        CHECK(self.kv is not None and jax.process_count() > 1,
              "join_round is for hashed multi-process FTRL")
        e = np.zeros(0, np.int64)
        self.kv.get_local(e)  # collective #1 (mirrors train_batch's gather)
        live = self.kv.last_round_had_data()
        # collective #2 mirrors the push; when the round was globally dry
        # its bucket round is a no-op on every rank alike
        self.kv.add_local(e, np.zeros((0, 2), np.float32))
        return live

    def join_predict_round(self) -> bool:
        """Dry-rank participation in one gather-only round (the Test loop's
        analog of join_round). Returns False when globally dry."""
        CHECK(self.kv is not None and jax.process_count() > 1,
              "join_predict_round is for hashed multi-process FTRL")
        self.kv.get_local(np.zeros(0, np.int64))
        return self.kv.last_round_had_data()

    # -- model api --------------------------------------------------------

    def _idx(self, batch: Dict[str, Any]) -> np.ndarray:
        # hashed mode keeps raw 64-bit feature keys; dense mode indexes rows
        return np.asarray(batch["idx"], np.int64 if self.hashed else np.int32)

    def train_batch(self, batch: Dict[str, Any]) -> float:
        idx = self._idx(batch)
        val = jnp.asarray(batch["val"])
        zn_rows = self._gather_rows(idx)
        loss, dz, dn = self._step(zn_rows, val, jnp.asarray(batch["y"]))
        # zero-padding slots have val 0 -> g 0 -> dz/dn 0: safe to scatter
        self._push(idx, dz, dn)
        return float(loss)

    def predict(self, batch: Dict[str, Any]) -> np.ndarray:
        idx = self._idx(batch)
        zn_rows = self._gather_rows(idx)
        p = self._predict(zn_rows, jnp.asarray(batch["val"]))
        return np.asarray(p)[:, None]

    def test_batch(self, batch: Dict[str, Any]):
        scores = self.predict(batch)
        correct = int(
            (np.round(scores[:, 0]) == (np.asarray(batch["y"]) == 1)).sum()
        )
        return scores, correct

    def weights(self) -> np.ndarray:
        CHECK(not self.hashed,
              "hashed FTRL has no dense weight vector; use hashed_weights()")
        zn = self.table.get() if self.table is not None else np.asarray(self._zn)
        return np.asarray(self._w_from_zn(jnp.asarray(zn[:, 0]), jnp.asarray(zn[:, 1])))

    def hashed_weights(self):
        """(keys, w) for every feature seen so far (hashed mode)."""
        CHECK(self.hashed, "hashed_weights() requires input_size=0")
        keys, zn = self.kv.items()
        w = self._w_from_zn(jnp.asarray(zn[:, 0]), jnp.asarray(zn[:, 1]))
        return keys, np.asarray(w)

    def save(self, uri: str) -> None:
        import io as _pyio

        from multiverso_tpu.io.streams import as_stream

        if self.hashed:
            self.kv.store(uri)  # (keys, zn) pairs — no dimension bound
            return
        # non-hashed branches: dense PS is single-process by construction
        # (CHECK in __init__) and local _zn is rank-local state — a
        # rank-0-only write would silently drop other ranks' training
        CHECK(jax.process_count() == 1,
              "non-hashed FTRL state is process-local; multi-process "
              "checkpoints require the hashed KV store (input_size=0)")
        zn = self.table.get() if self.table is not None else np.asarray(self._zn)
        stream, owned = as_stream(uri, "w")
        buf = _pyio.BytesIO()
        np.savez(buf, zn=zn)
        stream.Write(buf.getvalue())
        if owned:
            stream.Close()

    def load(self, uri: str) -> None:
        import io as _pyio

        from multiverso_tpu.io.streams import as_stream

        if self.hashed:
            self.kv.load(uri)
            return
        stream, owned = as_stream(uri, "r")
        data = np.load(_pyio.BytesIO(stream.Read(-1)), allow_pickle=False)
        if owned:
            stream.Close()
        zn = data["zn"]
        CHECK(zn.shape == (self.F, 2), f"ftrl state shape {zn.shape} != {(self.F, 2)}")
        if self.table is not None:
            # one logical SPMD Add, issued by every process (the reference's
            # worker-0 gate — ps_model.cpp:113-168 — exists because its N
            # processes would each add a copy; gating here would deadlock
            # multihost collectives instead)
            self.table.add(zn - self.table.get())
            self.table.wait()
        else:
            self._zn = jnp.asarray(zn, jnp.float32)
