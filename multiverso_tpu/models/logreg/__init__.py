"""LogisticRegression application.

TPU-first rebuild of Applications/LogisticRegression (ref: SURVEY.md §2.7):
config-file driven LR/softmax/FTRL trainer; local mode (weights as device
arrays) or PS mode (weights in sharded tables with sync_frequency /
double-buffer pipelined pulls). The reference computes per-sample scalar
loops (ref: src/objective/objective.cpp); here objectives are batched jitted
functions — one MXU matmul per minibatch.
"""

from multiverso_tpu.models.logreg.config import Configure
from multiverso_tpu.models.logreg.logreg import LogReg

__all__ = ["Configure", "LogReg"]
