"""Batched objectives + regularizers.

Reference semantics (ref: Applications/LogisticRegression/src/objective/
objective.cpp, sigmoid_objective.h, softmax_objective.h; regular/l1_regular.h,
l2_regular.h), vectorised over a minibatch:

* **default (linear)**: predict = W·x per class; per-sample loss = squared
  error vs one-hot (ref: objective.cpp:50-61); dL/dlogits = predict − onehot
  (ref Diff: objective.cpp:42 "diff -= (label == i)").
* **sigmoid**: output_size 1; p = σ(w·x); loss = −log p (label 1) /
  −log(1−p) (label 0) (ref: objective.cpp:174-180); diff = p − label.
* **softmax**: stable softmax (max-subtracted — ref:
  objective.cpp:203-218); cross-entropy loss; diff = p − onehot.
* **regular**: gradient += coef·sign(w) (L1) or coef·w (L2)
  (ref: l1_regular.h/l2_regular.h Calculate), none by default.

Gradients are w.r.t. the (output_size, input_size) weight matrix and are
averaged over the minibatch. Dense input X is (B, F); sparse input is
(idx (B,k) int32 padded with 0, val (B,k) — val 0 on padding).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from multiverso_tpu.utils.log import Log

__all__ = ["make_objective", "Objective"]


def _regular_grad(regular_type: str, coef: float):
    if regular_type in ("default", "", "none", None):
        return lambda w: jnp.zeros_like(w)
    if regular_type.lower() == "l1":
        return lambda w: coef * jnp.sign(w)
    if regular_type.lower() == "l2":
        return lambda w: coef * w
    Log.Fatal("unknown regular_type %r", regular_type)


class Objective:
    """Batched objective: ``loss_grad(W, X, y)`` and ``predict(W, X)``.

    ``W``: (C, F) weights. Dense ``X``: (B, F). Sparse: pass
    ``X=(idx, val)``. ``y``: (B,) int labels.
    """

    def __init__(self, objective_type: str, output_size: int,
                 regular_type: str = "default", regular_coef: float = 0.0):
        self.objective_type = objective_type
        self.output_size = output_size
        self._reg = _regular_grad(regular_type, regular_coef)
        if objective_type not in ("default", "sigmoid", "softmax"):
            Log.Fatal("unknown objective_type %r", objective_type)

    # -- shared pieces ----------------------------------------------------

    def _logits(self, W, X):
        if isinstance(X, tuple):
            idx, val = X  # (B,k) feature ids, (B,k) values (0 on padding)
            cols = W[:, idx]  # (C, B, k) gather
            return jnp.einsum("cbk,bk->bc", cols, val)
        return X @ W.T  # (B, C)

    def _diff_and_loss(self, logits, y):
        C = self.output_size
        onehot = jax.nn.one_hot(y, C, dtype=logits.dtype) if C > 1 else None
        if self.objective_type == "default":
            target = onehot if C > 1 else (y == 1).astype(logits.dtype)[:, None]
            diff = logits - target
            per = jnp.sum(diff**2, axis=1)
            if C > 1:
                per = per / C  # ref: objective.cpp:60 divides by output_size
            return diff, per
        if self.objective_type == "sigmoid":
            p = jax.nn.sigmoid(logits[:, 0])
            target = (y == 1).astype(p.dtype)
            eps = 1e-12
            per = -(target * jnp.log(p + eps) + (1 - target) * jnp.log(1 - p + eps))
            return (p - target)[:, None], per
        # softmax
        p = jax.nn.softmax(logits, axis=1)
        eps = 1e-12
        per = -jnp.log(p[jnp.arange(p.shape[0]), y] + eps)
        return p - onehot, per

    # -- public api -------------------------------------------------------

    def loss_grad(self, W, X, y) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Returns (mean loss, dL/dW averaged over batch + regularization)."""
        logits = self._logits(W, X)
        diff, per = self._diff_and_loss(logits, y)
        B = diff.shape[0]
        if isinstance(X, tuple):
            idx, val = X
            contrib = diff[:, None, :] * val[..., None]  # (B, k, C)
            grad = jnp.zeros_like(W.T).at[idx.reshape(-1)].add(
                contrib.reshape(-1, diff.shape[1])
            ).T / B
        else:
            grad = diff.T @ X / B  # (C, F)
        return jnp.mean(per), grad + self._reg(W)

    def predict(self, W, X) -> jnp.ndarray:
        """Class scores/probabilities (ref Predict — ref: objective.cpp:114-120)."""
        logits = self._logits(W, X)
        if self.objective_type == "sigmoid":
            return jax.nn.sigmoid(logits)
        if self.objective_type == "softmax":
            return jax.nn.softmax(logits, axis=1)
        return logits

    def correct(self, y, scores) -> jnp.ndarray:
        """Per-sample correctness (ref Correct — ref: objective.cpp:123-140):
        output_size 1 rounds the score; otherwise argmax."""
        if self.output_size == 1:
            return (jnp.round(scores[:, 0]) == (y == 1)).astype(jnp.int32)
        return (jnp.argmax(scores, axis=1) == y).astype(jnp.int32)


def make_objective(config) -> Objective:
    """Factory (ref Objective::Get)."""
    otype = config.objective_type
    if otype == "ftrl":
        # FTRL prediction/gradient lives in the FTRL model (ftrl.py)
        otype = "sigmoid"
    return Objective(
        otype,
        config.output_size,
        regular_type=config.regular_type,
        regular_coef=config.regular_coef,
    )
