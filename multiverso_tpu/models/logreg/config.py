"""Config-file parser — reference ``logreg::Configure`` parity
(ref: Applications/LogisticRegression/src/configure.h:9-103,
configure.cpp): ``key=value`` lines, same option names and defaults; unknown
keys are ignored with a log line; ``#`` comments allowed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from multiverso_tpu.io.streams import TextReader
from multiverso_tpu.utils.log import CHECK, Log

__all__ = ["Configure"]


@dataclasses.dataclass
class Configure:
    # dimensions (ref: configure.h:20-22 — must be provided)
    input_size: int = 0
    output_size: int = 0
    sparse: bool = False

    train_epoch: int = 1
    minibatch_size: int = 20
    read_buffer_size: int = 2048
    show_time_per_sample: int = 10000
    # minibatches scanned per device dispatch (local models; superbatching)
    steps_per_call: int = 8

    regular_coef: float = 0.0005
    learning_rate: float = 0.8
    learning_rate_coef: float = 1e6

    # FTRL (ref: configure.h:45-49)
    alpha: float = 0.005
    beta: float = 1.0
    lambda1: float = 5.0
    lambda2: float = 0.002

    init_model_file: str = ""
    train_file: str = "train.data"
    reader_type: str = "default"  # default | weight | bsparse
    test_file: str = ""
    output_model_file: str = "logreg.model"
    output_file: str = "logreg.output"

    use_ps: bool = False
    pipeline: bool = True
    sync_frequency: int = 1

    # fault tolerance (resilience subsystem): crash-consistent training
    # checkpoints + elastic resume. checkpoint_every_n counts dispatch
    # groups (steps_per_call minibatches each); 0 disables auto-saves.
    checkpoint_dir: str = ""
    checkpoint_every_n: int = 0
    checkpoint_retain: int = 3
    resume: bool = True

    # max nonzero features per sparse sample (fixed TPU batch shape); samples
    # with more features are truncated with a logged warning
    max_sparse_features: int = 128

    updater_type: str = "default"  # default | sgd | ftrl
    objective_type: str = "default"  # default | ftrl | sigmoid | softmax
    regular_type: str = "default"  # default | L1 | L2

    @classmethod
    def from_file(cls, config_file: str) -> "Configure":
        cfg = cls()
        fields = {f.name: f for f in dataclasses.fields(cls)}
        reader = TextReader(config_file)
        for line in reader:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            key, sep, value = line.partition("=")
            if not sep:
                continue
            key, value = key.strip(), value.strip()
            f = fields.get(key)
            if f is None:
                Log.Info("[Configure] unknown key %r ignored", key)
                continue
            if f.type in ("int", int):
                setattr(cfg, key, int(value))
            elif f.type in ("float", float):
                setattr(cfg, key, float(value))
            elif f.type in ("bool", bool):
                setattr(cfg, key, value.lower() in ("true", "1", "yes"))
            else:
                setattr(cfg, key, value)
        reader.Close()
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if ((self.objective_type == "ftrl" or self.updater_type == "ftrl")
                and self.sparse):  # matches Model.Get's FTRL selection
            # input_size=0 => unbounded hashed u64 feature keys: FTRL state
            # lives in the hash-indexed KV store (ref: the reference's FTRL
            # hopscotch table needs no dimension bound either —
            # util/ftrl_sparse_table.h:12-88, hopscotch_hash.h)
            CHECK(self.input_size >= 0, "input_size must be >= 0")
        else:
            CHECK(self.input_size > 0, "config must provide input_size > 0")
        CHECK(self.output_size > 0, "config must provide output_size > 0")
        if self.objective_type == "sigmoid":
            CHECK(self.output_size == 1, "sigmoid objective requires output_size=1")
        if self.objective_type == "softmax":
            CHECK(self.output_size >= 2, "softmax objective requires output_size>=2")
        if self.objective_type == "ftrl":
            CHECK(self.output_size == 1, "ftrl objective requires output_size=1")
            CHECK(self.sparse, "ftrl objective requires sparse input")
