"""CLI entry — reference main.cpp parity
(ref: Applications/LogisticRegression/src/main.cpp: ``logreg config_file``).

Usage: python -m multiverso_tpu.models.logreg <config_file> [MV flags]
"""

import sys

import multiverso_tpu as mv
from multiverso_tpu.models.logreg import LogReg
from multiverso_tpu.utils.log import Log


def main(argv):
    rest = mv.MV_Init(argv)
    args = [a for a in rest[1:] if not a.startswith("-")]
    if not args:
        Log.Error("usage: python -m multiverso_tpu.models.logreg <config_file>")
        return 1
    lr = LogReg(args[0])
    lr.Train()  # runs a per-epoch Test when test_file is configured
    mv.MV_ShutDown()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
