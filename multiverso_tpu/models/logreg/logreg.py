"""LogReg driver — the reference ``LogReg<T>`` train/test/save loop
(ref: Applications/LogisticRegression/src/logreg.h/.cpp:41-173):
config-driven; async reader feeds minibatches; per-epoch test when
``test_file`` is set; predictions written to ``output_file``; model saved to
``output_model_file``; progress logged every ``show_time_per_sample``
samples with samples/sec (ref: logreg.cpp:72-77).
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from multiverso_tpu.models.logreg.config import Configure
from multiverso_tpu.models.logreg.model import Model
from multiverso_tpu.models.logreg.reader import make_reader
from multiverso_tpu.utils.log import Log
from multiverso_tpu.utils.timer import Timer

__all__ = ["LogReg"]


class LogReg:
    def __init__(self, config: Union[str, Configure]):
        if isinstance(config, str):
            config = Configure.from_file(config)
        config.validate()
        self.config = config
        self.model = Model.Get(config)
        self.reader = make_reader(config)
        if config.init_model_file:
            self.model.load(config.init_model_file)

    def Train(self) -> float:
        """Run ``train_epoch`` epochs; returns the final epoch's mean loss."""
        from multiverso_tpu.analysis.guards import register_training_thread

        # this thread owns the training loop and its PS table pulls/pushes
        # (thread-identity guard, mvlint R1)
        register_training_thread()
        cfg = self.config
        Model.check_trainable(cfg, self.model)  # un-checkpointable? fail NOW
        last_epoch_loss = 0.0
        # superbatch grouping: scan S same-shape minibatches per dispatch
        # when the model supports it (local models; PS steps singly)
        S = max(1, int(cfg.steps_per_call))
        can_fuse = hasattr(self.model, "train_superbatch") and S > 1

        def flush(group):
            if len(group) > 1 and can_fuse and all(
                g["y"].shape == group[0]["y"].shape for g in group
            ):
                return self.model.train_superbatch(group), sum(
                    len(g["y"]) for g in group
                )
            total = 0
            loss_sum = 0.0
            for g in group:
                loss_sum = loss_sum + self.model.train_batch(g)
                total += len(g["y"])
            return loss_sum / len(group), total

        # elastic resume (resilience subsystem): restore the model + lr
        # schedule + data cursor from the latest valid checkpoint, replay
        # the reader to the cursor, continue. Saves are synchronous (the
        # model dump must see the exact post-step weights, and logreg
        # models are small).
        ck, start_epoch, resume_skip, gstep, restarts = (None, 0, 0, 0, 0)
        if cfg.checkpoint_dir:
            import os as _os

            import jax

            from multiverso_tpu.resilience import (
                AutoCheckpointer,
                latest_valid,
                load_checkpoint,
            )
            from multiverso_tpu.resilience import stats as _rstats
            from multiverso_tpu.utils.log import CHECK

            CHECK(jax.process_count() == 1,
                  "checkpoint_dir requires a single process (multi-process "
                  "logreg checkpoints go through the PS tables)")
            if cfg.resume:
                path = latest_valid(cfg.checkpoint_dir)
                if path is not None:
                    _arrays, meta = load_checkpoint(path)
                    self.model.load(_os.path.join(path, "model.bin"))
                    if hasattr(self.model, "schedule"):
                        self.model.schedule.count = int(meta.get("lr_count", 0))
                    start_epoch = int(meta["epoch"])
                    resume_skip = int(meta["batches_in_epoch"])
                    gstep = int(meta["step"])
                    restarts = int(meta.get("restarts", 0)) + 1
                    _rstats.note_restart(restarts)
                    Log.Info(
                        "[LogReg] resumed from %s: step %d, epoch %d, "
                        "batch %d, restart #%d",
                        path, gstep, start_epoch, resume_skip, restarts,
                    )
            ck = AutoCheckpointer(
                cfg.checkpoint_dir,
                every_n_steps=cfg.checkpoint_every_n,
                retain=cfg.checkpoint_retain,
                async_=False,
            )
        from multiverso_tpu.resilience import chaos, save_checkpoint

        def on_step(epoch, batches_in_epoch, n_flushed):
            """Post-flush fault points: policy checkpoint, chaos kill."""
            nonlocal gstep
            gstep += 1
            if ck is not None:
                step, cursor = gstep, batches_in_epoch
                lr_count = (
                    int(self.model.schedule.count)
                    if hasattr(self.model, "schedule") else 0
                )
                ck.maybe_save(
                    step,
                    lambda: lambda: save_checkpoint(
                        ck.root, step,
                        write_payload=lambda d: self.model.save(
                            _join(d, "model.bin")
                        ),
                        meta={
                            "epoch": epoch,
                            "batches_in_epoch": cursor,
                            "step": step,
                            "lr_count": lr_count,
                            "restarts": restarts,
                        },
                    ),
                )
            chaos.maybe_kill(gstep)

        from os.path import join as _join

        for epoch in range(start_epoch, cfg.train_epoch):
            timer = Timer()
            seen, since_log = 0, 0
            # loss stays a device value between log points (forcing it per
            # batch would serialise training on the dispatch round trip);
            # accumulate sums and sync once per show_time_per_sample window
            ep_sum, ep_n, win_sum, win_n = 0.0, 0, 0.0, 0
            group: list = []
            skip = resume_skip if epoch == start_epoch else 0
            skipped = 0
            batches_in_epoch = skip

            for batch in self.reader.async_batches(batch_size=cfg.minibatch_size):
                if skipped < skip:
                    # resume cursor: these minibatches were trained before
                    # the crash; replay the (deterministic) reader past them
                    skipped += 1
                    continue
                group.append(batch)
                if len(group) < S:
                    continue
                n_flushed = len(group)
                loss, n_in_group = flush(group)
                group = []
                batches_in_epoch += n_flushed
                on_step(epoch, batches_in_epoch, n_flushed)
                win_sum = win_sum + loss
                win_n += 1
                seen += n_in_group
                since_log += n_in_group
                if since_log >= cfg.show_time_per_sample:
                    rate = seen / max(timer.elapsed_s(), 1e-9)
                    w = float(win_sum)  # the one device sync per log window
                    Log.Info(
                        "[LogReg] epoch %d: %d samples, %.0f samples/s, loss %.5f",
                        epoch, seen, rate, w / win_n,
                    )
                    ep_sum, ep_n = ep_sum + w, ep_n + win_n
                    win_sum, win_n = 0.0, 0
                    since_log = 0
            if group:  # epoch tail: whatever is left of the last group
                n_flushed = len(group)
                loss, n_in_group = flush(group)
                batches_in_epoch += n_flushed
                on_step(epoch, batches_in_epoch, n_flushed)
                win_sum = win_sum + loss
                win_n += 1
                seen += n_in_group
            # multi-process collective-round models (hashed FTRL, sparse
            # PSModel): every train_batch is a lockstep round; a rank whose
            # reader drained early keeps joining rounds with empty batches
            # until ALL ranks are done (mirrors the WordEmbedding PS
            # dry-rank protocol)
            if getattr(self.model, "collective_rounds", False):
                import jax

                if jax.process_count() > 1:
                    while self.model.join_round():
                        pass
            if win_n:
                ep_sum, ep_n = ep_sum + float(win_sum), ep_n + win_n
            last_epoch_loss = ep_sum / ep_n if ep_n else 0.0
            Log.Info(
                "[LogReg] epoch %d done: %d samples in %.2fs, mean loss %.5f",
                epoch, seen, timer.elapsed_s(), last_epoch_loss,
            )
            if cfg.test_file:
                self.Test()
        if cfg.output_model_file:
            self.model.save(cfg.output_model_file)
        return last_epoch_loss

    def Test(self, output_file: Optional[str] = None) -> float:
        """Accuracy over ``test_file``; writes per-sample scores to
        ``output_file`` (ref: logreg.cpp:121-173)."""
        cfg = self.config
        files = [f for f in str(cfg.test_file).split(";") if f]
        total, correct = 0, 0
        out_lines = []
        for batch in self.reader.iter_batches(
            batch_size=cfg.minibatch_size, files=files
        ):
            scores, c = self.model.test_batch(batch)
            correct += c
            total += len(batch["y"])
            for row in np.asarray(scores):
                out_lines.append(" ".join(f"{v:.6f}" for v in np.atleast_1d(row)))
        # multi-process: models whose predictions gather through tables
        # drain with gather-only rounds until every rank's shard is done
        if getattr(self.model, "collective_predict", False):
            import jax

            if jax.process_count() > 1:
                while self.model.join_predict_round():
                    pass
        acc = correct / max(total, 1)
        Log.Info("[LogReg] test: %d/%d correct (%.4f)", correct, total, acc)
        path = output_file or cfg.output_file
        import jax

        if path and jax.process_count() > 1:
            # each rank scored only its own test shard: write per-rank
            # files (the reference's per-node output convention) instead of
            # racing every rank over one path
            path = f"{path}.rank{jax.process_index()}"
        if path:
            from multiverso_tpu.io.streams import as_stream

            stream, owned = as_stream(path, "w")
            stream.Write(("\n".join(out_lines) + "\n").encode())
            if owned:
                stream.Close()
        return acc

    # reference-style aliases
    SaveModel = lambda self, uri=None: self.model.save(uri or self.config.output_model_file)
    LoadModel = lambda self, uri=None: self.model.load(uri or self.config.output_model_file)
