"""LogReg models: local and parameter-server mode.

Reference semantics (ref: Applications/LogisticRegression/src/model/model.h:
20-73, model.cpp; ps_model.h/.cpp):

* ``Model::Get(config)`` factory → local model, or PS model when ``use_ps``
  (ref: model.h:66-73); FTRL gets its own model (ftrl.py).
* app-level updater scales the *delta before push* (ref: src/updater/
  updater.cpp:52-70): ``default`` pushes the raw gradient, ``sgd`` multiplies
  by a decaying learning rate ``lr = max(1e-3, lr0 − update_count /
  (lr_coef · minibatch))`` (ref: updater.cpp:67-69).
* PS mode: weights live in a table; push = AddAsync(delta), pull every
  ``sync_frequency`` minibatches; ``pipeline`` overlaps the pull with compute
  via a double buffer (ref: ps_model.cpp:232-271 GetPipelineTable).

TPU layout: weights are stored **feature-major** — a (input_size,
output_size) MatrixTable — so sparse minibatches update only the touched
feature rows (= the reference's sparse-key pushes), while the jitted step
uses the transposed (C, F) view.
"""

from __future__ import annotations

import io as _pyio
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from multiverso_tpu.models.logreg.objective import make_objective
from multiverso_tpu.utils.log import CHECK, Log

__all__ = ["Model", "LocalModel", "PSModel"]


class _LrSchedule:
    """ref: updater.cpp:45-70."""

    def __init__(self, config):
        self.kind = config.updater_type
        CHECK(self.kind in ("default", "sgd", "ftrl"), f"bad updater_type {self.kind!r}")
        self.lr0 = float(config.learning_rate)
        self.coef = float(config.learning_rate_coef)
        self.minibatch = int(config.minibatch_size)
        self.count = 0

    def next_lr(self) -> float:
        if self.kind == "default":
            return 1.0  # raw delta push (ref "simple minus updater")
        self.count += 1
        return max(1e-3, self.lr0 - self.count / (self.coef * self.minibatch))


class Model:
    """Factory (ref: model.h:66-73)."""

    @staticmethod
    def Get(config):
        if config.updater_type == "ftrl" or config.objective_type == "ftrl":
            from multiverso_tpu.models.logreg.ftrl import FTRLModel

            return FTRLModel(config)
        return PSModel(config) if config.use_ps else LocalModel(config)

    @staticmethod
    def check_trainable(config, model) -> None:
        """Fail FAST — at TRAIN start, not after the epochs — on configs
        whose end-of-training checkpoint would be rejected (rank-local
        state cannot produce a meaningful multi-process checkpoint). Not
        enforced at construction: inference-only multi-process jobs (Test
        with init_model_file) never save and must keep working with the
        default non-empty output_model_file."""
        if jax.process_count() == 1 or not config.output_model_file:
            return
        from multiverso_tpu.models.logreg.ftrl import FTRLModel

        if isinstance(model, FTRLModel):
            CHECK(model.hashed,
                  "multi-process non-hashed FTRL cannot write "
                  "output_model_file (state is process-local); use "
                  "input_size=0 (hashed KV store) or drop the checkpoint")
            return
        CHECK(isinstance(model, PSModel),
              "multi-process non-PS LogReg cannot write output_model_file "
              "(each rank's weights are rank-local); use use_ps=true")


class LocalModel:
    """Weights as device arrays; one jitted step per minibatch."""

    # multi-process lockstep-round capabilities (the LogReg driver drains
    # ranks through join_round/join_predict_round only when set)
    collective_rounds = False
    collective_predict = False

    def __init__(self, config):
        self.config = config
        self.objective = make_objective(config)
        self.C, self.F = int(config.output_size), int(config.input_size)
        self.W = jnp.zeros((self.C, self.F), jnp.float32)
        self.schedule = _LrSchedule(config)
        self._step_dense = jax.jit(self._grad_dense)
        self._step_sparse = jax.jit(self._grad_sparse)
        # fused SGD steps: weights donated, loss returned on device — the
        # local training loop never syncs per batch (a host read-back per
        # minibatch serialises everything on the dispatch round trip)
        self._fused_dense = jax.jit(self._sgd_dense, donate_argnums=(0,))
        self._fused_sparse = jax.jit(self._sgd_sparse, donate_argnums=(0,))
        self._fused_dense_scan = jax.jit(self._scan_dense, donate_argnums=(0,))
        self._fused_sparse_scan = jax.jit(self._scan_sparse, donate_argnums=(0,))

    # gradient programs (shared with PSModel)
    def _grad_dense(self, W, X, y):
        return self.objective.loss_grad(W, X, y)

    def _grad_sparse(self, W, idx, val, y):
        return self.objective.loss_grad(W, (idx, val), y)

    def _sgd_dense(self, W, X, y, lr):
        loss, grad = self._grad_dense(W, X, y)
        return W - lr * grad, loss

    def _sgd_sparse(self, W, idx, val, y, lr):
        loss, grad = self._grad_sparse(W, idx, val, y)
        return W - lr * grad, loss

    def _gradient(self, batch: Dict[str, Any]):
        if "X" in batch:
            return self._step_dense(self.W, jnp.asarray(batch["X"]), jnp.asarray(batch["y"]))
        return self._step_sparse(
            self.W,
            jnp.asarray(batch["idx"]),
            jnp.asarray(batch["val"]),
            jnp.asarray(batch["y"]),
        )

    def train_superbatch(self, batches):
        """Scan over identically-shaped minibatches in ONE dispatch
        (superbatching — amortizes dispatch latency exactly like the
        WordEmbedding steps_per_call path). Returns the device mean loss.
        PS-mode models override: their per-batch delta push is the PS
        protocol and cannot be fused."""
        lrs = jnp.asarray(
            [self.schedule.next_lr() for _ in batches], jnp.float32
        )
        if "X" in batches[0]:
            Xs = jnp.asarray(np.stack([b["X"] for b in batches]))
            ys = jnp.asarray(np.stack([b["y"] for b in batches]))
            self.W, loss = self._fused_dense_scan(self.W, Xs, ys, lrs)
        else:
            idx = jnp.asarray(np.stack([b["idx"] for b in batches]))
            val = jnp.asarray(np.stack([b["val"] for b in batches]))
            ys = jnp.asarray(np.stack([b["y"] for b in batches]))
            self.W, loss = self._fused_sparse_scan(self.W, idx, val, ys, lrs)
        return loss

    def _scan_dense(self, W, Xs, ys, lrs):
        def body(W, xs):
            X, y, lr = xs
            loss, grad = self._grad_dense(W, X, y)
            return W - lr * grad, loss

        W, losses = jax.lax.scan(body, W, (Xs, ys, lrs))
        return W, jnp.mean(losses)

    def _scan_sparse(self, W, idx, val, ys, lrs):
        def body(W, xs):
            i, v, y, lr = xs
            loss, grad = self._grad_sparse(W, i, v, y)
            return W - lr * grad, loss

        W, losses = jax.lax.scan(body, W, (idx, val, ys, lrs))
        return W, jnp.mean(losses)

    def train_batch(self, batch: Dict[str, Any]):
        """One fused SGD step; returns the *device* loss scalar — callers
        force it only at log points (ref: logreg.cpp's show_time cadence)."""
        lr = jnp.float32(self.schedule.next_lr())
        if "X" in batch:
            self.W, loss = self._fused_dense(
                self.W, jnp.asarray(batch["X"]), jnp.asarray(batch["y"]), lr
            )
        else:
            self.W, loss = self._fused_sparse(
                self.W,
                jnp.asarray(batch["idx"]),
                jnp.asarray(batch["val"]),
                jnp.asarray(batch["y"]),
                lr,
            )
        return loss

    def predict(self, batch: Dict[str, Any]) -> np.ndarray:
        X = batch["X"] if "X" in batch else (jnp.asarray(batch["idx"]), jnp.asarray(batch["val"]))
        return np.asarray(self.objective.predict(self.W, X))

    def test_batch(self, batch: Dict[str, Any]):
        scores = self.predict(batch)
        correct = np.asarray(
            self.objective.correct(jnp.asarray(batch["y"]), jnp.asarray(scores))
        )
        return scores, int(correct.sum())

    # -- persistence (binary model dump — ref model.cpp Store) -------------

    def weights(self) -> np.ndarray:
        return np.asarray(self.W)

    def save(self, uri: str) -> None:
        # non-PS weights are RANK-LOCAL state: a rank-0-only write would
        # silently discard every other rank's training — fail loudly
        # (PSModel overrides: its pulled weights are globally agreed)
        CHECK(jax.process_count() == 1,
              "LocalModel.save under multi-process would keep only rank "
              "0's independently-trained weights; use use_ps=true for "
              "cross-process training with checkpoints")
        self._write_weights(uri)

    def _write_weights(self, uri: str) -> None:
        from multiverso_tpu.io.streams import as_stream

        stream, owned = as_stream(uri, "w")
        buf = _pyio.BytesIO()
        np.savez(buf, W=self.weights())
        stream.Write(buf.getvalue())
        if owned:
            stream.Close()

    def load(self, uri: str) -> None:
        from multiverso_tpu.io.streams import as_stream

        stream, owned = as_stream(uri, "r")
        data = np.load(_pyio.BytesIO(stream.Read(-1)), allow_pickle=False)
        if owned:
            stream.Close()
        W = data["W"]
        CHECK(W.shape == (self.C, self.F), f"model shape {W.shape} != {(self.C, self.F)}")
        self.set_weights(W)

    def set_weights(self, W: np.ndarray) -> None:
        self.W = jnp.asarray(W, jnp.float32)


class PSModel(LocalModel):
    """Weights in a sharded table; delta push per minibatch, pull every
    ``sync_frequency`` batches, optional pipelined (double-buffered) pull.

    Multi-process (sparse input): every minibatch is a lockstep round —
    ranks agree on a padded key bucket and push their deltas through one
    stacked SPMD scatter (``add_rows_local``); the pull cadence counts
    ROUNDS (identical on every rank, so the collective ``get`` stays
    lockstep), and drained ranks keep joining with zero deltas
    (``join_round``). The reference's N-worker deployment
    (ps_model.cpp:12-67). Dense-input multi-process is rejected loudly
    (per-rank full-delta adds need a per-client reduction path the sparse
    protocol already provides)."""

    def __init__(self, config):
        super().__init__(config)
        from multiverso_tpu.runtime import runtime
        from multiverso_tpu.tables import MatrixTableOption, create_table

        CHECK(runtime().started, "use_ps=true requires MV_Init first")
        # feature-major table: rows = features, cols = classes
        self.table = create_table(
            MatrixTableOption(num_row=self.F, num_col=self.C, name="logreg_weights")
        )
        self._since_pull = 0
        self._pipeline = bool(config.pipeline)
        self.collective_rounds = jax.process_count() > 1

    def _pull(self) -> None:
        # pipelined pulls serve bounded-stale state in async mode and exact
        # state under -sync=true (BSP forbids stale reads); the mode rule
        # lives in one place — DenseTable.get_pipelined
        table_fm = (
            self.table.get_pipelined() if self._pipeline else self.table.get()
        )
        self.W = jnp.asarray(table_fm.T)  # class-major view for the step

    def train_superbatch(self, batches):
        """PS mode cannot fuse across minibatches: each batch's delta push
        through the table IS the protocol (ref: ps_model.cpp per-batch
        AddAsync). Steps singly."""
        losses = [self.train_batch(b) for b in batches]
        return float(np.mean([float(l) for l in losses]))

    def _tick_pull(self) -> None:
        """Round-counted pull cadence (ONE definition: ranks' collective
        counts diverge silently if this logic forks)."""
        self._since_pull += 1
        if self._since_pull >= self.config.sync_frequency:
            self._pull()
            self._since_pull = 0

    def _push_round(self, keys: np.ndarray, delta_rows: np.ndarray) -> bool:
        """One lockstep push (multi-process); the caller runs its local
        apply and then _tick_pull, keeping the single-process order
        push -> local apply -> pull (pulling first would hand back a table
        that already contains this batch's delta and the local apply would
        then double-step it). Returns False when the round was globally
        dry (nothing pushed anywhere)."""
        any_data, bucket = self.table.round_bucket(len(keys))
        if not any_data:
            return False
        ids = np.zeros(bucket, np.int64)
        ids[: len(keys)] = keys
        deltas = np.zeros((bucket, self.C), np.float32)
        deltas[: len(keys)] = delta_rows
        self.table.add_rows_local(ids, deltas)
        return True

    def join_round(self) -> bool:
        """Drained-rank participation in one training round. Returns False
        when the round was globally dry (every rank finished)."""
        if not self._push_round(
            np.zeros(0, np.int64), np.zeros((0, self.C), np.float32)
        ):
            return False
        self._tick_pull()
        return True

    def train_batch(self, batch: Dict[str, Any]) -> float:
        loss, grad = self._gradient(batch)  # grad: (C, F)
        lr = self.schedule.next_lr()
        delta_fm = np.asarray(lr * grad).T  # (F, C) feature-major
        if self.collective_rounds:
            # gate on key PRESENCE only: an EMPTY key set is a legitimate
            # round (n=0 push, same as join_round) — crashing one rank for
            # it would hang the others in the allgather. Dense X batches
            # (identical shape everywhere, but per-rank full deltas) stay
            # single-process.
            CHECK("keys" in batch,
                  "multi-process PS LogReg requires sparse batches (the "
                  "lockstep round protocol pushes key buckets); dense X "
                  "batches are single-process")
            keys = np.asarray(batch["keys"], np.int64)
            pushed = self._push_round(keys, -delta_fm[keys])
            # the local apply happens whether or not the round pushed: a
            # globally dry round (every rank's key set empty) still carried
            # this rank's gradient (e.g. a regularizer term) — dropping it
            # silently would diverge from the single-process path. Only the
            # table push and the round-counted pull are collective.
            self.W = self.W - lr * grad
            if pushed:
                self._tick_pull()
            return float(loss)
        if "keys" in batch and len(batch["keys"]) and len(batch["keys"]) < self.F:
            keys = np.asarray(batch["keys"], np.int32)
            self.table.add_rows(keys, -delta_fm[keys])  # sparse push
        else:
            self.table.add(-delta_fm)
        # apply locally too so we keep training between pulls
        self.W = self.W - lr * grad
        self._tick_pull()
        return float(loss)

    def save(self, uri: str) -> None:
        # ref ps_model Store: pull whole model first (ps_model.cpp:96-111).
        # The pull is collective (every rank joins); the pulled weights are
        # identical everywhere, so ONE rank writes the file.
        self.W = jnp.asarray(self.table.get().T)
        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        self._write_weights(uri)

    def load(self, uri: str) -> None:
        """Load-as-Add (ref: ps_model.cpp:113-168). The reference gates the
        injection on worker 0 because each of its N processes issues its own
        Add; here the Add is ONE logical SPMD program, issued identically by
        every process (multihost included — gating any process on rank would
        deadlock the collectives), so it lands exactly once by construction."""
        super().load(uri)
        current = self.table.get()
        self.table.add(np.asarray(self.W).T - current)
        self.table.wait()
        self.W = jnp.asarray(self.table.get().T)
