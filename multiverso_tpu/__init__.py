"""multiverso_tpu — a TPU-native parameter-server framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of Multiverso
(github.com/StillKeepTry/Multiverso, mounted read-only at /root/reference):
sharded parameter tables (array / matrix / sparse matrix / KV), asynchronous
and BSP-synchronous Get/Add semantics, server-side optimizers (SGD / momentum /
AdaGrad / FTRL), model-averaging allreduce, checkpointing, Python table
handlers and framework param-manager hooks, the two reference
applications (WordEmbedding, LogisticRegression), and an online serving
subsystem (``multiverso_tpu.serving``: dynamic-batching ``TableServer``
with hot-swap weights over frozen table snapshots, deployable as a
replicated self-healing fleet — HTTP data plane, per-replica snapshot
rollout from trainer checkpoints, per-tenant admission control, and a
failover client; see ``serving.replica`` / ``deploy/serving_fleet.py``).

Architecture (see SURVEY.md §7): tables are sharded ``jax.Array``s in HBM over
a device mesh; Get/Add lower to XLA collectives over ICI/DCN; updaters are
jitted/Pallas kernels on local shards; the reference's actor/MPI machinery has
no equivalent code because the SPMD model subsumes it.
"""

from multiverso_tpu.api import (
    MV_Aggregate,
    MV_Barrier,
    MV_CreateTable,
    MV_Init,
    MV_NetBind,
    MV_NetConnect,
    MV_NumServers,
    MV_NumWorkers,
    MV_Rank,
    MV_ServerId,
    MV_SetFlag,
    MV_ShutDown,
    MV_Size,
    MV_WorkerId,
)
from multiverso_tpu.runtime import Runtime, runtime

__version__ = "0.1.0"

__all__ = [
    "MV_Aggregate",
    "MV_Barrier",
    "MV_CreateTable",
    "MV_Init",
    "MV_NetBind",
    "MV_NetConnect",
    "MV_NumServers",
    "MV_NumWorkers",
    "MV_Rank",
    "MV_ServerId",
    "MV_SetFlag",
    "MV_ShutDown",
    "MV_Size",
    "MV_WorkerId",
    "Runtime",
    "runtime",
    "__version__",
]
