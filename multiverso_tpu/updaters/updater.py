"""Updater implementations + AddOption/GetOption hyperparameter records.

Semantics ported from the reference (behavior, not code):

* ``AddOption`` — 5-slot record {worker_id, momentum, learning_rate, rho,
  lambda} with defaults {current worker, 0.0, 0.01, 0.1, 0.1}
  (ref: include/multiverso/updater/updater.h:10-70). ``GetOption`` carries
  only worker_id (ref: updater.h:72-110).
* factory keyed on the ``-updater_type`` flag: default/sgd/momentum_sgd/
  adagrad; integer tables always get the default updater
  (ref: src/updater/updater.cpp:42-58).
* **default**: ``data += delta`` (ref: updater.cpp:24-31).
* **sgd**: ``data -= delta`` — caller pre-multiplies the learning rate
  (ref: updater/sgd_updater.h:8-27).
* **momentum_sgd**: ``smooth = m*smooth + (1-m)*delta; data -= smooth`` with
  one shared smooth buffer per table (ref: updater/momentum_updater.h:9-31).
* **adagrad**: per-worker historic g² accumulators
  (ref: updater/adagrad_updater.h:14-58). We implement the *intended*
  semantics: ``G_w += (delta/lr)²; data -= rho * (delta/lr) / sqrt(G_w + e)``
  with e=1e-6. Documented deviation: the reference's implementation has two
  defects — it copies the accumulator vector by value (`auto` instead of
  `auto&`, so accumulation is silently lost) and accumulates with ``-=``
  (which would drive sqrt() negative). The per-worker accumulator layout
  (num_workers x shard) is preserved and sharded with the table.

Deltas are element-wise over shards, so every updater is sharding-agnostic:
the same function runs on a CPU test mesh shard or a TPU HBM shard.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax.numpy as jnp

from multiverso_tpu.utils.configure import MV_DEFINE_string, GetFlag
from multiverso_tpu.utils.log import Log

__all__ = ["AddOption", "GetOption", "Updater", "make_updater", "available_updaters"]

MV_DEFINE_string(
    "updater_type",
    "default",
    "server-side updater: default|sgd|momentum_sgd|adagrad|dcasgd",
)


@dataclasses.dataclass
class AddOption:
    """Per-Add hyperparameters (ref: updater.h:10-70, same slots & defaults)."""

    worker_id: int = 0
    momentum: float = 0.0
    learning_rate: float = 0.01
    rho: float = 0.1
    lambda_: float = 0.1

    def scalars(self) -> Dict[str, jnp.ndarray]:
        """Traced scalar args for the jitted add program (no recompiles on
        hyperparameter change)."""
        return {
            "momentum": jnp.float32(self.momentum),
            "learning_rate": jnp.float32(self.learning_rate),
            "rho": jnp.float32(self.rho),
            "lambda_": jnp.float32(self.lambda_),
        }


@dataclasses.dataclass
class GetOption:
    """Per-Get options (ref: updater.h:72-110) — worker_id only; used by the
    sparse tables' delta tracking."""

    worker_id: int = 0


State = Dict[str, Any]


class Updater:
    """Pure-function updater contract.

    ``linear=True`` means update(sum of deltas) == sequential updates with
    each delta, enabling the single fused reduce-scatter add path.
    ``per_worker_state=True`` states carry a leading num_workers dim.
    """

    name = "base"
    linear = True
    per_worker_state = False
    # sign of the raw scatter for linear updaters (+= for default, -= for sgd):
    # lets row-sparse adds lower to one O(k) scatter instead of a full-table op
    delta_sign = 1

    def init_state(
        self, shape: Tuple[int, ...], num_workers: int, dtype, init=None
    ) -> State:
        """``init`` is the table's (padded) initial value, for updaters whose
        state must start at the weights (DC-ASGD backups)."""
        return {}

    def scatter_apply(
        self, data: jnp.ndarray, ids: jnp.ndarray, deltas: jnp.ndarray
    ) -> jnp.ndarray:
        """Row-sparse apply for linear updaters: one scatter-add on dim 0
        (duplicate ids accumulate, matching the reference server applying
        each row in sequence — ref: src/table/matrix_table.cpp:387-416)."""
        assert self.linear, "scatter_apply is only valid for linear updaters"
        sign = jnp.asarray(self.delta_sign, data.dtype)
        return data.at[ids].add(sign * deltas.astype(data.dtype))

    def apply(
        self,
        data: jnp.ndarray,
        delta: jnp.ndarray,
        state: State,
        worker_id: jnp.ndarray,
        opt: Dict[str, jnp.ndarray],
    ) -> Tuple[jnp.ndarray, State]:
        raise NotImplementedError

    def access(self, data: jnp.ndarray) -> jnp.ndarray:
        """Server-side Get transform (ref Updater::Access = memcpy)."""
        return data


class DefaultUpdater(Updater):
    name = "default"

    def apply(self, data, delta, state, worker_id, opt):
        return data + delta, state


class SGDUpdater(Updater):
    name = "sgd"
    delta_sign = -1

    def apply(self, data, delta, state, worker_id, opt):
        return data - delta, state


class MomentumUpdater(Updater):
    name = "momentum_sgd"
    linear = False

    def init_state(self, shape, num_workers, dtype, init=None):
        return {"smooth": jnp.zeros(shape, dtype)}

    def apply(self, data, delta, state, worker_id, opt):
        m = opt["momentum"].astype(data.dtype)
        smooth = m * state["smooth"] + (1 - m) * delta
        return data - smooth, {"smooth": smooth}


class AdaGradUpdater(Updater):
    name = "adagrad"
    linear = False
    per_worker_state = True
    epsilon = 1e-6

    def init_state(self, shape, num_workers, dtype, init=None):
        # per-worker accumulators, one row per worker, sharded with the table
        # (ref: adagrad_updater.h:19 — historic_g_sqr_[num_workers][size])
        return {"g2": jnp.zeros((num_workers,) + tuple(shape), dtype)}

    def apply(self, data, delta, state, worker_id, opt):
        lr = opt["learning_rate"].astype(data.dtype)
        rho = opt["rho"].astype(data.dtype)
        grad = delta / lr
        g2_w = state["g2"][worker_id] + grad * grad
        data = data - rho * grad / jnp.sqrt(g2_w + self.epsilon)
        return data, {"g2": state["g2"].at[worker_id].set(g2_w)}


class DCASGDUpdater(Updater):
    """Delay-compensated ASGD (Zheng et al., ICML 2017).

    The reference build system references a ``dcasgd`` updater
    (ref: CMakeLists.txt:9 ``ENABLE_DCASGD``; src/updater/updater.cpp:7-9,53-55
    expects ``updater/dcasgd/dcasgd_updater.h``) but the directory is empty in
    the snapshot — a documented-but-absent feature. Implemented here from the
    paper's update rule: for a delta pushed by worker ``m`` (computed against
    the stale weights that worker last pulled),

        grad   = delta / lr
        data  -= lr * (grad + lambda * grad ⊙ grad ⊙ (data - backup[m]))
        backup[m] = data            (the compensated post-update weights)

    ``lambda`` rides the AddOption ``lambda_`` slot — the slot the reference
    reserved for exactly this updater (ref: updater.h:10-70). The per-worker
    backup layout (num_workers x shard) matches the per-worker AdaGrad
    accumulator layout and is sharded with the table.
    """

    name = "dcasgd"
    linear = False
    per_worker_state = True

    def init_state(self, shape, num_workers, dtype, init=None):
        if init is None:
            return {"backup": jnp.zeros((num_workers,) + tuple(shape), dtype)}
        base = jnp.asarray(init, dtype)
        return {"backup": jnp.broadcast_to(base, (num_workers,) + tuple(shape))}

    def apply(self, data, delta, state, worker_id, opt):
        lr = opt["learning_rate"].astype(data.dtype)
        lam = opt["lambda_"].astype(data.dtype)
        grad = delta / lr
        backup = state["backup"][worker_id]
        data = data - lr * (grad + lam * grad * grad * (data - backup))
        return data, {"backup": state["backup"].at[worker_id].set(data)}


_REGISTRY = {
    u.name: u
    for u in (
        DefaultUpdater(),
        SGDUpdater(),
        MomentumUpdater(),
        AdaGradUpdater(),
        DCASGDUpdater(),
    )
}


def available_updaters():
    return sorted(_REGISTRY)


def make_updater(updater_type: str | None, dtype) -> Updater:
    """Factory (ref: src/updater/updater.cpp:42-58): flag-driven default;
    integer tables always use the default ``+=`` updater."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        return _REGISTRY["default"]
    name = updater_type or GetFlag("updater_type")
    updater = _REGISTRY.get(name)
    if updater is None:
        Log.Fatal("unknown updater_type %r (have: %s)", name, ", ".join(_REGISTRY))
    return updater
