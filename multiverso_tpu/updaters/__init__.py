"""Server-side updaters (optimizers) applied to table shards.

TPU-native equivalent of the reference updater layer
(ref: include/multiverso/updater/*, src/updater/updater.cpp — SURVEY.md §2.4).
In the reference, updaters run inside ``ServerTable::ProcessAdd`` on the
server's chunk, per incoming worker Add message, optionally parallelised with
OpenMP (ref: updater.cpp:24-31). Here they are pure jnp element-wise functions
applied to the local shard inside the table's jitted add program — XLA fuses
them into the reduce-scatter epilogue, and the shard axis replaces OpenMP.

Update-vs-sum semantics: the reference server applies each worker's Add as a
separate ``Update`` call. For *linear* updaters (default ``+=``, SGD) that is
equivalent to one update with the worker-summed delta, so the add path uses a
single fused reduce-scatter. Non-linear updaters (momentum, AdaGrad) are
applied per worker, sequentially in worker-id order, inside one jitted
``lax.scan`` — deterministic where the reference's async arrival order was
not (documented strengthening).
"""

from multiverso_tpu.updaters.updater import (
    AddOption,
    GetOption,
    Updater,
    available_updaters,
    make_updater,
)

__all__ = ["AddOption", "GetOption", "Updater", "available_updaters", "make_updater"]
