"""Deterministic fault injection + bounded retries.

The reference survives worker churn because workers are stateless against
sharded server tables; the TPU-native SPMD port concentrates all state in
one program, so process death, torn checkpoint writes and poisoned
publishes must be *testable* events, not hopes. This module is the one
switchboard: every fault is a ``MV_DEFINE_*`` flag (so the multiprocess
e2e workers and the CLI drivers can arm faults through ordinary argv,
deterministically — no sleeps, no signal races), and every production
code path that can fail transiently goes through ``with_retries``
(seeded-jitter exponential backoff under a hard deadline).

Fault points (all off by default):

* ``-chaos_kill_at_step=K``      — the training loop dies at step K
  (``os._exit(137)``, or ``ChaosInterrupt`` with
  ``-chaos_kill_mode=raise`` for in-process tests);
* ``-chaos_torn_checkpoint=true``   — the checkpoint writer crashes after
  the payload but *before* the atomic rename (leaves a ``.tmp-`` corpse);
* ``-chaos_corrupt_checkpoint=true`` — a published checkpoint gets one
  payload byte flipped after its checksums were recorded (what a partial
  disk write or bit rot looks like to ``latest_valid``);
* ``-chaos_route_errors=lookup:3``   — the next 3 serving flushes whose
  route contains ``lookup`` raise (drives the circuit breaker);
* ``-chaos_rendezvous_failures=N``   — the first N cluster-rendezvous
  attempts raise (drives the multihost retry path);
* ``-chaos_hang_collective=round:secs`` — the PS comms thread sleeps
  ``secs`` inside round ``round``'s pull (a hung collective, fired once:
  drives the per-ticket deadline / ``RankFailure`` path);
* ``-chaos_drop_rank=rank:round``    — process ``rank`` dies at PS round
  ``round`` (``os._exit(137)``, or ``ChaosInterrupt`` under
  ``-chaos_kill_mode=raise`` — the 2-process failure-domain drill);
* ``-chaos_drop_heartbeats_after=N`` — this rank's heartbeat thread stops
  publishing beacons after N beats while the process stays alive (pure
  heartbeat-loss injection: peers must escalate to RankFailure);
* ``-chaos_quorum_missing_stage=R``  — rank R skips writing its quorum
  stage record during a multi-process ``save_tables`` (rank 0 must abort
  the commit; no half checkpoint may publish).

Counters are process-local and reset with ``reset()`` (test isolation).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from multiverso_tpu.utils.configure import (
    MV_DEFINE_bool,
    MV_DEFINE_int,
    MV_DEFINE_string,
    GetFlag,
)
from multiverso_tpu.utils.log import Log

__all__ = [
    "ChaosInterrupt",
    "kill_exit_code",
    "maybe_kill",
    "maybe_hang_collective",
    "maybe_drop_rank",
    "heartbeats_dropped",
    "quorum_stage_should_skip",
    "torn_checkpoint",
    "corrupt_checkpoint",
    "should_fail_route",
    "rendezvous_should_fail",
    "reset",
    "with_retries",
]

MV_DEFINE_int("chaos_kill_at_step", -1, "kill this process at training step K (-1 = off)")
MV_DEFINE_string(
    "chaos_kill_mode", "exit",
    "how -chaos_kill_at_step dies: exit (os._exit 137, the crash-recovery "
    "e2e) | raise (ChaosInterrupt, in-process tests)",
)
MV_DEFINE_bool(
    "chaos_torn_checkpoint", False,
    "checkpoint saves crash after the payload write, before the atomic "
    "rename (leaves a .tmp- directory; no new version is published)",
)
MV_DEFINE_bool(
    "chaos_corrupt_checkpoint", False,
    "flip one payload byte of each published checkpoint AFTER its "
    "checksums were recorded (latest_valid must detect and skip it)",
)
MV_DEFINE_string(
    "chaos_route_errors", "",
    "substr:count — the next <count> serving flushes whose route contains "
    "<substr> raise an injected error (circuit-breaker drills)",
)
MV_DEFINE_int(
    "chaos_rendezvous_failures", 0,
    "fail the first N multihost rendezvous attempts (retry-path drills)",
)
MV_DEFINE_string(
    "chaos_hang_collective", "",
    "round:secs — the PS comms thread sleeps <secs> inside round <round>'s "
    "pull, once (a hung collective: per-ticket-deadline drills)",
)
MV_DEFINE_string(
    "chaos_drop_rank", "",
    "rank:round — process <rank> dies at PS round <round> (os._exit 137, "
    "or ChaosInterrupt under -chaos_kill_mode=raise): the failure-domain "
    "2-process drill",
)
MV_DEFINE_int(
    "chaos_drop_heartbeats_after", -1,
    "stop publishing this rank's liveness beacons after N beats while the "
    "process stays alive (-1 = off): pure heartbeat-loss injection",
)
MV_DEFINE_int(
    "chaos_quorum_missing_stage", -1,
    "rank R skips writing its quorum stage record during save_tables "
    "(-1 = off): the two-phase commit must abort, never half-publish",
)

_KILL_EXIT_CODE = 137

_lock = threading.Lock()
_route_budget: Dict[str, int] = {}  # parsed spec -> remaining failures
_route_spec_seen: Optional[str] = None
_rendezvous_failed = 0
_hang_fired = False


class ChaosInterrupt(RuntimeError):
    """An injected fault fired (never raised unless a chaos flag is set)."""


def kill_exit_code() -> int:
    return _KILL_EXIT_CODE


def reset() -> None:
    """Forget all chaos counters (test isolation; flags reset separately)."""
    global _route_spec_seen, _rendezvous_failed, _hang_fired
    with _lock:
        _route_budget.clear()
        _route_spec_seen = None
        _rendezvous_failed = 0
        _hang_fired = False


def maybe_kill(step: int) -> None:
    """Training-loop fault point: die at the armed step.

    ``exit`` mode uses ``os._exit`` — a real crash, no atexit handlers, no
    checkpoint flush — so the recovery test exercises exactly what a host
    loss leaves behind."""
    k = GetFlag("chaos_kill_at_step")
    if k < 0 or step != k:
        return
    Log.Error("[chaos] killing process at step %d (-chaos_kill_at_step)", step)
    if GetFlag("chaos_kill_mode") == "raise":
        raise ChaosInterrupt(f"chaos: killed at step {step}")
    os._exit(_KILL_EXIT_CODE)


def maybe_hang_collective(round_idx: int) -> None:
    """PS comms-thread fault point: sleep through the armed round's pull
    once — what a hung peer's collective looks like to the ticket wait."""
    spec = GetFlag("chaos_hang_collective")
    if not spec:
        return
    global _hang_fired
    rd, _, secs = spec.partition(":")
    if int(rd) != round_idx:
        return
    with _lock:
        if _hang_fired:
            return
        _hang_fired = True
    Log.Error(
        "[chaos] hanging collective at round %d for %ss "
        "(-chaos_hang_collective)", round_idx, secs or "5",
    )
    time.sleep(float(secs or 5))


def maybe_drop_rank(round_idx: int) -> None:
    """PS training-loop fault point: the armed rank dies at the armed
    round (a real ``os._exit`` by default — the 2-process drill — or
    ``ChaosInterrupt`` under ``-chaos_kill_mode=raise``)."""
    spec = GetFlag("chaos_drop_rank")
    if not spec:
        return
    import jax

    rk, _, rd = spec.partition(":")
    if jax.process_index() != int(rk) or round_idx != int(rd):
        return
    Log.Error(
        "[chaos] dropping rank %s at round %d (-chaos_drop_rank)",
        rk, round_idx,
    )
    if GetFlag("chaos_kill_mode") == "raise":
        raise ChaosInterrupt(f"chaos: rank {rk} dropped at round {round_idx}")
    os._exit(_KILL_EXIT_CODE)


def heartbeats_dropped(seq: int) -> bool:
    """Heartbeat-thread fault point: True once this rank's beacon budget
    is exhausted (the process stays alive; peers must notice)."""
    n = GetFlag("chaos_drop_heartbeats_after")
    return n >= 0 and seq >= n


def quorum_stage_should_skip() -> bool:
    """save_tables fault point: this rank 'dies' before writing its stage
    record (rank 0 must abort the two-phase commit)."""
    r = GetFlag("chaos_quorum_missing_stage")
    if r < 0:
        return False
    import jax

    if jax.process_index() == r:
        Log.Error(
            "[chaos] skipping quorum stage record for rank %d "
            "(-chaos_quorum_missing_stage)", r,
        )
        return True
    return False


def torn_checkpoint() -> bool:
    return bool(GetFlag("chaos_torn_checkpoint"))


def corrupt_checkpoint() -> bool:
    return bool(GetFlag("chaos_corrupt_checkpoint"))


def should_fail_route(route: str) -> bool:
    """Serving-flush fault point: consume one failure from the armed
    ``substr:count`` budget when the route matches."""
    spec = GetFlag("chaos_route_errors")
    if not spec:
        return False
    global _route_spec_seen
    with _lock:
        if spec != _route_spec_seen:  # flag changed: re-arm the budget
            _route_budget.clear()
            for part in spec.split(";"):
                substr, _, cnt = part.partition(":")
                if substr:
                    _route_budget[substr] = int(cnt or 1)
            _route_spec_seen = spec
        for substr in _route_budget:
            if substr in route and _route_budget[substr] > 0:
                _route_budget[substr] -= 1
                Log.Error("[chaos] injected route failure on %r", route)
                return True
    return False


def rendezvous_should_fail() -> bool:
    """Rendezvous fault point: fail the first N attempts."""
    n = GetFlag("chaos_rendezvous_failures")
    if n <= 0:
        return False
    global _rendezvous_failed
    with _lock:
        if _rendezvous_failed < n:
            _rendezvous_failed += 1
            Log.Error(
                "[chaos] injected rendezvous failure %d/%d",
                _rendezvous_failed, n,
            )
            return True
    return False


# --------------------------------------------------------------- retries


class FullJitterBackoff:
    """The ``with_retries`` backoff schedule as a reusable object: delay
    for attempt i is ``min(max_delay_s, base * 2^i)`` scaled into
    [0.5, 1.0) by a seeded xorshift32 — deterministic per seed, full
    jitter against thundering herds. ``with_retries`` and the pod
    supervisor's restart budget both draw from this one definition."""

    def __init__(self, base_delay_s: float, max_delay_s: float,
                 seed: int = 0):
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self._state = (seed * 2654435761 + 0x9E3779B9) & 0xFFFFFFFF or 1
        # one instance feeds retries on arbitrary threads (the fleet
        # watch thread and the training thread share the supervisor's
        # budget): the stream advance is a read-modify-write
        self._state_lock = threading.Lock()

    def next_delay(self, attempt: int) -> float:
        """Jittered delay for (0-based) retry ``attempt``; advances the
        jitter stream by one draw."""
        with self._state_lock:
            s = self._state
            # xorshift32: cheap, seedable, good enough for jitter
            s ^= (s << 13) & 0xFFFFFFFF
            s ^= s >> 17
            s ^= (s << 5) & 0xFFFFFFFF
            self._state = s
        u = s / 0xFFFFFFFF
        return min(
            self.max_delay_s, self.base_delay_s * (2.0 ** attempt)
        ) * (0.5 + 0.5 * u)


def with_retries(
    fn: Callable[[], Any],
    *,
    attempts: int = 5,
    base_delay_s: float = 0.05,
    max_delay_s: float = 2.0,
    deadline_s: Optional[float] = None,
    retry_on: Tuple[type, ...] = (Exception,),
    seed: int = 0,
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Run ``fn`` with jittered exponential backoff under a hard deadline.

    Deterministic: the jitter sequence is a seeded xorshift, so two runs
    with the same seed retry on an identical schedule (no flaky test
    timing). Backoff for attempt i is ``min(max_delay_s, base * 2^i)``
    scaled into [0.5, 1.0) — full-jitter halves thundering herds while the
    floor keeps the deadline math predictable. A ``deadline_s`` bounds the
    TOTAL time: a retry whose backoff would cross the deadline is not
    taken (bounded failure instead of hanging forever — the reference's
    ZMQ rendezvous simply blocks; we refuse to)."""
    assert attempts >= 1
    start = clock()
    backoff = FullJitterBackoff(base_delay_s, max_delay_s, seed=seed)
    last: Optional[BaseException] = None
    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop
            last = e
            if i == attempts - 1:
                break
            delay = backoff.next_delay(i)
            if deadline_s is not None and (clock() - start) + delay > deadline_s:
                Log.Error(
                    "%s: giving up after %d attempt(s) — deadline %.1fs "
                    "would be exceeded (%s)", describe, i + 1, deadline_s, e,
                )
                break
            Log.Info(
                "%s failed (attempt %d/%d): %s — retrying in %.3fs",
                describe, i + 1, attempts, e, delay,
            )
            sleep(delay)
    assert last is not None
    raise last
