"""Distributed failure-domain layer: liveness beacons + structured failures.

The PR-4 pipeline moved every PS table collective onto a comms thread
(``utils.async_buffer.TaskPipe``) with no failure handling: one hung or
dead rank stalled the pipe forever and the training thread blocked on a
ticket that would never resolve. This module turns that silent
cluster-wide hang into a *detected, drained, resumable* event:

* ``RankFailure`` — the structured exception a training thread sees when
  a peer dies or a collective exceeds its deadline (kind, rank, round,
  cause), instead of blocking forever;
* ``PipelineBroken`` — fail-fast for every submit/result after the first
  failure marked the pipe poisoned (containment: one bad collective must
  not let later callers block on tickets that can never resolve);
* ``QuorumAbort`` — a two-phase multi-process ``save_tables`` commit was
  refused because some rank's stage record is missing or broken (a rank
  dying mid-save can never publish a half checkpoint);
* ``HeartbeatMonitor`` — a side-thread liveness beacon per rank (over a
  file-backed store on a shared filesystem, or the jax distributed KV
  service when available) plus peer-age tracking: a peer that misses
  ``-heartbeat_deadline_s`` raises ``RankFailure`` on the next watched
  wait;
* ``fd_stats`` — the process-wide ``failure_domain`` Dashboard section
  (heartbeat ages, ticket wait p50/p99, broken-pipe / drain /
  quorum-abort counters) that also feeds ``/healthz`` and the bench leg.

Peer liveness is judged on the OBSERVER's monotonic clock (age since the
last *new* beacon sequence number was seen), so wall-clock skew between
hosts never fakes a death.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from multiverso_tpu.utils.configure import (
    MV_DEFINE_double,
    MV_DEFINE_string,
    GetFlag,
)
from multiverso_tpu.analysis.guards import OrderedLock
from multiverso_tpu.utils.log import Log

__all__ = [
    "RankFailure",
    "PipelineBroken",
    "QuorumAbort",
    "classify_collective_error",
    "FileHeartbeatStore",
    "KVHeartbeatStore",
    "HeartbeatMonitor",
    "monitor_from_flags",
    "collective_timeout_s",
    "fd_stats",
]

# Failure-domain flags (all off by default — arming them is what turns a
# hang into a bounded, structured failure; see DEPLOY.md for tuning).
MV_DEFINE_double(
    "collective_timeout_s", 0.0,
    "per-ticket deadline on pipelined PS collectives (and multi-process "
    "checkpoint sync points): a collective that exceeds this raises "
    "RankFailure on the training thread instead of hanging (0 = off). "
    "Tune ABOVE the slowest legitimate collective incl. first-round "
    "compile — see DEPLOY.md",
)
MV_DEFINE_double(
    "heartbeat_deadline_s", 0.0,
    "a peer that publishes no new liveness beacon for this long is "
    "declared dead (RankFailure kind=heartbeat_lost; 0 = watchdog off)",
)
MV_DEFINE_double(
    "heartbeat_interval_s", 0.0,
    "beacon publish/poll period (0 = auto: heartbeat_deadline_s / 4)",
)
MV_DEFINE_string(
    "heartbeat_dir", "",
    "file-backed beacon directory (must be shared across ranks — one "
    "host or a shared filesystem); empty = use the jax distributed KV "
    "service when available",
)


class RankFailure(RuntimeError):
    """A peer rank died or a collective exceeded its deadline.

    Structured: ``kind`` in {"heartbeat_lost", "collective_timeout",
    "peer_dead"}, ``rank`` (the suspected peer, -1 unknown), ``round``
    (PS round when known), ``cause`` (the underlying exception, if any).
    """

    def __init__(self, kind: str, detail: str, *, rank: int = -1,
                 round_idx: int = -1, cause: Optional[BaseException] = None):
        self.kind = kind
        self.rank = int(rank)
        self.round_idx = int(round_idx)
        self.cause = cause
        msg = f"RankFailure[{kind}] {detail}"
        if rank >= 0:
            msg += f" (suspected rank {rank})"
        if round_idx >= 0:
            msg += f" at round {round_idx}"
        if cause is not None:
            msg += f": {type(cause).__name__}: {cause}"
        super().__init__(msg)


class PipelineBroken(RuntimeError):
    """The comms pipe was poisoned by an earlier failure; this call fails
    fast instead of blocking on a ticket that can never resolve."""

    def __init__(self, cause: Optional[BaseException] = None):
        self.cause = cause
        super().__init__(
            "comms pipeline is broken (poisoned by an earlier failure"
            + (f": {cause}" if cause is not None else "")
            + "); drain() and restart from the last drained checkpoint"
        )


class QuorumAbort(RuntimeError):
    """Two-phase checkpoint commit refused: not every rank's stage record
    verified, so no version was published (the tmp staging dir is the
    only artifact)."""


# Transport/coordination-layer signatures that mean "a peer is gone", not
# "this program has a bug" — a comms-thread exception matching one of
# these is promoted to RankFailure so the containment path runs (same
# signature family the cluster test launcher retries on).
_PEER_DEATH_SIGNATURES = (
    "gloo",
    "op.preamble.length",
    "connection reset",
    "connection refused",
    "broken pipe",
    "heartbeat timeout",
    "deadline exceeded",
    "barrier",
    "distributed runtime",
    "peer closed",
    "socket closed",
)


def classify_collective_error(
    exc: BaseException, *, round_idx: int = -1
) -> Optional[RankFailure]:
    """Map a comms-thread exception to a structured ``RankFailure`` when
    it looks like peer death / transport loss; ``None`` for anything else
    (logic errors must propagate unchanged)."""
    if isinstance(exc, RankFailure):
        return exc
    low = f"{type(exc).__name__}: {exc}".lower()
    if any(sig in low for sig in _PEER_DEATH_SIGNATURES):
        return RankFailure(
            "peer_dead", "collective failed like a dead peer",
            round_idx=round_idx, cause=exc,
        )
    return None


# ----------------------------------------------------------- beacon stores


class FileHeartbeatStore:
    """Beacons as one JSON file per rank on a shared filesystem. Writes
    are atomic (tmp + rename) so a reader never sees a torn beacon."""

    def __init__(self, directory: str, rank: int):
        self.directory = os.path.abspath(directory)
        self.rank = int(rank)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, rank: int) -> str:
        return os.path.join(self.directory, f"hb-{int(rank)}.json")

    def beat(self, seq: int) -> None:
        path = self._path(self.rank)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "seq": int(seq),
                       "wall": time.time()}, f)
        os.replace(tmp, path)

    def latest_seq(self, rank: int, hint: int = -1) -> Optional[int]:
        try:
            with open(self._path(rank)) as f:
                return int(json.load(f)["seq"])
        except (OSError, ValueError, KeyError):
            return None  # no (readable) beacon yet


class KVHeartbeatStore:
    """Beacons over the jax distributed KV service (write-once keys:
    ``mv_hb/<rank>/<seq>``). Peers probe forward from their last
    confirmed sequence — no overwrite semantics needed."""

    def __init__(self, client, rank: int):
        self._client = client
        self.rank = int(rank)

    @classmethod
    def try_create(cls, rank: int) -> Optional["KVHeartbeatStore"]:
        from multiverso_tpu.parallel.multihost import kv_client

        client = kv_client()
        if client is None:
            return None
        return cls(client, rank)

    def beat(self, seq: int) -> None:
        try:
            self._client.key_value_set(
                f"mv_hb/{self.rank}/{int(seq)}", str(time.time())
            )
        except Exception as e:  # noqa: BLE001 — beacon loss is survivable
            Log.Error("heartbeat publish failed (kv): %s", e)

    def latest_seq(self, rank: int, hint: int = -1) -> Optional[int]:
        seq = None if hint < 0 else hint
        probe = (hint + 1) if hint >= 0 else 0
        while True:
            try:
                got = self._client.key_value_try_get(f"mv_hb/{rank}/{probe}")
            except Exception:  # noqa: BLE001 — NotFound surfaces as raise
                got = None
            if not got:
                return seq
            seq = probe
            probe += 1


# ----------------------------------------------------------- monitor


class HeartbeatMonitor:
    """Publish this rank's beacon and watch the peers' — a peer that
    produces no NEW beacon for ``deadline_s`` (observer's monotonic
    clock) is recorded as failed; the failure surfaces through
    ``check()`` / ``failed()`` and through any watchdog-aware ticket wait
    (``TaskPipe`` integration). ``poll_once()`` is the deterministic unit
    tests drive with a fake clock; ``start()`` runs it on a side thread.
    """

    def __init__(
        self,
        store,
        rank: int,
        world: int,
        deadline_s: float,
        interval_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.store = store
        self.rank = int(rank)
        self.world = int(world)
        self.deadline_s = float(deadline_s)
        self.interval_s = float(interval_s or max(deadline_s / 4.0, 1e-3))
        self._clock = clock
        self._sleep = sleep
        self._seq = 0
        now = clock()
        # peers get a full deadline from monitor start to their first
        # beacon; [last_seq, last_seen_mono, gap_recorded] — the latch
        # gives the flight recorder ONE heartbeat_gap event per silence
        self._peers: Dict[int, List] = {
            p: [-1, now, False] for p in range(self.world) if p != self.rank
        }
        self._failure: Optional[RankFailure] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # OrderedLock (mvlint R2): the beacon thread and every
        # watchdog-aware ticket wait read/write the peer records
        self._lock = OrderedLock("heartbeat_store._lock")

    def poll_once(self) -> Optional[RankFailure]:
        """One beacon publish + one peer sweep (the thread body; also the
        deterministic test entry point)."""
        from multiverso_tpu.resilience import chaos

        # the seq bump is a read-modify-write: the monitor thread and a
        # deterministic test/bench driver may both run poll_once (mvlint
        # R9); beat() publishes outside the lock (its store serialises
        # itself, and nesting the two would pin a lock order for nothing)
        with self._lock:
            seq = self._seq
            publish = not chaos.heartbeats_dropped(seq)
            if publish:
                self._seq = seq + 1
        if publish:
            self.store.beat(seq)
        now = self._clock()
        with self._lock:
            for peer, rec in self._peers.items():
                seq = self.store.latest_seq(peer, hint=rec[0])
                if seq is not None and seq != rec[0]:
                    rec[0], rec[1], rec[2] = seq, now, False
                    continue
                age = now - rec[1]
                if age > self.deadline_s / 2.0 and not rec[2]:
                    rec[2] = True
                    from multiverso_tpu.obs.flight import recorder

                    recorder.record(
                        "heartbeat_gap", rank=peer, age_s=round(age, 3),
                        deadline_s=self.deadline_s,
                    )
                if age > self.deadline_s and self._failure is None:
                    self._failure = RankFailure(
                        "heartbeat_lost",
                        f"no beacon from peer for {now - rec[1]:.2f}s "
                        f"(deadline {self.deadline_s:.2f}s)",
                        rank=peer,
                    )
                    fd_stats.note_rank_failure("heartbeat_lost")
                    Log.Error("[watchdog] %s", self._failure)
            return self._failure

    def failed(self) -> Optional[RankFailure]:
        with self._lock:
            return self._failure

    def check(self) -> None:
        f = self.failed()
        if f is not None:
            raise f

    def ages(self) -> Dict[int, float]:
        """Seconds since each peer's last NEW beacon was observed."""
        now = self._clock()
        with self._lock:
            return {p: round(now - rec[1], 3) for p, rec in self._peers.items()}

    def start(self) -> "HeartbeatMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="mv-heartbeat"
            )
            self._thread.start()
            fd_stats.set_heartbeat_ages_provider(self.ages)
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the watchdog must not die
                Log.Error("[watchdog] poll failed: %s", e)
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        fd_stats.set_heartbeat_ages_provider(None)


def collective_timeout_s() -> Optional[float]:
    """The armed per-ticket collective deadline, or None when off."""
    t = float(GetFlag("collective_timeout_s"))
    return t if t > 0 else None


def monitor_from_flags(
    *, clock: Callable[[], float] = time.monotonic
) -> Optional[HeartbeatMonitor]:
    """Build + start the flag-armed heartbeat monitor (None when
    ``-heartbeat_deadline_s`` is 0 or no beacon transport is usable)."""
    import jax

    deadline = float(GetFlag("heartbeat_deadline_s"))
    if deadline <= 0:
        return None
    rank, world = jax.process_index(), jax.process_count()
    hb_dir = GetFlag("heartbeat_dir")
    if hb_dir:
        store = FileHeartbeatStore(hb_dir, rank)
    else:
        store = KVHeartbeatStore.try_create(rank)
        if store is None:
            Log.Error(
                "-heartbeat_deadline_s=%.1f armed but no beacon transport: "
                "set -heartbeat_dir to a shared directory (or run under "
                "the jax distributed service) — watchdog DISABLED", deadline,
            )
            return None
    interval = float(GetFlag("heartbeat_interval_s")) or None
    return HeartbeatMonitor(
        store, rank, world, deadline, interval, clock=clock
    ).start()


# ----------------------------------------------------------- fd stats


class _FailureDomainStats:
    """Process-wide failure-domain counters: Dashboard ``failure_domain``
    section, ``/healthz`` payload and the bench resilience leg all read
    the same record."""

    def __init__(self) -> None:
        # OrderedLock, not threading.Lock: fd_stats is an import-time
        # singleton, and a stdlib lock born before mvtsan arms is
        # invisible to the race detector (the lock-factory patch only
        # covers locks created after arming) — the readiness writes
        # then report as unordered. The owned primitive is tracked for
        # its whole lifetime and adds R2 order coverage for free.
        self._lock = OrderedLock("watchdog.fd_stats")
        self.tickets = 0
        self._waits_ms: deque = deque(maxlen=4096)
        # running p99 refreshed every 128 tickets: the flight recorder's
        # breach detector must not sort 4096 floats on every wait
        self._wait_p99_cache_ms = 0.0
        self.broken_pipes = 0
        self.drains = 0
        self.drain_timeouts = 0
        self.drain_ms_total = 0.0
        self.quorum_commits = 0
        self.quorum_aborts = 0
        self.rank_failures = 0
        self.stragglers = 0
        self.last_straggler_rank: Optional[int] = None
        self.last_failure_kind: Optional[str] = None
        self._ages_fn: Optional[Callable[[], Dict[int, float]]] = None
        # alive-vs-ready (ISSUE 7): liveness is the process existing;
        # readiness flips once tables are restored/published
        # (serving.http_health.set_ready is the single writer)
        self.ready = False
        self.phase = "starting"

    def _register(self) -> None:
        # lazy + keyed: survives Dashboard.Reset() by re-adding on next note
        from multiverso_tpu.utils.dashboard import Dashboard

        Dashboard.add_section("failure_domain", self.lines,
                              snapshot=self.to_dict)

    def note_ticket_wait(self, wait_s: float) -> None:
        wait_ms = wait_s * 1e3
        breach = False
        with self._lock:
            self.tickets += 1
            # breach check BEFORE this sample joins the window (a spike
            # must not raise the bar it is judged against), against a bar
            # of 3x the cached p99 with a 1ms floor — "p99 breach" in the
            # flight recorder means "far outside the recent distribution",
            # not the definitional 1% of samples above p99
            p99 = self._wait_p99_cache_ms
            if (
                self.tickets > 128
                and wait_ms > max(1.0, 3.0 * p99)
            ):
                breach = True
            self._waits_ms.append(wait_ms)
            if self.tickets % 128 == 0:
                self._wait_p99_cache_ms = self._wait_pct_locked(99)
        if breach:
            from multiverso_tpu.obs.flight import recorder

            recorder.record(
                "ticket_wait_p99_breach", wait_ms=round(wait_ms, 3),
                p99_ms=round(p99, 3),
            )
        self._register()

    def note_broken_pipe(self) -> None:
        with self._lock:
            self.broken_pipes += 1
        from multiverso_tpu.obs.flight import recorder

        recorder.record("broken_pipe")
        self._register()

    def note_drain(self, seconds: float, ok: bool) -> None:
        with self._lock:
            self.drains += 1
            self.drain_ms_total += seconds * 1e3
            if not ok:
                self.drain_timeouts += 1
        if not ok:
            from multiverso_tpu.obs.flight import recorder

            recorder.record("drain_timeout", drain_s=round(seconds, 3))
        self._register()

    def note_quorum_commit(self) -> None:
        with self._lock:
            self.quorum_commits += 1
        from multiverso_tpu.obs.flight import recorder

        recorder.record("quorum_commit")
        self._register()

    def note_quorum_abort(self) -> None:
        with self._lock:
            self.quorum_aborts += 1
        from multiverso_tpu.obs.flight import recorder

        recorder.record("quorum_abort")
        self._register()

    def note_straggler(self, rank: int, timer_us: float = 0.0,
                       median_us: float = 0.0) -> None:
        """A rank confirmed drifting >k-sigma above the pod-median round
        timer (obs.slo.StragglerDetector) — alive and beating, so the
        heartbeat watchdog cannot see it; this counter is the precursor
        signal an operator pages on before it becomes a rank failure."""
        with self._lock:
            self.stragglers += 1
            self.last_straggler_rank = int(rank)
        self._register()

    def note_rank_failure(self, kind: str) -> None:
        with self._lock:
            self.rank_failures += 1
            self.last_failure_kind = kind
        from multiverso_tpu.obs.flight import recorder

        recorder.record("rank_failure", failure_kind=kind)
        self._register()

    def set_readiness(self, ready: bool, phase: str) -> None:
        with self._lock:
            self.ready = bool(ready)
            self.phase = str(phase)
        self._register()

    def set_heartbeat_ages_provider(
        self, fn: Optional[Callable[[], Dict[int, float]]]
    ) -> None:
        with self._lock:
            self._ages_fn = fn
        if fn is not None:
            self._register()

    def heartbeat_ages(self) -> Dict[int, float]:
        with self._lock:
            fn = self._ages_fn
        try:
            return fn() if fn is not None else {}
        except Exception:  # noqa: BLE001 — a stopped monitor must not throw
            return {}

    def to_dict(self) -> Dict:
        ages = self.heartbeat_ages()
        with self._lock:
            return {
                "ready": self.ready,
                "phase": self.phase,
                "tickets": self.tickets,
                "ticket_wait_p50_ms": round(self._wait_pct_locked(50), 3),
                "ticket_wait_p99_ms": round(self._wait_pct_locked(99), 3),
                "broken_pipes": self.broken_pipes,
                "drains": self.drains,
                "drain_timeouts": self.drain_timeouts,
                "drain_ms_avg": round(
                    self.drain_ms_total / self.drains, 3
                ) if self.drains else 0.0,
                "quorum_commits": self.quorum_commits,
                "quorum_aborts": self.quorum_aborts,
                "rank_failures": self.rank_failures,
                "stragglers": self.stragglers,
                "last_straggler_rank": self.last_straggler_rank,
                "last_failure_kind": self.last_failure_kind,
                "heartbeat_ages_s": {str(k): v for k, v in ages.items()},
            }

    def _wait_pct_locked(self, pct: float) -> float:
        if not self._waits_ms:
            return 0.0
        s = sorted(self._waits_ms)
        return s[min(len(s) - 1, int(pct / 100.0 * len(s)))]

    def lines(self) -> List[str]:
        d = self.to_dict()
        hb = " ".join(
            f"r{k}={v}s" for k, v in sorted(d["heartbeat_ages_s"].items())
        ) or "none"
        return [
            "[failure_domain] ready=%s phase=%s tickets=%d wait_p50=%.2fms "
            "wait_p99=%.2fms broken_pipes=%d drains=%d (timeouts=%d, "
            "avg=%.1fms)" % (
                d["ready"], d["phase"], d["tickets"],
                d["ticket_wait_p50_ms"],
                d["ticket_wait_p99_ms"], d["broken_pipes"], d["drains"],
                d["drain_timeouts"], d["drain_ms_avg"],
            ),
            "[failure_domain] quorum commits=%d aborts=%d rank_failures=%d "
            "last=%s heartbeat_ages: %s" % (
                d["quorum_commits"], d["quorum_aborts"], d["rank_failures"],
                d["last_failure_kind"], hb,
            ),
        ]


fd_stats = _FailureDomainStats()
