"""Crash-consistent checkpoint lifecycle: atomic publish, discovery, GC.

The seed's ``save_tables`` wrote the orbax tree and the
``logical_shapes.json`` sidecar non-atomically, in sequence — a crash
mid-save left a torn directory that ``restore_tables``/``load_arrays``
would happily misread. This module makes every checkpoint a single
atomic event with an integrity proof:

1. the payload is written into ``<final>.tmp-<uuid>`` (never the final
   name);
2. a ``MANIFEST.json`` is written LAST inside the tmp dir, carrying the
   step, caller metadata (data cursor, restart count, ...) and a
   size+crc32 record of every payload file, then fsynced;
3. the tmp dir is renamed onto the final name (one atomic filesystem op)
   and the parent directory fsynced.

A reader therefore sees either nothing, a ``.tmp-`` corpse (ignored), or
a complete checkpoint whose manifest proves the payload intact.
``latest_valid`` walks a checkpoint root newest-first and returns the
first version that verifies — torn, truncated, checksum-flipped or
manifest-less directories are skipped with a logged reason, never
loaded. ``gc_checkpoints`` bounds disk: newest N valid versions stay,
older versions and tmp corpses go.

On top sit the policy pieces training loops wire in: ``CheckpointPolicy``
(every-N-steps / every-N-seconds), ``AutoCheckpointer`` (snapshot on the
training thread, write off it), and a process-wide ``stats`` record
(restart count, last-checkpoint age) that lands on the Dashboard.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from multiverso_tpu.analysis.guards import OrderedLock
from multiverso_tpu.resilience import chaos
from multiverso_tpu.utils.log import CHECK, Log

__all__ = [
    "MANIFEST_NAME",
    "write_manifest",
    "commit_atomic",
    "verify_checkpoint",
    "require_valid",
    "list_checkpoints",
    "latest_valid",
    "gc_checkpoints",
    "save_checkpoint",
    "load_checkpoint",
    "CheckpointPolicy",
    "AutoCheckpointer",
    "stats",
]

MANIFEST_NAME = "MANIFEST.json"
_FORMAT = 1
_PREFIX = "ckpt-"


# ------------------------------------------------------------ integrity


def _payload_files(directory: str) -> List[str]:
    """Relative paths of every payload file under ``directory`` (manifest
    excluded), sorted for stable manifests."""
    out: List[str] = []
    for base, _dirs, files in os.walk(directory):
        for f in files:
            rel = os.path.relpath(os.path.join(base, f), directory)
            if rel != MANIFEST_NAME:
                out.append(rel)
    return sorted(out)


def _crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_manifest(
    directory: str, step: Optional[int] = None, meta: Optional[Dict] = None
) -> str:
    """Checksum the payload and write+fsync ``MANIFEST.json`` — the commit
    record. Must be the LAST write into the tmp dir."""
    files = {}
    for rel in _payload_files(directory):
        p = os.path.join(directory, rel)
        files[rel] = {"size": os.path.getsize(p), "crc32": _crc32(p)}
    manifest = {
        "format": _FORMAT,
        "step": step,
        "created": time.time(),
        "meta": meta or {},
        "files": files,
    }
    path = os.path.join(directory, MANIFEST_NAME)
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    return path


def commit_atomic(
    tmp_dir: str,
    final_dir: str,
    *,
    step: Optional[int] = None,
    meta: Optional[Dict] = None,
) -> str:
    """Manifest + atomic rename: publish ``tmp_dir`` as ``final_dir``.

    If ``final_dir`` already exists it is moved aside first and removed
    after the rename, so no reader ever observes a half-replaced
    directory. Chaos hooks: ``-chaos_torn_checkpoint`` dies between the
    manifest and the rename (the crash window the protocol defends
    against); ``-chaos_corrupt_checkpoint`` flips a payload byte after
    publication (what verification must catch)."""
    write_manifest(tmp_dir, step=step, meta=meta)
    if chaos.torn_checkpoint():
        raise chaos.ChaosInterrupt(
            f"torn checkpoint write: crashed before renaming {tmp_dir}"
        )
    aside = None
    if os.path.exists(final_dir):
        aside = f"{final_dir}.old-{uuid.uuid4().hex[:8]}"
        os.rename(final_dir, aside)
    os.replace(tmp_dir, final_dir)
    try:  # durability of the rename itself
        _fsync_path(os.path.dirname(os.path.abspath(final_dir)) or ".")
    except OSError:
        pass  # fsync-on-dir unsupported (some filesystems): rename still atomic
    if aside is not None:
        shutil.rmtree(aside, ignore_errors=True)
    if chaos.corrupt_checkpoint():
        _flip_one_payload_byte(final_dir)
    return final_dir


def _flip_one_payload_byte(directory: str) -> None:
    rels = _payload_files(directory)
    CHECK(rels, f"chaos corrupt: no payload files under {directory}")
    target = max(rels, key=lambda r: os.path.getsize(os.path.join(directory, r)))
    path = os.path.join(directory, target)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    Log.Error("[chaos] corrupted checkpoint payload byte: %s", path)


def verify_checkpoint(directory: str) -> Optional[str]:
    """Return None when ``directory`` is a complete, uncorrupted
    checkpoint, else one human-readable reason (the first problem found:
    missing manifest, missing/truncated payload file, checksum
    mismatch)."""
    if not os.path.isdir(directory):
        return "not a directory"
    mpath = os.path.join(directory, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return f"missing {MANIFEST_NAME}"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (ValueError, KeyError, OSError) as e:
        return f"unreadable {MANIFEST_NAME} ({e})"
    for rel, rec in sorted(files.items()):
        p = os.path.join(directory, rel)
        if not os.path.exists(p):
            return f"missing payload file {rel}"
        size = os.path.getsize(p)
        if size != rec["size"]:
            return f"truncated payload file {rel} ({size} != {rec['size']} bytes)"
        if _crc32(p) != rec["crc32"]:
            return f"checksum mismatch in {rel}"
    return None


def require_valid(directory: str) -> Dict:
    """Verify or die with ONE clear error naming the directory and the
    broken piece (never an orbax stack trace). Returns the manifest."""
    problem = verify_checkpoint(directory)
    if problem is not None:
        Log.Fatal(
            "checkpoint %s is incomplete or corrupt: %s", directory, problem
        )
    with open(os.path.join(directory, MANIFEST_NAME)) as f:
        return json.load(f)


# ------------------------------------------------------------ discovery


def _is_version_dir(name: str) -> bool:
    return (
        name.startswith(_PREFIX)
        and ".tmp-" not in name
        and ".old-" not in name
        and name[len(_PREFIX):].isdigit()
    )


def list_checkpoints(root: str) -> List[Tuple[int, str]]:
    """All published versions under ``root`` as (step, path), ascending.
    ``.tmp-``/``.old-`` corpses are not versions."""
    if not os.path.isdir(root):
        return []
    out = [
        (int(name[len(_PREFIX):]), os.path.join(root, name))
        for name in os.listdir(root)
        if _is_version_dir(name)
    ]
    return sorted(out)


def latest_valid(root: str) -> Optional[str]:
    """Newest checkpoint under ``root`` that passes verification; torn or
    corrupt versions are skipped (logged) — the fallback the torn-write
    fixtures pin."""
    for step, path in reversed(list_checkpoints(root)):
        problem = verify_checkpoint(path)
        if problem is None:
            return path
        Log.Error(
            "skipping checkpoint %s (step %d): %s", path, step, problem
        )
    return None


# How long a ``.tmp-``/``.old-`` corpse must sit UNTOUCHED before the
# sweeper may take it. The corpse sweep is no longer single-writer: a
# supervisor-relaunched rank runs gc while its SIBLINGS may be mid-way
# through staging the next quorum save in a live ``.tmp-<token>`` dir —
# sweeping that would abort a healthy commit. A staging dir being
# actively written keeps a fresh mtime (stage records and payload land
# at its top level), so an age gate separates "crashed save's corpse"
# from "in-progress save" without any cross-process locking.
CORPSE_GRACE_S = 900.0


def _corpse_age_s(path: str) -> Optional[float]:
    """Seconds since the NEWEST write anywhere under ``path`` (the top
    dir's own mtime included — orbax writes into nested dirs, and only
    the deepest file's mtime proves the save is still making progress)."""
    newest = None
    try:
        newest = os.path.getmtime(path)
        for base, _dirs, files in os.walk(path):
            for f in files + [""]:
                m = os.path.getmtime(os.path.join(base, f) if f else base)
                if m > newest:
                    newest = m
    except OSError:
        return None  # vanished under us: someone else swept it already
    return time.time() - newest


def gc_checkpoints(
    root: str, retain: int = 3, *, corpse_grace_s: float = CORPSE_GRACE_S
) -> List[str]:
    """Bound disk: keep the newest ``retain`` VALID versions; delete every
    other version (older valid ones and torn/corrupt ones) and every
    ``.tmp-``/``.old-`` corpse older than ``corpse_grace_s``. Returns the
    removed paths.

    The corpse sweep is age-gated (see ``CORPSE_GRACE_S``): under a
    self-healing supervisor, a relaunched rank's gc runs CONCURRENTLY
    with its siblings' in-flight quorum save, and an un-gated sweep could
    delete the live staging directory mid-phase-1 (the race ISSUE 7
    names). A dir younger than the grace window is left alone — if the
    save it belongs to really crashed, the next gc after the window takes
    it. ``corpse_grace_s=0`` restores the old eager sweep (tests)."""
    CHECK(retain >= 1, "gc_checkpoints retain must be >= 1")
    removed: List[str] = []
    if not os.path.isdir(root):
        return removed
    versions = list_checkpoints(root)
    valid = [p for _s, p in versions if verify_checkpoint(p) is None]
    keep = set(valid[-retain:])
    for _step, path in versions:
        if path not in keep:
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
    for name in os.listdir(root):
        if ".tmp-" in name or ".old-" in name:
            corpse = os.path.join(root, name)
            age = _corpse_age_s(corpse)
            if age is None:
                continue  # a racing sweeper got it: not a double-sweep
            if age < corpse_grace_s:
                Log.Info(
                    "checkpoint gc: leaving young staging dir %s alone "
                    "(%.0fs < %.0fs grace — may be a sibling's in-flight "
                    "save)", corpse, age, corpse_grace_s,
                )
                continue
            shutil.rmtree(corpse, ignore_errors=True)
            removed.append(corpse)
    if removed:
        Log.Info("checkpoint gc: removed %d entr(y/ies) under %s", len(removed), root)
    return removed


# ------------------------------------------------------------ array ckpts


def save_checkpoint(
    root: str,
    step: int,
    *,
    arrays: Optional[Dict[str, Any]] = None,
    meta: Optional[Dict] = None,
    write_payload: Optional[Callable[[str], None]] = None,
) -> str:
    """Publish ``<root>/ckpt-<step>`` atomically.

    Payload is a flat name->array dict (written as ``arrays.npz``), a
    caller-supplied ``write_payload(tmp_dir)`` (e.g. a model's own binary
    dump), or both. ``meta`` rides in the manifest — step counter, data
    cursor, restart count: everything elastic resume needs beyond the
    arrays themselves."""
    import numpy as np

    CHECK(arrays is not None or write_payload is not None,
          "save_checkpoint needs arrays and/or write_payload")
    os.makedirs(root, exist_ok=True)
    final = os.path.join(root, f"{_PREFIX}{int(step)}")
    tmp = f"{final}.tmp-{uuid.uuid4().hex[:8]}"
    os.makedirs(tmp)
    try:
        if write_payload is not None:
            write_payload(tmp)
        if arrays is not None:
            np.savez(
                os.path.join(tmp, "arrays.npz"),
                **{k: np.asarray(v) for k, v in arrays.items()},
            )
        return commit_atomic(tmp, final, step=step, meta=meta)
    except chaos.ChaosInterrupt:
        raise  # the tmp corpse IS the fixture the tests want
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_checkpoint(directory: str) -> Tuple[Dict[str, Any], Dict]:
    """(arrays, meta) from a ``save_checkpoint`` directory. Verifies
    first; a torn/corrupt directory dies with one clear error."""
    import numpy as np

    manifest = require_valid(directory)
    arrays: Dict[str, Any] = {}
    npz = os.path.join(directory, "arrays.npz")
    if os.path.exists(npz):
        with np.load(npz, allow_pickle=False) as data:
            arrays = {k: data[k] for k in data.files}
    return arrays, dict(manifest.get("meta") or {})


# ------------------------------------------------------------ policy


class CheckpointPolicy:
    """When to checkpoint: ``every_n_steps`` and/or ``every_n_seconds``
    (either may be 0 = off; both 0 = never). Injectable clock for tests."""

    def __init__(
        self,
        every_n_steps: int = 0,
        every_n_seconds: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.every_n_steps = int(every_n_steps)
        self.every_n_seconds = float(every_n_seconds)
        self._clock = clock
        self._last_t = clock()
        self._last_step: Optional[int] = None

    @property
    def enabled(self) -> bool:
        return self.every_n_steps > 0 or self.every_n_seconds > 0

    def due(self, step: int) -> bool:
        if self._last_step == step:
            return False  # one decision per step
        if self.every_n_steps > 0 and step % self.every_n_steps == 0:
            return True
        if (
            self.every_n_seconds > 0
            and self._clock() - self._last_t >= self.every_n_seconds
        ):
            return True
        return False

    def record(self, step: int) -> None:
        self._last_t = self._clock()
        self._last_step = step


class AutoCheckpointer:
    """Policy-driven checkpointing, off the training thread.

    ``maybe_save(step, build)``: when the policy says so, ``build()`` runs
    ON the training thread (snapshot device state to host there — the
    next step may donate those buffers) and must return a zero-arg job
    that performs the actual ``save_checkpoint`` write; with
    ``async_=True`` (default) the job runs on a worker thread while
    training continues. A save that is still writing when the next one
    comes due makes the new one a no-op (never a backlog). Failures are
    recorded (``last_error``, Dashboard save_failures) and logged — a
    broken disk must not kill the training run it exists to protect."""

    def __init__(
        self,
        root: str,
        *,
        every_n_steps: int = 0,
        every_n_seconds: float = 0.0,
        retain: int = 3,
        async_: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.root = root
        self.retain = int(retain)
        self.policy = CheckpointPolicy(every_n_steps, every_n_seconds, clock)
        self.async_ = bool(async_)
        self.last_error: Optional[BaseException] = None
        self.saves = 0
        # the async writer thread mutates saves/last_error while the
        # training thread polls them (mvlint R9)
        self._state_lock = OrderedLock("checkpointer._state_lock")
        self._thread: Optional[threading.Thread] = None

    def maybe_save(self, step: int, build: Callable[[], Callable[[], str]]) -> bool:
        """Returns True when a save was started (or completed, sync)."""
        if not self.policy.enabled or not self.policy.due(step):
            return False
        if self._thread is not None and self._thread.is_alive():
            Log.Info(
                "checkpoint at step %d skipped: previous save still writing",
                step,
            )
            return False
        job = build()
        self.policy.record(step)
        if self.async_:
            self._thread = threading.Thread(
                target=self._run, args=(step, job), daemon=True,
                name="mv-checkpointer",
            )
            self._thread.start()
        else:
            self._run(step, job)
            with self._state_lock:
                err = self.last_error
            if err is not None:
                raise err
        return True

    def _run(self, step: int, job: Callable[[], str]) -> None:
        try:
            path = job()
            gc_checkpoints(self.root, self.retain)
            with self._state_lock:
                self.saves += 1
                self.last_error = None
            stats.note_save(step, path)
            Log.Info("checkpoint published: %s (step %d)", path, step)
        except BaseException as e:  # noqa: BLE001 — surface, don't kill training
            with self._state_lock:
                self.last_error = e
            stats.note_save_failure()
            Log.Error("checkpoint save at step %d FAILED: %s", step, e)

    def wait(self, timeout_s: float = 60.0) -> None:
        th = self._thread
        if th is not None:
            th.join(timeout=timeout_s)

    def close(self, timeout_s: float = 60.0) -> None:
        self.wait(timeout_s)


# ------------------------------------------------------------ stats


class _ResilienceStats:
    """Process-wide fault-tolerance counters, surfaced on the Dashboard
    next to the serving health section: restart count (from resume meta),
    checkpoint saves/failures, and the age of the last good checkpoint —
    the number an operator actually pages on."""

    def __init__(self) -> None:
        # OrderedLock, not threading.Lock: this is an import-time
        # singleton, and a stdlib lock born before mvtsan arms is
        # invisible to the race detector — the counter updates would
        # report as unordered (see DEPLOY.md "Race detector"). The
        # owned primitive is tracked for its whole lifetime.
        self._lock = OrderedLock("checkpoint.resilience_stats")
        self.restarts = 0
        self.saves = 0
        self.save_failures = 0
        self.last_checkpoint_t: Optional[float] = None
        self.last_checkpoint_step: Optional[int] = None
        self.last_checkpoint_path: Optional[str] = None

    def _register(self) -> None:
        # lazy + keyed: survives Dashboard.Reset() by re-adding on next note
        from multiverso_tpu.utils.dashboard import Dashboard

        Dashboard.add_section("resilience", self.lines,
                              snapshot=self.to_dict)

    def note_save(self, step: int, path: str) -> None:
        with self._lock:
            self.saves += 1
            self.last_checkpoint_t = time.monotonic()
            self.last_checkpoint_step = step
            self.last_checkpoint_path = path
        self._register()

    def note_save_failure(self) -> None:
        with self._lock:
            self.save_failures += 1
        self._register()

    def note_restart(self, restarts: int) -> None:
        with self._lock:
            self.restarts = int(restarts)
        self._register()

    def last_checkpoint_age_s(self) -> Optional[float]:
        with self._lock:
            if self.last_checkpoint_t is None:
                return None
            return time.monotonic() - self.last_checkpoint_t

    def to_dict(self) -> Dict[str, Any]:
        """The health/bench view of the same record (``/healthz`` embeds
        it next to the serving and failure_domain sections)."""
        age = self.last_checkpoint_age_s()
        with self._lock:
            return {
                "restarts": self.restarts,
                "saves": self.saves,
                "save_failures": self.save_failures,
                "last_checkpoint_step": self.last_checkpoint_step,
                "last_checkpoint_path": self.last_checkpoint_path,
                "last_checkpoint_age_s": (
                    None if age is None else round(age, 1)
                ),
            }

    def lines(self) -> List[str]:
        age = self.last_checkpoint_age_s()
        with self._lock:
            return [
                f"[Resilience] restarts={self.restarts} saves={self.saves} "
                f"save_failures={self.save_failures} "
                f"last_ckpt_step={self.last_checkpoint_step} "
                f"last_ckpt_age_s={-1.0 if age is None else round(age, 1)}"
            ]


stats = _ResilienceStats()
