"""Per-endpoint outlier ejection: take gray-failing replicas out of
client rotation.

The route ``CircuitBreaker`` protects a *server* from its own failing
routes; this is the client-side twin. A replica that is alive but
useless — resetting connections, timing out, or answering 30x slower
than its peers (the classic gray failure a /healthz probe never sees)
— keeps absorbing a share of every client's attempts and drags fleet
p99 with it. ``OutlierEjector`` scores each endpoint with EWMAs of its
error rate and its success latency (relative to the MEDIAN of all
endpoints' latency EWMAs — a shared mean would be polluted by the
outlier's own samples, which at EWMA weight alpha put a floor of
``alpha * L`` under the baseline and make a constant-latency outlier
mathematically un-ejectable) and ejects an outlier from rotation;
after ``cooldown_s`` it
half-opens and admits exactly one probe — success recovers the
endpoint, failure re-ejects it for another cooldown. The state machine
and the ``peek``/``allow``/``record`` calling convention deliberately
mirror ``resilience/breaker.py`` so both sides of the contract read the
same way (DEPLOY.md's runbook spells out the split: breakers shed a
*route*, ejection skips an *endpoint*).

The ejector never decides fail-closed on its own: a caller whose every
endpoint is ejected is expected to fail open (``ServingClient`` uses
the full list again — permanently blacklisting the whole fleet would
fight the supervisor's self-healing, exactly like the client's
no-permanent-blacklist rule for single failures).

Deterministic by construction: state moves only on ``peek``/``allow``/
``record`` calls, the clock is injectable, there are no background
threads. Transitions land in the flight recorder
(``outlier_eject`` / ``outlier_probe`` / ``outlier_recover``) and on an
optional ``on_transition`` callback — the fleet drill routes that into
``fleet.log.jsonl`` so one file shows the eject→probe→recover cycle
next to the replica kills.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["OutlierEjector"]


class _EndpointScore:
    __slots__ = ("err", "lat", "n", "state", "since")

    def __init__(self) -> None:
        self.err = 0.0        # EWMA of the failure indicator (0/1)
        self.lat = 0.0        # EWMA of success latency (seconds)
        self.n = 0            # outcomes observed since last recovery
        self.state = "ok"     # ok | ejected | probing
        self.since = 0.0      # clock() of the last ejection


class OutlierEjector:
    """EWMA error-rate + latency-outlier ejection with half-open
    probing.

    * ``record(key, ok, latency_s)`` — one attempt outcome. Trips the
      ejection when, after ``min_samples`` outcomes, the error EWMA
      crosses ``error_threshold`` OR the endpoint's success-latency
      EWMA exceeds ``latency_factor``× the median of the per-endpoint
      latency EWMAs (with an absolute ``min_latency_s`` floor so
      loopback noise can never eject; with a single endpoint the
      median IS its own EWMA, so latency ejection never fires — there
      is no peer to be an outlier against).
    * ``peek(key)`` — non-mutating admission check (rotation filter).
    * ``allow(key)`` — like ``peek`` but claims the single half-open
      probe slot when the cooldown has elapsed; the caller that got
      ``True`` on a recovering endpoint MUST follow with ``record``.
    """

    def __init__(
        self,
        *,
        error_threshold: float = 0.5,
        latency_factor: float = 3.0,
        min_latency_s: float = 0.010,
        min_samples: int = 5,
        cooldown_s: float = 5.0,
        alpha: float = 0.3,
        clock: Callable[[], float] = time.monotonic,
        name: str = "client",
        on_transition: Optional[Callable[..., None]] = None,
    ):
        assert 0.0 < alpha <= 1.0 and min_samples >= 1
        self.error_threshold = float(error_threshold)
        self.latency_factor = float(latency_factor)
        self.min_latency_s = float(min_latency_s)
        self.min_samples = int(min_samples)
        self.cooldown_s = float(cooldown_s)
        self.alpha = float(alpha)
        self.name = name
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._scores: Dict[str, _EndpointScore] = {}

    # ------------------------------------------------------------ events

    def _note(self, kind: str, endpoint: str, **fields: Any) -> None:
        """Flight-recorder breadcrumb + optional callback, OUTSIDE the
        lock (mirrors ``CircuitBreaker._note_transition``)."""
        from multiverso_tpu.obs.flight import recorder

        recorder.record(kind, ejector=self.name, endpoint=endpoint,
                        **fields)
        if self._on_transition is not None:
            try:
                self._on_transition(kind, endpoint=endpoint, **fields)
            except Exception:  # noqa: BLE001 — an observer must never
                pass           # break the data path

    # ------------------------------------------------------------ score

    def _score(self, key: str) -> _EndpointScore:
        s = self._scores.get(key)
        if s is None:
            s = _EndpointScore()
            self._scores[key] = s
        return s

    def _baseline_lat(self) -> float:
        """Median of the per-endpoint success-latency EWMAs (caller
        holds the lock). Robust by construction: one gray endpoint
        cannot drag the baseline it is judged against."""
        lats = sorted(s.lat for s in self._scores.values() if s.lat > 0.0)
        if not lats:
            return 0.0
        mid = len(lats) // 2
        if len(lats) % 2:
            return lats[mid]
        return 0.5 * (lats[mid - 1] + lats[mid])

    def record(self, key: str, ok: bool, latency_s: float = 0.0) -> None:
        """One attempt outcome for ``key``; drives ejection and probe
        resolution."""
        now = self._clock()
        note = None
        with self._lock:
            s = self._score(key)
            if s.state == "probing":
                # this outcome IS the probe verdict
                if ok:
                    s.state = "ok"
                    s.err = 0.0
                    s.n = 0
                    note = ("outlier_recover", {})
                else:
                    s.state = "ejected"
                    s.since = now
                    note = ("outlier_eject", {"probe_failed": True})
            else:
                a = self.alpha
                s.err = a * (0.0 if ok else 1.0) + (1.0 - a) * s.err
                if ok and latency_s > 0.0:
                    s.lat = (a * latency_s + (1.0 - a) * s.lat
                             if s.lat > 0.0 else latency_s)
                s.n += 1
                if s.state == "ok" and s.n >= self.min_samples:
                    baseline = self._baseline_lat()
                    lat_floor = max(
                        self.min_latency_s,
                        self.latency_factor * baseline,
                    )
                    slow = (self.latency_factor > 0.0
                            and baseline > 0.0
                            and s.lat > lat_floor)
                    if s.err >= self.error_threshold or slow:
                        s.state = "ejected"
                        s.since = now
                        note = ("outlier_eject", {
                            "err_ewma": round(s.err, 4),
                            "lat_ewma_ms": round(s.lat * 1e3, 3),
                            "fleet_lat_ms": round(baseline * 1e3, 3),
                            "slow": bool(slow),
                        })
        if note is not None:
            self._note(note[0], key, **note[1])

    # ------------------------------------------------------------ admit

    def peek(self, key: str) -> bool:
        """Non-mutating: is ``key`` currently in rotation? An ejected
        endpoint past its cooldown reads as admissible (a probe
        candidate); a probe already in flight does not."""
        now = self._clock()
        with self._lock:
            s = self._scores.get(key)
            if s is None or s.state == "ok":
                return True
            if s.state == "probing":
                return False
            return now - s.since >= self.cooldown_s

    def allow(self, key: str) -> bool:
        """Admission that claims the half-open probe slot: an ejected
        endpoint past cooldown transitions to ``probing`` and admits
        exactly this caller; everyone else sees False until the probe's
        ``record`` resolves it."""
        now = self._clock()
        note = None
        with self._lock:
            s = self._scores.get(key)
            if s is None or s.state == "ok":
                return True
            if s.state == "probing":
                out = False
            elif now - s.since >= self.cooldown_s:
                s.state = "probing"
                note = ("outlier_probe", {})
                out = True
            else:
                out = False
        if note is not None:
            self._note(note[0], key, **note[1])
        return out

    # ------------------------------------------------------------ read

    def state(self, key: str) -> str:
        with self._lock:
            s = self._scores.get(key)
            return s.state if s is not None else "ok"

    def ejected(self) -> List[str]:
        with self._lock:
            return sorted(
                k for k, s in self._scores.items() if s.state != "ok"
            )

    def forget(self, key: str) -> None:
        """Drop an endpoint's score entirely (it vanished from the
        endpoint source — a drained replica, not an outage)."""
        with self._lock:
            self._scores.pop(key, None)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                k: {
                    "state": s.state,
                    "err_ewma": round(s.err, 4),
                    "lat_ewma_ms": round(s.lat * 1e3, 3),
                    "samples": s.n,
                }
                for k, s in sorted(self._scores.items())
            }
