"""Self-healing pod supervisor: the piece that closes the failure loop.

Every prior resilience layer ends at "the survivor exits rc 42 with a
valid drained quorum checkpoint" (PR 5) — and then a human relaunches
the pod. This module is that human: ``PodSupervisor`` launches the N
worker processes of one pod, watches their return codes, their heartbeat
beacons and the ``FAILURE-round<k>.json`` reports the containment path
publishes, and on any rank failure relaunches the whole pod from
``latest_valid`` — either with a *replacement rank* at the same world
size (the bit-for-bit resume path) or *degraded to N-1* (the elastic
re-shard path, ``restore_tables(reshard=True)``), exactly what
production parameter-server pods do.

Restart storms are bounded: each relaunch waits a full-jitter
exponential backoff (``chaos.FullJitterBackoff`` — the same schedule
``with_retries`` uses) and a sliding restart budget (at most
``max_restarts`` restarts inside ``restart_window_s``) turns a
crash-looping pod into a structured give-up report instead of an
infinite loop. Every decision lands in a JSONL *recovery log*
(``recovery.log.jsonl`` next to the checkpoints) with wall + monotonic
stamps, which is also where the MTTR bench reads detection /
relaunch / time-to-ready from.

The supervisor is deliberately **jax-free**: it must stay alive and
sane when every worker is wedged inside a collective, so it never
touches the accelerator runtime itself. Worker liveness is judged the
same way the in-process watchdog judges peers — age since the last NEW
beacon on the supervisor's own clock — so a worker that is alive-but-
hung (no rc, no beacons) is killed and relaunched too, not waited on
forever.

Deployment front-end: ``deploy/supervised.py`` wraps any flag-driven
worker command line (``{rank}``/``{world}``/``{coordinator}``
placeholders, or automatic ``-process_id/-num_processes/-coordinator``
injection) — see DEPLOY.md "Self-healing pods".
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from multiverso_tpu.analysis.guards import OrderedLock
from multiverso_tpu.resilience.chaos import FullJitterBackoff
from multiverso_tpu.resilience.checkpoint import latest_valid
from multiverso_tpu.resilience.watchdog import _PEER_DEATH_SIGNATURES
from multiverso_tpu.utils.log import CHECK, Log

__all__ = ["PodSupervisor", "PodResult", "RestartBudget", "free_port",
           "GENERATION_ENV"]

# exported to every worker so chaos drills can fire in generation 0 only
# (the relaunch must not re-kill itself) and logs can be tagged
GENERATION_ENV = "MV_SUPERVISOR_GENERATION"

# transport-layer crash signatures: the watchdog's peer-death family IS
# the infra list (its "gloo"/"barrier" substrings subsume the cluster
# test launcher's longer markers after lowercasing) — a child whose log
# tail matches died of the transport, not its own logic; the recovery
# log records the classification so an operator can tell infra churn
# from real failures
_INFRA_SIGNATURES = _PEER_DEATH_SIGNATURES


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class RestartBudget:
    """At most ``max_restarts`` restarts inside a sliding
    ``window_s``-second window; every restart draws a full-jitter backoff
    delay from the shared ``with_retries`` schedule."""

    def __init__(self, max_restarts: int = 5, window_s: float = 600.0,
                 base_delay_s: float = 0.5, max_delay_s: float = 30.0,
                 seed: int = 0, clock: Callable[[], float] = time.monotonic):
        CHECK(max_restarts >= 0, "max_restarts must be >= 0")
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self._clock = clock
        self._stamps: List[float] = []
        # shared between the fleet watch thread and direct callers: the
        # prune-then-append window scan is a read-modify-write
        self._stamps_lock = OrderedLock("restart_budget._stamps_lock")
        self._backoff = FullJitterBackoff(base_delay_s, max_delay_s,
                                          seed=seed)

    def _prune_locked(self) -> None:
        now = self._clock()
        self._stamps = [t for t in self._stamps if now - t <= self.window_s]

    def exhausted(self) -> bool:
        with self._stamps_lock:
            self._prune_locked()
            return len(self._stamps) >= self.max_restarts

    def spend(self) -> float:
        """Record one restart; returns the backoff delay to wait before
        it. Caller checks ``exhausted()`` first."""
        with self._stamps_lock:
            self._prune_locked()
            attempt = len(self._stamps)
            self._stamps.append(self._clock())
        # the jitter draw takes the backoff's own lock: keep it outside
        return self._backoff.next_delay(attempt)

    def used(self) -> int:
        with self._stamps_lock:
            self._prune_locked()
            return len(self._stamps)


@dataclass
class PodResult:
    ok: bool
    gave_up: bool
    generations: int
    restarts: int
    final_world: int
    reason: str
    events: List[Dict[str, Any]] = field(default_factory=list)


class PodSupervisor:
    """Launch + babysit one training pod; relaunch it from the latest
    valid checkpoint on any rank failure.

    ``make_argv(rank, world, generation, coordinator)`` builds each
    worker's command line; workers must exit 0 on success. ``on_failure``
    picks the recovery shape: ``"replace"`` relaunches at the same world
    size (a replacement rank joins; elastic resume is bit-for-bit),
    ``"degrade"`` drops to world-1 per failure down to ``min_world``
    (elastic re-shard resume; convergence-equivalent). Heartbeat files
    under ``heartbeat_dir`` (the workers' ``-heartbeat_dir``) give the
    supervisor a wedge detector: a worker with a live pid but no new
    beacon for ``heartbeat_deadline_s`` is killed and counted as failed.
    Ready markers (``MV_READY_FILE``, touched by
    ``serving.http_health.set_ready``) stamp the pod_ready event MTTR is
    measured to."""

    def __init__(
        self,
        make_argv: Callable[[int, int, int, str], List[str]],
        *,
        world: int,
        checkpoint_dir: Optional[str] = None,
        heartbeat_dir: Optional[str] = None,
        heartbeat_deadline_s: float = 0.0,
        ready_dir: Optional[str] = None,
        on_failure: str = "replace",
        min_world: int = 1,
        max_restarts: int = 5,
        restart_window_s: float = 600.0,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        seed: int = 0,
        poll_s: float = 0.2,
        exit_grace_s: float = 10.0,
        log_dir: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        CHECK(world >= 1, "world must be >= 1")
        CHECK(on_failure in ("replace", "degrade"),
              f"on_failure must be 'replace' or 'degrade', got {on_failure!r}")
        CHECK(1 <= min_world <= world, "need 1 <= min_world <= world")
        self.make_argv = make_argv
        self.world = int(world)
        self.checkpoint_dir = checkpoint_dir
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_deadline_s = float(heartbeat_deadline_s)
        self.ready_dir = ready_dir
        self.on_failure = on_failure
        self.min_world = int(min_world)
        self.budget = RestartBudget(
            max_restarts, restart_window_s, backoff_base_s, backoff_max_s,
            seed=seed, clock=clock,
        )
        self.poll_s = float(poll_s)
        self.exit_grace_s = float(exit_grace_s)
        self.log_dir = log_dir or checkpoint_dir
        self.extra_env = dict(env or {})
        self._clock = clock
        self._sleep = sleep
        self.events: List[Dict[str, Any]] = []
        self._seen_reports: set = set()

    # ------------------------------------------------------ recovery log

    def _event(self, event_kind: str, **fields) -> Dict[str, Any]:
        ev = {"event": event_kind, "wall": time.time(),
              "mono": self._clock(), **fields}
        self.events.append(ev)
        Log.Info("[supervisor] %s %s", event_kind,
                 json.dumps(fields, default=str, sort_keys=True))
        if self.log_dir:
            try:
                os.makedirs(self.log_dir, exist_ok=True)
                with open(os.path.join(self.log_dir, "recovery.log.jsonl"),
                          "a") as f:
                    f.write(json.dumps(ev, default=str) + "\n")
            except OSError as e:
                Log.Error("[supervisor] recovery log write failed: %s", e)
        return ev

    # ------------------------------------------------------ child helpers

    def _child_log_path(self, gen: int, rank: int) -> Optional[str]:
        if not self.log_dir:
            return None
        return os.path.join(self.log_dir, f"worker-g{gen}-r{rank}.log")

    def _spawn(self, gen: int, world: int) -> List[Dict[str, Any]]:
        coord = f"127.0.0.1:{free_port()}"
        self._event("launch", generation=gen, world=world, coordinator=coord)
        children = []
        for rank in range(world):
            env = {**os.environ, **self.extra_env,
                   GENERATION_ENV: str(gen)}
            if self.ready_dir:
                os.makedirs(self.ready_dir, exist_ok=True)
                env["MV_READY_FILE"] = os.path.join(
                    self.ready_dir, f"ready-g{gen}-r{rank}.json"
                )
                try:  # a PRIOR supervisor run's marker must not make
                    # pod_ready fire while this worker is still restoring
                    os.remove(env["MV_READY_FILE"])
                except OSError:
                    pass
            log_path = self._child_log_path(gen, rank)
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
            out = open(log_path, "wb") if log_path else subprocess.DEVNULL
            proc = subprocess.Popen(
                self.make_argv(rank, world, gen, coord),
                stdout=out, stderr=subprocess.STDOUT, env=env,
                start_new_session=True,  # one killpg reaps grandchildren
            )
            if log_path:
                out.close()  # the child holds its own handle now
            children.append({
                "rank": rank, "proc": proc, "log": log_path,
                "hb_seq": -1, "hb_seen": self._clock(),
                "ready_file": env.get("MV_READY_FILE"),
            })
        return children

    @staticmethod
    def _kill(children: List[Dict[str, Any]]) -> None:
        for c in children:
            proc = c["proc"]
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError, OSError):
                    proc.kill()
        for c in children:
            try:
                c["proc"].wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    def _classify(self, child: Dict[str, Any]) -> str:
        """Best-effort failure classification from the child's log tail:
        'infra' (transport-layer crash — the gloo gremlin the cluster
        tests retry on), 'rank_failure' (structured containment ran) or
        'crash'."""
        path = child.get("log")
        if not path or not os.path.exists(path):
            return "crash"
        try:
            with open(path, "rb") as f:
                f.seek(max(0, os.path.getsize(path) - 65536))
                tail = f.read().decode("utf-8", "replace")
        except OSError:
            return "crash"
        low = tail.lower()
        if "rank_failure" in low or "rankfailure" in low:
            return "rank_failure"
        if any(sig.lower() in low for sig in _INFRA_SIGNATURES):
            return "infra"
        return "crash"

    def _hb_beacon(self, rank: int) -> Optional[int]:
        if not self.heartbeat_dir:
            return None
        try:
            with open(os.path.join(self.heartbeat_dir,
                                   f"hb-{rank}.json")) as f:
                return int(json.load(f)["seq"])
        except (OSError, ValueError, KeyError):
            return None

    def _last_beacon_walls(self) -> Dict[str, float]:
        """Wall mtime of each rank's beacon file — the MTTR anchor
        (detection latency is measured from the dead rank's last beat)."""
        out: Dict[str, float] = {}
        if not self.heartbeat_dir or not os.path.isdir(self.heartbeat_dir):
            return out
        for name in os.listdir(self.heartbeat_dir):
            if name.startswith("hb-") and name.endswith(".json"):
                try:
                    out[name[3:-5]] = os.path.getmtime(
                        os.path.join(self.heartbeat_dir, name)
                    )
                except OSError:
                    pass
        return out

    def _collect_flight_recorders(self, gen: int) -> List[str]:
        """Move the ranks' ``flight-recorder-rank<p>.jsonl`` dumps (the
        containment path writes them next to the FAILURE report) into
        the recovery log dir, tagged with the failed generation — the
        next generation's containment must start from a clean slate, and
        the post-mortem wants the rings keyed by failure, not
        overwritten by it."""
        out: List[str] = []
        src_dir = self.checkpoint_dir
        if not src_dir or not os.path.isdir(src_dir) or not self.log_dir:
            return out
        for name in sorted(os.listdir(src_dir)):
            if not (name.startswith("flight-recorder-rank")
                    and name.endswith(".jsonl")):
                continue
            dst = os.path.join(
                self.log_dir,
                name.replace(".jsonl", f"-g{gen}.jsonl"),
            )
            try:
                import shutil

                os.makedirs(self.log_dir, exist_ok=True)
                if os.path.exists(dst):
                    os.remove(dst)
                shutil.move(os.path.join(src_dir, name), dst)
                out.append(dst)
            except OSError as e:
                Log.Error("[supervisor] flight recorder collect failed "
                          "for %s: %s", name, e)
        return out

    def _new_failure_reports(self) -> List[str]:
        if not self.checkpoint_dir or not os.path.isdir(self.checkpoint_dir):
            return []
        fresh = []
        for name in sorted(os.listdir(self.checkpoint_dir)):
            if name.startswith("FAILURE-") and name.endswith(".json") \
                    and name not in self._seen_reports:
                self._seen_reports.add(name)
                fresh.append(os.path.join(self.checkpoint_dir, name))
        return fresh

    # ------------------------------------------------------ the main loop

    def _watch(self, children: List[Dict[str, Any]], gen: int
               ) -> Optional[Dict[str, Any]]:
        """Block until the pod exits cleanly (returns None) or a failure
        is detected (returns the failure record). Detection sources: a
        nonzero child rc, a live-but-silent child past the heartbeat
        deadline (wedged), a published FAILURE report."""
        ready_logged = False
        first_bad: Optional[Dict[str, Any]] = None
        first_bad_t = 0.0
        report_pending: Optional[Dict[str, Any]] = None
        report_t = 0.0
        while True:
            now = self._clock()
            for c in children:
                rc = c["proc"].poll()
                if rc is not None and rc != 0 and first_bad is None:
                    first_bad = {"rank": c["rank"], "rc": rc,
                                 "kind": self._classify(c)}
                    first_bad_t = now
                seq = self._hb_beacon(c["rank"])
                if seq is not None and seq != c["hb_seq"]:
                    c["hb_seq"], c["hb_seen"] = seq, now
                elif (
                    first_bad is None
                    and self.heartbeat_deadline_s > 0
                    and c["hb_seq"] >= 0  # deadline arms at FIRST beacon:
                    # startup (jax import + rendezvous + a host-side
                    # elastic restore of tier-scale tables) legitimately
                    # exceeds any sane deadline, and the in-process
                    # watchdog is not even running yet — a rank that dies
                    # during startup is caught by its rc, not by silence
                    and c["proc"].poll() is None
                    and now - c["hb_seen"] > self.heartbeat_deadline_s
                ):
                    first_bad = {"rank": c["rank"], "rc": None,
                                 "kind": "wedged"}
                    first_bad_t = now
            if not ready_logged and self.ready_dir and all(
                c["ready_file"] and os.path.exists(c["ready_file"])
                for c in children
            ):
                ready_logged = True
                self._event("pod_ready", generation=gen,
                            world=len(children))
            reports = self._new_failure_reports()
            for rep in reports:
                self._event("failure_report", generation=gen, path=rep)
                if report_pending is None:
                    report_pending = {"rank": -1, "rc": None,
                                      "kind": "failure_report",
                                      "report": rep}
                    report_t = now
            if (
                first_bad is None
                and report_pending is not None
                and now - report_t >= self.exit_grace_s
            ):
                # the third detection channel: containment published a
                # FAILURE report but no child produced an rc within the
                # grace — the publisher is wedged (e.g. a distributed
                # teardown blocking on the dead peer) and must be killed
                # and relaunched, not waited on (an rc arriving inside
                # the grace takes precedence below, as usual)
                first_bad = report_pending
                first_bad_t = now
            if first_bad is None and all(
                c["proc"].poll() == 0 for c in children
            ):
                return None  # clean pod exit
            if first_bad is not None:
                # short grace for siblings to land their own structured
                # exits (the survivor's rc-42 containment), then reap
                done = all(c["proc"].poll() is not None for c in children)
                if done or now - first_bad_t >= self.exit_grace_s:
                    return first_bad
            self._sleep(self.poll_s)

    def run(self) -> PodResult:
        gen = 0
        world = self.world
        restarts = 0
        while True:
            if self.heartbeat_dir and os.path.isdir(self.heartbeat_dir):
                # a previous generation's beacons must not look live
                for name in os.listdir(self.heartbeat_dir):
                    if name.startswith("hb-"):
                        try:
                            os.remove(os.path.join(self.heartbeat_dir, name))
                        except OSError:
                            pass
            children = self._spawn(gen, world)
            failure = self._watch(children, gen)
            if failure is None:
                self._event("healthy_exit", generation=gen, world=world,
                            restarts=restarts)
                return PodResult(
                    ok=True, gave_up=False, generations=gen + 1,
                    restarts=restarts, final_world=world,
                    reason="pod exited cleanly", events=self.events,
                )
            beacons = self._last_beacon_walls()
            self._kill(children)
            # absorb any report published between the last poll and the
            # kill: it belongs to THIS failure, and must not arm the
            # report channel against the next (healthy) generation
            self._new_failure_reports()
            rcs = {c["rank"]: c["proc"].poll() for c in children}
            resume_from = (
                latest_valid(self.checkpoint_dir)
                if self.checkpoint_dir else None
            )
            self._event(
                "failure_detected", generation=gen, world=world,
                rank=failure["rank"], rc=failure["rc"],
                kind=failure["kind"], rcs=rcs, resume_from=resume_from,
                last_beacon_walls=beacons,
            )
            # collect the ranks' flight-recorder dumps into the recovery
            # log dir, keyed by the failed generation (obs subsystem)
            collected = self._collect_flight_recorders(gen)
            if collected:
                self._event(
                    "flight_recorder_collected", generation=gen,
                    paths=collected,
                )
            if self.budget.exhausted():
                report = {
                    "gave_up": True,
                    "restarts_in_window": self.budget.used(),
                    "max_restarts": self.budget.max_restarts,
                    "restart_window_s": self.budget.window_s,
                    "last_failure": failure,
                    "resume_from": resume_from,
                    "world": world,
                    "generations": gen + 1,
                }
                self._event("give_up", **report)
                if self.log_dir:
                    try:
                        with open(os.path.join(self.log_dir,
                                               "RECOVERY-GIVEUP.json"),
                                  "w") as f:
                            json.dump(report, f, indent=1, default=str)
                    except OSError:
                        pass
                return PodResult(
                    ok=False, gave_up=True, generations=gen + 1,
                    restarts=restarts, final_world=world,
                    reason=(
                        f"restart budget exhausted: {self.budget.used()} "
                        f"restarts in {self.budget.window_s:.0f}s"
                    ),
                    events=self.events,
                )
            delay = self.budget.spend()
            restarts += 1
            next_world = world
            if self.on_failure == "degrade":
                next_world = max(self.min_world, world - 1)
            self._event(
                "relaunch", generation=gen + 1, world=next_world,
                policy=self.on_failure, backoff_s=round(delay, 3),
                resume_from=resume_from,
            )
            self._sleep(delay)
            world = next_world
            gen += 1
