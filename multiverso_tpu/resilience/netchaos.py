"""Network chaos proxy: deterministic TCP fault injection for serving.

Process-level chaos (``resilience/chaos.py`` — kills, route errors,
torn checkpoints) never touches the *wire*: until this module, the
binary data plane and its keep-alive pool had only ever seen a loopback
that delivers every byte instantly and in order. Real networks deliver
tail latency, partitions, half-open connections and corrupted segments
— the gray failures that kill p99 at scale. ``NetChaosProxy`` is a
stdlib-threaded TCP proxy that fronts any replica ``-data_port`` and
injects exactly those faults, deterministically (seeded xorshift32 —
the same PRNG family as ``chaos.FullJitterBackoff``), so ci drills and
tests can script a partition the way they script a kill.

Fault schedule (a ``FaultSpec``; every field independent, all off by
default). The direction mapping is fixed so one small flag surface
stays unambiguous:

* ``latency_ms`` + ``jitter_ms`` — added delay per forwarded chunk on
  the **server→client** direction (a slow replica: the request arrives,
  the response straggles). Jitter is uniform in ``[0, jitter_ms)``,
  drawn from the per-connection PRNG.
* ``bandwidth_kbps`` — throttle on the server→client direction
  (chunked pacing sleep after each forward).
* ``reset_after_bytes`` — once the connection has forwarded this many
  bytes (both directions combined), both sockets are closed with
  ``SO_LINGER 0``: the peer sees a hard RST mid-stream, not a FIN.
* ``blackhole`` — ``"c2s"`` / ``"s2c"`` / ``"both"``: bytes in the
  blackholed direction are read and silently dropped (a partition: the
  TCP connection stays up, data never arrives). A connection *accepted*
  during a ``"both"`` blackhole is never connected upstream at all —
  the client's connect succeeds (the kernel completed the handshake)
  and then nothing ever answers, which is exactly what a partitioned
  endpoint looks like behind a balancer.
* ``corrupt_offset`` + ``corrupt_mode`` — at byte N of the
  **client→server** stream either flip one bit (``"bitflip"``) or stop
  forwarding and close (``"truncate"``): a corrupted / truncated
  request frame that the server must answer 400 and survive.
* ``stall_s`` — accept-then-stall: hold the accepted socket this long
  before connecting upstream (the slow-loris shape, server side).

**Scheduling.** Faults come from three layers, strongest first: a
runtime override (``set_faults`` / ``clear_faults`` — what tests and
drills flip mid-traffic), the active phase of a JSON scenario, and the
proxy-wide default spec. A scenario is::

    {"phases": [
      {"start_s": 0,  "end_s": 10, "faults": {"latency_ms": 150}},
      {"start_s": 10, "end_s": 15, "faults": {"blackhole": "both"}}
    ]}

with phase times measured from proxy start on the injectable clock
(tests flip phases with a fake clock, zero sleeps). ``ci.sh``'s
netchaos drill scripts its tail-latency + partition scenario this way.

**Flags** (the CLI entry point — ``python -m
multiverso_tpu.resilience.netchaos -netchaos_upstream=host:port``):
``-netchaos_listen_port``, ``-netchaos_seed``, ``-netchaos_scenario``
(JSON file) and one flag per ``FaultSpec`` field for scenario-less use.

Everything is stdlib sockets + threads: no asyncio, no dependencies,
deterministic byte accounting (``stats()``).
"""

from __future__ import annotations

import json
import os
import select
import socket
import struct
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from multiverso_tpu.utils.configure import (
    MV_DEFINE_double,
    MV_DEFINE_int,
    MV_DEFINE_string,
    GetFlag,
    ParseCMDFlags,
)
from multiverso_tpu.utils.log import CHECK, Log

__all__ = [
    "FaultSpec",
    "Scenario",
    "NetChaosProxy",
    "proxy_from_flags",
    "main",
]

MV_DEFINE_string(
    "netchaos_upstream", "",
    "netchaos proxy: host:port of the replica data plane to front — the "
    "proxy forwards every accepted connection there with the armed "
    "faults injected (required by the CLI entry point)",
)
MV_DEFINE_int(
    "netchaos_listen_port", 0,
    "netchaos proxy: listen port clients connect to (0 = ephemeral; the "
    "bound port is logged and returned by proxy_from_flags)",
)
MV_DEFINE_int(
    "netchaos_seed", 0,
    "netchaos proxy: seed for the per-connection xorshift32 PRNG — the "
    "same seed + scenario + traffic replays the same jitter draws",
)
MV_DEFINE_string(
    "netchaos_scenario", "",
    "netchaos proxy: JSON scenario file of timed fault phases "
    "({'phases': [{'start_s', 'end_s', 'faults': {...}}]}, clocked from "
    "proxy start) — how ci.sh scripts a tail-latency window followed by "
    "a partition (empty = the per-fault flags below apply always)",
)
MV_DEFINE_double(
    "netchaos_latency_ms", 0.0,
    "netchaos proxy: added delay per forwarded chunk, server->client "
    "(a slow replica; 0 = off)",
)
MV_DEFINE_double(
    "netchaos_jitter_ms", 0.0,
    "netchaos proxy: uniform extra delay in [0, jitter_ms) on top of "
    "-netchaos_latency_ms, drawn from the seeded per-connection PRNG",
)
MV_DEFINE_double(
    "netchaos_bandwidth_kbps", 0.0,
    "netchaos proxy: throttle the server->client direction to this "
    "many kilobytes/second (0 = unthrottled)",
)
MV_DEFINE_int(
    "netchaos_reset_after_bytes", -1,
    "netchaos proxy: hard-RST both sides of a connection (SO_LINGER 0) "
    "once it has forwarded this many bytes in total (-1 = off) — the "
    "connection-reset-at-byte-N fault",
)
MV_DEFINE_string(
    "netchaos_blackhole", "",
    "netchaos proxy: partition direction — c2s (requests vanish), s2c "
    "(responses vanish) or both (connections accepted during the fault "
    "never reach the upstream at all); empty = off",
)
MV_DEFINE_int(
    "netchaos_corrupt_offset", -1,
    "netchaos proxy: byte offset in the client->server stream where "
    "-netchaos_corrupt_mode strikes (-1 = off) — the corrupted-frame "
    "fault the 400 contract is drilled against",
)
MV_DEFINE_string(
    "netchaos_corrupt_mode", "bitflip",
    "netchaos proxy: what happens at -netchaos_corrupt_offset — "
    "bitflip (one bit of that byte inverts) or truncate (the stream "
    "stops there and the connection closes)",
)
MV_DEFINE_double(
    "netchaos_stall_s", 0.0,
    "netchaos proxy: accept-then-stall — hold every accepted socket "
    "this long before connecting upstream (slow-loris shape; 0 = off)",
)

_CHUNK = 16384
_BLACKHOLE_POLL_S = 0.05
_FAULT_FIELDS = (
    "latency_ms", "jitter_ms", "bandwidth_kbps", "reset_after_bytes",
    "blackhole", "corrupt_offset", "corrupt_mode", "stall_s",
)


class FaultSpec:
    """One connection-fault schedule; every field independent."""

    __slots__ = _FAULT_FIELDS

    def __init__(self, latency_ms: float = 0.0, jitter_ms: float = 0.0,
                 bandwidth_kbps: float = 0.0, reset_after_bytes: int = -1,
                 blackhole: str = "", corrupt_offset: int = -1,
                 corrupt_mode: str = "bitflip", stall_s: float = 0.0):
        CHECK(blackhole in ("", "c2s", "s2c", "both"),
              f"blackhole must be ''|c2s|s2c|both, got {blackhole!r}")
        CHECK(corrupt_mode in ("bitflip", "truncate"),
              f"corrupt_mode must be bitflip|truncate, got {corrupt_mode!r}")
        self.latency_ms = float(latency_ms)
        self.jitter_ms = float(jitter_ms)
        self.bandwidth_kbps = float(bandwidth_kbps)
        self.reset_after_bytes = int(reset_after_bytes)
        self.blackhole = str(blackhole)
        self.corrupt_offset = int(corrupt_offset)
        self.corrupt_mode = str(corrupt_mode)
        self.stall_s = float(stall_s)

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "FaultSpec":
        unknown = set(doc) - set(_FAULT_FIELDS)
        CHECK(not unknown, f"unknown fault fields: {sorted(unknown)}")
        return cls(**doc)

    def clean(self) -> bool:
        return (self.latency_ms <= 0.0 and self.jitter_ms <= 0.0
                and self.bandwidth_kbps <= 0.0
                and self.reset_after_bytes < 0 and not self.blackhole
                and self.corrupt_offset < 0 and self.stall_s <= 0.0)

    def to_dict(self) -> Dict[str, Any]:
        return {f: getattr(self, f) for f in _FAULT_FIELDS}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        on = {f: v for f, v in self.to_dict().items()
              if v not in (0.0, -1, "", "bitflip")}
        return f"FaultSpec({on or 'clean'})"


class Scenario:
    """Timed fault phases, evaluated against the proxy's uptime."""

    def __init__(self, phases: List[Tuple[float, float, FaultSpec]]):
        self.phases = list(phases)

    @classmethod
    def from_doc(cls, doc: Any) -> "Scenario":
        phases_doc = doc.get("phases", []) if isinstance(doc, dict) else doc
        phases = []
        for p in phases_doc:
            phases.append((
                float(p.get("start_s", 0.0)),
                float(p.get("end_s", float("inf"))),
                FaultSpec.from_dict(dict(p.get("faults", {}))),
            ))
        return cls(phases)

    @classmethod
    def load(cls, path: str) -> "Scenario":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_doc(json.load(f))

    def active(self, uptime_s: float) -> Optional[FaultSpec]:
        """The last phase covering ``uptime_s`` (later phases win), or
        ``None`` when no phase is active."""
        hit = None
        for start, end, spec in self.phases:
            if start <= uptime_s < end:
                hit = spec
        return hit


class _XorShift32:
    """The chaos module's deterministic PRNG, one instance per
    connection: seed + connection index fully determine every jitter
    draw, so a replayed drill replays its delays."""

    def __init__(self, seed: int):
        self._state = (int(seed) & 0xFFFFFFFF) or 0x9E3779B9
        # one rng is shared by a connection's two pump threads; the
        # state advance must be atomic or draws can repeat/corrupt
        self._mu = threading.Lock()

    def uniform(self) -> float:
        with self._mu:
            x = self._state
            x ^= (x << 13) & 0xFFFFFFFF
            x ^= x >> 17
            x ^= (x << 5) & 0xFFFFFFFF
            self._state = x
        return x / 4294967296.0


def _no_nagle(sock: socket.socket) -> None:
    """Disable Nagle so the proxy's extra hop is transparent: forwarded
    request/response frames are small, and Nagle + delayed ACK would
    tax every one of them with a ~40ms stall the real path never pays."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass


def _hard_reset(sock: Optional[socket.socket]) -> None:
    """Close with SO_LINGER 0 — the peer sees RST, not FIN."""
    if sock is None:
        return
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER,
            struct.pack("ii", 1, 0),
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class NetChaosProxy:
    """Fault-injecting TCP proxy in front of one upstream endpoint.

    ``port=0`` binds ephemeral (read ``.port`` / ``.url`` back).
    ``clock`` paces the scenario phases only — byte forwarding always
    uses real sockets. Use as a context manager or call ``stop()``."""

    def __init__(self, upstream_host: str, upstream_port: int, *,
                 host: str = "127.0.0.1", port: int = 0, seed: int = 0,
                 scenario: Optional[Scenario] = None,
                 faults: Optional[FaultSpec] = None,
                 name: str = "netchaos",
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.upstream = (upstream_host, int(upstream_port))
        self.name = name
        self.seed = int(seed)
        self.scenario = scenario
        self._default = faults or FaultSpec()
        self._override: Optional[FaultSpec] = None
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._stats = {
            "connections": 0, "active": 0, "bytes_c2s": 0, "bytes_s2c": 0,
            "resets": 0, "corrupted": 0, "truncated": 0,
            "blackholed_bytes": 0, "blackholed_conns": 0,
            "stalled_conns": 0, "upstream_errors": 0,
        }
        self._stopping = threading.Event()
        self._conns: List[Tuple[socket.socket, Optional[socket.socket]]] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self.host = host
        self.port = int(self._listener.getsockname()[1])
        self._t0 = self._clock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"mv-{name}-accept",
        )
        self._accept_thread.start()

    # ------------------------------------------------------------ control

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def set_faults(self, spec: Optional[FaultSpec] = None,
                   **fields: Any) -> FaultSpec:
        """Arm a runtime fault override (wins over the scenario and the
        default spec). Pass a ``FaultSpec`` or keyword fields."""
        if spec is None:
            spec = FaultSpec(**fields)
        with self._lock:
            self._override = spec
        return spec

    def clear_faults(self) -> None:
        with self._lock:
            self._override = None

    def current_faults(self) -> FaultSpec:
        """The spec in effect right now: override > scenario phase >
        proxy default."""
        with self._lock:
            if self._override is not None:
                return self._override
        if self.scenario is not None:
            hit = self.scenario.active(self._clock() - self._t0)
            if hit is not None:
                return hit
        return self._default

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._stats[key] += n

    def stop(self) -> None:
        self._stopping.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns = []
        for c, s in conns:
            for sock in (c, s):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
        self._accept_thread.join(timeout=5)

    def __enter__(self) -> "NetChaosProxy":
        return self

    def __exit__(self, *_exc: Any) -> None:
        self.stop()

    # ------------------------------------------------------------ accept

    def _accept_loop(self) -> None:
        idx = 0
        while not self._stopping.is_set():
            try:
                client, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutting down
            idx += 1
            self._bump("connections")
            rng = _XorShift32(self.seed ^ (idx * 0x9E3779B1))
            t = threading.Thread(
                target=self._serve_conn, args=(client, rng), daemon=True,
                name=f"mv-{self.name}-conn{idx}",
            )
            t.start()

    def _serve_conn(self, client: socket.socket, rng: _XorShift32) -> None:
        self._bump("active")
        server: Optional[socket.socket] = None
        try:
            # a transparent proxy must not ADD latency the wire didn't
            # order: with Nagle on, the store-and-forward hop turns each
            # small HTTP frame into a ~40ms delayed-ACK stall
            _no_nagle(client)
            spec = self.current_faults()
            if spec.stall_s > 0.0:
                self._bump("stalled_conns")
                self._sleep(spec.stall_s)
            # accepted mid-partition: never connect upstream — sit on
            # the socket discarding anything the client sends until the
            # fault clears or the client gives up (what a partitioned
            # endpoint looks like: connect succeeds, nothing answers)
            if spec.blackhole == "both":
                self._bump("blackholed_conns")
                if not self._hold_blackholed(client):
                    return
            try:
                server = socket.create_connection(self.upstream, timeout=10)
                _no_nagle(server)
            except OSError:
                self._bump("upstream_errors")
                _hard_reset(client)
                return
            with self._lock:
                self._conns.append((client, server))
            # shared per-connection byte budget for reset_after_bytes
            shared = {"fwd": 0, "reset": False}
            lock = threading.Lock()
            t = threading.Thread(
                target=self._pump, args=(
                    client, server, "c2s", rng, shared, lock
                ), daemon=True, name=f"mv-{self.name}-c2s",
            )
            t.start()
            self._pump(server, client, "s2c", rng, shared, lock)
            t.join(timeout=5)
        finally:
            for sock in (client, server):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            self._bump("active", -1)

    def _hold_blackholed(self, client: socket.socket) -> bool:
        """Park a connection accepted during a full partition. Returns
        True when the fault cleared with the client still there (the
        connection then proceeds upstream), False when the client hung
        up or the proxy is stopping."""
        while not self._stopping.is_set():
            spec = self.current_faults()
            if spec.blackhole != "both":
                return True
            try:
                r, _w, _x = select.select([client], [], [],
                                          _BLACKHOLE_POLL_S)
            except (OSError, ValueError):
                return False
            if r:
                # re-check before consuming: bytes that arrived AFTER
                # the fault cleared belong to the healed connection (a
                # real network would retransmit them) — leave them in
                # the kernel buffer for the pump to forward
                if self.current_faults().blackhole != "both":
                    return True
                try:
                    data = client.recv(_CHUNK)
                except OSError:
                    return False
                if not data:
                    return False  # client gave up
                self._bump("blackholed_bytes", len(data))
        return False

    # ------------------------------------------------------------ pump

    def _pump(self, src: socket.socket, dst: socket.socket,
              direction: str, rng: _XorShift32,
              shared: Dict[str, Any], lock: threading.Lock) -> None:
        """Forward ``src`` -> ``dst`` applying the live fault spec per
        chunk. ``direction`` is ``"c2s"`` (requests: corruption point)
        or ``"s2c"`` (responses: latency/throttle point)."""
        seen = 0  # bytes read from src on this direction
        try:
            while not self._stopping.is_set():
                try:
                    data = src.recv(_CHUNK)
                except OSError:
                    break
                if not data:
                    # half-close: propagate the FIN so the peer's read
                    # completes instead of hanging until its timeout
                    try:
                        dst.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    break
                spec = self.current_faults()
                if spec.blackhole in (direction, "both"):
                    self._bump("blackholed_bytes", len(data))
                    continue
                if spec.corrupt_offset >= 0 and direction == "c2s":
                    data, stop = self._corrupt(data, seen, spec)
                    if stop:
                        seen += len(data)
                        if data:
                            try:
                                dst.sendall(data)
                            except OSError:
                                pass
                        self._bump("truncated")
                        with lock:
                            shared["reset"] = True
                        _hard_reset(src)
                        _hard_reset(dst)
                        break
                seen += len(data)
                if direction == "s2c":
                    delay = spec.latency_ms * 1e-3
                    if spec.jitter_ms > 0.0:
                        delay += rng.uniform() * spec.jitter_ms * 1e-3
                    if delay > 0.0:
                        self._sleep(delay)
                    if spec.bandwidth_kbps > 0.0:
                        self._sleep(
                            len(data) / (spec.bandwidth_kbps * 1024.0)
                        )
                try:
                    dst.sendall(data)
                except OSError:
                    break
                self._bump(f"bytes_{direction}", len(data))
                if spec.reset_after_bytes >= 0:
                    with lock:
                        shared["fwd"] += len(data)
                        fire = (not shared["reset"]
                                and shared["fwd"] >= spec.reset_after_bytes)
                        if fire:
                            shared["reset"] = True
                    if fire:
                        self._bump("resets")
                        _hard_reset(src)
                        _hard_reset(dst)
                        break
        finally:
            pass

    def _corrupt(self, data: bytes, seen: int,
                 spec: FaultSpec) -> Tuple[bytes, bool]:
        """Apply the corrupt-at-offset fault to one chunk whose first
        byte sits at stream offset ``seen``. Returns ``(data, stop)``:
        ``stop`` means truncate-here (forward the prefix, then RST)."""
        off = spec.corrupt_offset
        if off < seen or off >= seen + len(data):
            return data, False
        i = off - seen
        if spec.corrupt_mode == "truncate":
            return data[:i], True
        self._bump("corrupted")
        flipped = bytes([data[i] ^ 0x10])
        return data[:i] + flipped + data[i + 1:], False


# ---------------------------------------------------------------- flags


def _faults_from_flags() -> FaultSpec:
    return FaultSpec(
        latency_ms=float(GetFlag("netchaos_latency_ms")),
        jitter_ms=float(GetFlag("netchaos_jitter_ms")),
        bandwidth_kbps=float(GetFlag("netchaos_bandwidth_kbps")),
        reset_after_bytes=int(GetFlag("netchaos_reset_after_bytes")),
        blackhole=str(GetFlag("netchaos_blackhole")),
        corrupt_offset=int(GetFlag("netchaos_corrupt_offset")),
        corrupt_mode=str(GetFlag("netchaos_corrupt_mode")),
        stall_s=float(GetFlag("netchaos_stall_s")),
    )


def proxy_from_flags() -> NetChaosProxy:
    """Build the proxy the ``-netchaos_*`` flags describe (the CLI
    entry point and flag-driven drills)."""
    upstream = str(GetFlag("netchaos_upstream"))
    CHECK(":" in upstream,
          "-netchaos_upstream must be host:port (the replica data port "
          "the proxy fronts)")
    host, _, port_s = upstream.rpartition(":")
    scenario_path = str(GetFlag("netchaos_scenario"))
    scenario = Scenario.load(scenario_path) if scenario_path else None
    return NetChaosProxy(
        host, int(port_s),
        port=int(GetFlag("netchaos_listen_port")),
        seed=int(GetFlag("netchaos_seed")),
        scenario=scenario,
        faults=_faults_from_flags(),
    )


def main(argv: Optional[List[str]] = None) -> int:
    leftover = ParseCMDFlags(list(sys.argv if argv is None else argv))
    if len(leftover) > 1:
        Log.Error("netchaos: unrecognised argv %s", leftover[1:])
        return 2
    proxy = proxy_from_flags()
    Log.Info(
        "netchaos: %s -> %s:%d (pid %d)",
        proxy.url, proxy.upstream[0], proxy.upstream[1], os.getpid(),
    )
    stop = threading.Event()
    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
