"""Per-route circuit breaker: shed fast when a route keeps failing.

An online server whose route throws on every flush still pays the full
queue -> batch -> dispatch cost per request, turning one bad route (a
poisoned table, a chaos drill, an OOM-ing program) into whole-server
latency collapse. The breaker converts repeated failure into *fast*
failure: after ``threshold`` consecutive failures the route opens and
requests are rejected immediately with a retry-after hint; after
``cooldown_s`` it half-opens and admits exactly one probe — success
closes it, failure re-opens it for another cooldown.

Deterministic by construction: state moves only on ``allow`` /
``record_*`` calls, the clock is injectable, and there are no background
threads — tests drive transitions with a fake clock, never a sleep.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Tuple

__all__ = ["CircuitBreaker"]


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing.

    States: ``closed`` (traffic flows; failures counted), ``open``
    (reject with retry-after = remaining cooldown), ``half_open`` (one
    in-flight probe admitted; the rest rejected until it resolves).
    """

    def __init__(
        self,
        threshold: int = 5,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
    ):
        assert threshold >= 1
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    def _note_transition(self, prev: str, new: str) -> None:
        """Flight-recorder breadcrumb for every state change — the shed
        storm's timeline next to the rank/round events. Called OUTSIDE
        the breaker lock."""
        if prev == new:
            return
        from multiverso_tpu.obs.flight import recorder

        recorder.record(
            "breaker_transition", breaker=self.name, prev=prev, new=new
        )

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> Tuple[bool, float]:
        """(admitted, retry_after_s). Admitting from ``open`` past the
        cooldown transitions to ``half_open`` and claims the probe slot —
        the caller that got True MUST follow with record_success/failure."""
        now = self._clock()
        trans = None
        with self._lock:
            if self._state == "closed":
                out = (True, 0.0)
            elif self._state == "open":
                elapsed = now - self._opened_at
                if elapsed < self.cooldown_s:
                    out = (False, self.cooldown_s - elapsed)
                else:
                    trans = ("open", "half_open")
                    self._state = "half_open"
                    self._probe_inflight = True
                    out = (True, 0.0)
            # half_open: one probe at a time
            elif self._probe_inflight:
                out = (False, self.cooldown_s)
            else:
                self._probe_inflight = True
                out = (True, 0.0)
        if trans is not None:
            self._note_transition(*trans)
        return out

    def peek(self) -> Tuple[bool, float]:
        """Like ``allow`` but WITHOUT claiming the half-open probe slot or
        mutating state — the submit-time fast-shed check. A request that
        passes ``peek`` may still be rejected by the flush-side ``allow``
        (someone else took the probe); that is the intended funnel."""
        now = self._clock()
        with self._lock:
            if self._state == "closed":
                return True, 0.0
            if self._state == "open":
                elapsed = now - self._opened_at
                if elapsed < self.cooldown_s:
                    return False, self.cooldown_s - elapsed
                return True, 0.0  # cooldown over: let a probe candidate in
            if self._probe_inflight:
                return False, self.cooldown_s
            return True, 0.0

    def record_success(self) -> None:
        with self._lock:
            prev = self._state
            self._state = "closed"
            self._failures = 0
            self._probe_inflight = False
        self._note_transition(prev, "closed")

    def record_failure(self) -> None:
        now = self._clock()
        trans = None
        with self._lock:
            self._probe_inflight = False
            if self._state == "half_open":
                self._state = "open"  # probe failed: full new cooldown
                self._opened_at = now
                trans = ("half_open", "open")
            else:
                self._failures += 1
                if self._failures >= self.threshold:
                    if self._state != "open":
                        trans = (self._state, "open")
                    self._state = "open"
                    self._opened_at = now
        if trans is not None:
            self._note_transition(*trans)
