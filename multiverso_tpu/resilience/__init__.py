"""Fault-tolerance layer: crash-consistent checkpoints, elastic resume,
deterministic fault injection, bounded retries, and serving degradation.

The reference parameter server survives worker churn by design — workers
are stateless against sharded server tables (ref: SURVEY.md §2.2) — but
the TPU-native SPMD port concentrates all state in one program. This
package makes process death, torn checkpoint writes and poisoned weight
publishes *normal*, tested events:

* ``resilience.checkpoint`` — atomic manifest-sealed checkpoint publish,
  ``latest_valid`` discovery that skips torn/corrupt versions, retention
  GC, and the ``AutoCheckpointer``/``CheckpointPolicy`` pieces the
  training loops wire in (``io/checkpoint.save_tables`` commits through
  the same machinery);
* ``resilience.chaos`` — ``MV_DEFINE_*``-armed seedable fault points
  (kill-at-step, torn writer, checksum corruption, route errors, failed
  rendezvous) plus ``with_retries`` (jittered exponential backoff under a
  hard deadline) used by the multihost rendezvous and checkpoint I/O;
* ``resilience.breaker`` — the per-route circuit breaker the
  ``TableServer`` sheds through when a route keeps failing;
* ``resilience.watchdog`` — the distributed failure-domain layer:
  per-rank liveness beacons + per-ticket collective deadlines that turn
  a hung/dead peer into a structured ``RankFailure`` (and poisoned-pipe
  ``PipelineBroken`` fail-fast) instead of a silent cluster-wide hang,
  plus the ``failure_domain`` Dashboard/health stats;
* ``resilience.supervisor`` — the self-healing pod supervisor that
  closes the loop: launches the pod, watches child rcs / heartbeat
  beacons / FAILURE reports, and relaunches from ``latest_valid`` with
  a replacement rank (bit-for-bit) or degraded to N-1 (elastic
  re-shard), under a full-jitter restart budget with a structured
  recovery log.

The same primitives run unchanged on the read path: the serving fleet
(``serving.fleet``) supervises replicas under ``RestartBudget``, the
fleet client (``serving.client``) retries through ``FullJitterBackoff``,
and each replica's ``SnapshotWatcher`` discovers rollout candidates via
``latest_valid`` — training-side robustness reused as serving-side
robustness.
"""

from multiverso_tpu.resilience.breaker import CircuitBreaker
from multiverso_tpu.resilience.chaos import (
    ChaosInterrupt,
    FullJitterBackoff,
    with_retries,
)
from multiverso_tpu.resilience.supervisor import (
    PodResult,
    PodSupervisor,
    RestartBudget,
)
from multiverso_tpu.resilience.watchdog import (
    HeartbeatMonitor,
    PipelineBroken,
    QuorumAbort,
    RankFailure,
    fd_stats,
)
from multiverso_tpu.resilience.checkpoint import (
    AutoCheckpointer,
    CheckpointPolicy,
    gc_checkpoints,
    latest_valid,
    list_checkpoints,
    load_checkpoint,
    require_valid,
    save_checkpoint,
    stats,
    verify_checkpoint,
)

__all__ = [
    "AutoCheckpointer",
    "ChaosInterrupt",
    "CheckpointPolicy",
    "CircuitBreaker",
    "FullJitterBackoff",
    "HeartbeatMonitor",
    "PipelineBroken",
    "PodResult",
    "PodSupervisor",
    "QuorumAbort",
    "RankFailure",
    "RestartBudget",
    "fd_stats",
    "gc_checkpoints",
    "latest_valid",
    "list_checkpoints",
    "load_checkpoint",
    "require_valid",
    "save_checkpoint",
    "stats",
    "verify_checkpoint",
    "with_retries",
]
