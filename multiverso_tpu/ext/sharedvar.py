"""Per-variable sync handles — the ``mv_shared`` pattern.

Reference semantics (ref: binding/python/multiverso/theano_ext/
sharedvar.py:12-102): a *single* model variable gets its own ArrayTable;
``mv_sync()`` pushes ``current - last_synced`` (the accumulated local
update, usually gradients) and pulls the latest merged value back. The
reference wraps a theano ``SharedVariable``; there is no theano here, so
the TPU-native analog wraps a plain mutable ndarray holder with the same
``get_value``/``set_value`` surface — any host training loop (numpy,
optax states materialized to host, torch tensors via ``.numpy()``) can
drive it. The whole-model granularity of this pattern lives in
``ext/param_manager.py``; this is the single-variable convenience.

Typical use::

    w = mv_shared(np.zeros((256, 10), np.float32))
    for batch in data:
        w.set_value(w.get_value() - lr * grad(batch, w.get_value()))
        if step % sync_every == 0:
            w.mv_sync()            # push delta, pull merged
    # or sync every registered variable at once:
    sync_all_mv_shared_vars()
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from multiverso_tpu.api import MV_Barrier
from multiverso_tpu.binding.tables import ArrayTableHandler

__all__ = ["MVSharedVariable", "mv_shared", "sync_all_mv_shared_vars"]


class MVSharedVariable:
    """One variable, one ArrayTable, delta sync (ref: sharedvar.py:12-50).

    Construction creates the table with this variable's value as
    ``init_value`` (master's value wins — the handler's master-init
    protocol), barriers, then pulls the table back so every worker starts
    identical. ``mv_sync()`` adds ``value - last_synced`` and refreshes
    the local value from the merged table state.
    """

    def __init__(self, value, name: Optional[str] = None):
        arr = np.ascontiguousarray(value, np.float32)
        self.name = name
        self._shape = arr.shape
        self._table = ArrayTableHandler(arr.size, init_value=arr.reshape(-1))
        MV_Barrier()  # initial value must have taken effect everywhere
        self._value = self._table.get().reshape(self._shape).copy()
        self._last = self._value.copy()

    def get_value(self) -> np.ndarray:
        return self._value.copy()

    def set_value(self, value) -> None:
        arr = np.ascontiguousarray(value, np.float32)
        if arr.shape != self._shape:
            raise ValueError(f"shape {arr.shape} != {self._shape}")
        self._value = arr.copy()

    @property
    def shape(self):
        return self._shape

    def mv_sync(self) -> None:
        """Push the local delta, pull the merged value (ref:
        sharedvar.py:37-50 — add(value - last), then get())."""
        self._table.add((self._value - self._last).reshape(-1))
        self._value = self._table.get().reshape(self._shape).copy()
        self._last = self._value.copy()


def mv_shared(value, name: Optional[str] = None) -> MVSharedVariable:
    """Create AND register a shared variable (ref: sharedvar.py:80-92 —
    the reference registers every ``mv_shared`` call for
    ``sync_all_mv_shared_vars``)."""
    sv = MVSharedVariable(value, name=name)
    mv_shared.shared_vars.append(sv)
    return sv


mv_shared.shared_vars: List[MVSharedVariable] = []


def sync_all_mv_shared_vars() -> None:
    """Sync every variable created through ``mv_shared`` (ref:
    sharedvar.py:95-102)."""
    for sv in mv_shared.shared_vars:
        sv.mv_sync()
