"""Model-parameter managers: flatten a model's params into ONE ArrayTable.

Reference semantics (ref: binding/python/multiverso/theano_ext/
param_manager.py:9-82, sharedvar.py:12-102):

* construction flattens every parameter into a single float32 vector, creates
  an ArrayTable initialised with it (master's value wins), barriers, then
  pulls the table back into the model — so all workers start identical;
* ``sync_all_param()`` pushes ``current - last_synced`` as a delta, pulls the
  latest table value, and writes it back into the model (ASGD model sync);
* the Keras extension's ``MVCallback`` synced on_batch_end
  (ref: theano_ext/keras_ext/callbacks.py:21-39) — generalised here as
  ``PeriodicSync``.

Two concrete managers: ``PytreeParamManager`` (any jax pytree — flax/optax
state included) and ``TorchParamManager`` (torch.nn.Module, CPU tensors).
"""

from __future__ import annotations

from typing import Any, List, Sequence

import numpy as np

from multiverso_tpu.api import MV_Barrier
from multiverso_tpu.binding.tables import ArrayTableHandler

__all__ = [
    "MVModelParamManager",
    "PytreeParamManager",
    "TorchParamManager",
    "PeriodicSync",
]


class MVModelParamManager:
    """Abstract manager (ref: param_manager.py:9-82). Subclasses implement
    get_all_param_values / set_all_param_values."""

    def __init__(self, model: Any):
        self.model = model
        self.shapes: List[tuple] = []
        self.sizes: List[int] = []
        flat_parts = []
        for arr in self.get_all_param_values():
            arr = np.asarray(arr, np.float32)
            self.shapes.append(arr.shape)
            self.sizes.append(arr.size)
            flat_parts.append(arr.reshape(-1))
        self.all_param_list = (
            np.concatenate(flat_parts) if flat_parts else np.zeros(0, np.float32)
        )
        self.tbh = ArrayTableHandler(
            len(self.all_param_list), init_value=self.all_param_list
        )
        MV_Barrier()  # make sure the initial values have taken effect
        self.all_param_list = self.tbh.get()
        self._set_all_param_to_model()

    # -- subclass contract -------------------------------------------------

    def get_all_param_values(self) -> Sequence[np.ndarray]:
        raise NotImplementedError

    def set_all_param_values(self, params: Sequence[np.ndarray]) -> None:
        raise NotImplementedError

    # -- sync --------------------------------------------------------------

    def _set_all_param_to_model(self) -> None:
        n = 0
        params = []
        for shape, size in zip(self.shapes, self.sizes):
            params.append(self.all_param_list[n : n + size].reshape(shape))
            n += size
        self.set_all_param_values(params)

    def sync_all_param(self) -> None:
        """Push local delta, pull the merged value (ref: param_manager.py:71-82)."""
        cur = np.concatenate(
            [np.asarray(a, np.float32).reshape(-1) for a in self.get_all_param_values()]
        ) if self.sizes else np.zeros(0, np.float32)
        self.tbh.add(cur - self.all_param_list)
        self.all_param_list = self.tbh.get()
        self._set_all_param_to_model()


class PytreeParamManager(MVModelParamManager):
    """Manager over any jax pytree (flax params / optax state / plain dicts).
    ``manager.params`` holds the live tree; sync writes pulled values back."""

    def __init__(self, tree: Any):
        import jax

        self._treedef = None
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        self._leaves = [np.asarray(l) for l in leaves]
        # the transport table is float32 (reference limitation —
        # param_manager.py:30-33); preserve each leaf's dtype on write-back
        self._dtypes = [l.dtype for l in self._leaves]
        self._treedef = treedef
        super().__init__(model=None)

    @property
    def params(self) -> Any:
        import jax

        return jax.tree_util.tree_unflatten(self._treedef, list(self._leaves))

    @params.setter
    def params(self, tree: Any) -> None:
        import jax

        from multiverso_tpu.utils.log import CHECK

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        CHECK(treedef == self._treedef, "pytree structure changed")
        self._leaves = [np.asarray(l) for l in leaves]

    def get_all_param_values(self) -> Sequence[np.ndarray]:
        return list(self._leaves)

    def set_all_param_values(self, params: Sequence[np.ndarray]) -> None:
        self._leaves = [
            np.asarray(p).astype(dt) for p, dt in zip(params, self._dtypes)
        ]


class TorchParamManager(MVModelParamManager):
    """Manager over a torch.nn.Module (CPU) — the torch/lua-binding analog
    (ref: binding/lua/* table handlers used the same delta-push protocol)."""

    def get_all_param_values(self) -> Sequence[np.ndarray]:
        return [
            p.detach().cpu().numpy().astype(np.float32)
            for p in self.model.parameters()
        ]

    def set_all_param_values(self, params: Sequence[np.ndarray]) -> None:
        import torch

        with torch.no_grad():
            for p, v in zip(self.model.parameters(), params):
                p.copy_(torch.from_numpy(np.asarray(v)).to(p.dtype))


class PeriodicSync:
    """Sync every N steps (ref: keras_ext/callbacks.py:21-39 MVCallback
    synced every batch; N generalises the LogReg ``sync_frequency`` knob)."""

    def __init__(self, manager: MVModelParamManager, every: int = 1):
        from multiverso_tpu.utils.log import CHECK

        CHECK(every >= 1, "PeriodicSync requires every >= 1")
        self.manager = manager
        self.every = every
        self._step = 0

    def step(self) -> bool:
        """Call once per training batch; returns True when a sync happened."""
        self._step += 1
        if self._step % self.every == 0:
            self.manager.sync_all_param()
            return True
        return False
