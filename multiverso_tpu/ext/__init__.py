"""Framework integration hooks — reference ``theano_ext`` family parity
(ref: binding/python/multiverso/theano_ext/**), rebuilt for today's stacks:
pytree/flax param managers and a torch module manager, plus the periodic-sync
callback the Keras extension provided."""

from multiverso_tpu.ext.param_manager import (
    MVModelParamManager,
    PeriodicSync,
    PytreeParamManager,
    TorchParamManager,
)

__all__ = [
    "MVModelParamManager",
    "PeriodicSync",
    "PytreeParamManager",
    "TorchParamManager",
]
