"""Framework integration hooks — reference ``theano_ext`` family parity
(ref: binding/python/multiverso/theano_ext/**), rebuilt for today's stacks:
pytree/flax param managers and a torch module manager, plus the periodic-sync
callback the Keras extension provided."""

from multiverso_tpu.ext.param_manager import (
    MVModelParamManager,
    PeriodicSync,
    PytreeParamManager,
    TorchParamManager,
)
from multiverso_tpu.ext.sharedvar import (
    MVSharedVariable,
    mv_shared,
    sync_all_mv_shared_vars,
)

__all__ = [
    "MVModelParamManager",
    "MVSharedVariable",
    "PeriodicSync",
    "PytreeParamManager",
    "TorchParamManager",
    "mv_shared",
    "sync_all_mv_shared_vars",
]
