"""NumPy-facing table handlers (ref: binding/python/multiverso/tables.py).

Reference semantics preserved:

* ``init_value`` is applied by a *synchronous Add* from the master worker
  (others add zeros) so that the value is committed when the constructor
  returns (ref: tables.py:50-57, 100-107). Single-controller: one sync add.
* ``add(data, sync=False)`` — async by default, ``sync=True`` blocks
  (ref: tables.py:69-81).
* ``MatrixTableHandler.get/add`` accept an optional row-id list
  (ref: tables.py:109-165).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from multiverso_tpu.tables import ArrayTableOption, MatrixTableOption, create_table
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils.log import CHECK

__all__ = ["ArrayTableHandler", "MatrixTableHandler"]


class ArrayTableHandler:
    """Sync a 1-D float32 value (ref: tables.py:38-81)."""

    def __init__(self, size: int, init_value: Optional[np.ndarray] = None):
        self._size = int(size)
        self._table = create_table(ArrayTableOption(size=self._size))
        if init_value is not None:
            from multiverso_tpu.binding import is_master_worker

            data = np.asarray(init_value, np.float32).reshape(-1)
            if is_master_worker():
                self.add(data, sync=True)
            else:  # pragma: no cover - multihost only
                self.add(np.zeros_like(data), sync=True)

    @property
    def table(self):
        return self._table

    def get(self) -> np.ndarray:
        return self._table.get()

    def add(self, data, sync: bool = False, option: Optional[AddOption] = None) -> None:
        data = np.asarray(data, np.float32).reshape(-1)
        CHECK(data.size == self._size, f"add size {data.size} != {self._size}")
        self._table.add(data, option)
        if sync:
            self._table.wait()


class MatrixTableHandler:
    """Sync a 2-D float32 value, whole or by rows (ref: tables.py:84-165)."""

    def __init__(
        self, num_row: int, num_col: int, init_value: Optional[np.ndarray] = None
    ):
        self._num_row, self._num_col = int(num_row), int(num_col)
        self._table = create_table(
            MatrixTableOption(num_row=self._num_row, num_col=self._num_col)
        )
        if init_value is not None:
            from multiverso_tpu.binding import is_master_worker

            data = np.asarray(init_value, np.float32).reshape(self._num_row, self._num_col)
            if is_master_worker():
                self.add(data, sync=True)
            else:  # pragma: no cover - multihost only
                self.add(np.zeros_like(data), sync=True)

    @property
    def table(self):
        return self._table

    def get(self, row_ids: Optional[Sequence[int]] = None) -> np.ndarray:
        if row_ids is None:
            return self._table.get()
        return self._table.get_rows(np.asarray(row_ids, np.int32))

    def add(
        self,
        data,
        row_ids: Optional[Sequence[int]] = None,
        sync: bool = False,
        option: Optional[AddOption] = None,
    ) -> None:
        data = np.asarray(data, np.float32)
        if row_ids is None:
            self._table.add(data.reshape(self._num_row, self._num_col), option)
        else:
            ids = np.asarray(row_ids, np.int32)
            self._table.add_rows(ids, data.reshape(len(ids), self._num_col), option)
        if sync:
            self._table.wait()
