"""Python binding layer — reference ``binding/python/multiverso`` parity.

The reference exposes ``multiverso.init/barrier/shutdown`` plus numpy-facing
table handlers over a ctypes-loaded C library
(ref: binding/python/multiverso/api.py:12-75, tables.py:38-165). Here the
core *is* Python, so the handlers wrap the table layer directly; the flat
C ABI for other languages lives in native/ (the dependency direction is
inverted relative to the reference — SURVEY.md §7 hard parts).
"""

from multiverso_tpu.api import (
    MV_Barrier as barrier,
    MV_Init,
    MV_NumServers,
    MV_NumWorkers,
    MV_Rank,
    MV_ShutDown,
    MV_WorkerId,
)
from multiverso_tpu.binding.tables import ArrayTableHandler, MatrixTableHandler

__all__ = [
    "init",
    "shutdown",
    "barrier",
    "workers_num",
    "worker_id",
    "server_num",
    "is_master_worker",
    "ArrayTableHandler",
    "MatrixTableHandler",
]


def init(sync: bool = False, **kwargs) -> None:
    """ref: api.py:12-34 — builds ``-sync=true`` style argv."""
    argv = [f"-sync={'true' if sync else 'false'}"]
    argv += [f"-{k}={v}" for k, v in kwargs.items()]
    MV_Init(argv)


def shutdown(finalize: bool = True) -> None:
    MV_ShutDown(finalize)


def workers_num() -> int:
    return MV_NumWorkers()


def worker_id() -> int:
    return MV_WorkerId()


def server_num() -> int:
    return MV_NumServers()


def is_master_worker() -> bool:
    """ref: api.py — the rank-0 worker owns initialisation."""
    return MV_Rank() == 0
