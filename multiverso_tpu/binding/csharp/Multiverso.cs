// C# binding for the TPU-native Multiverso framework.
//
// Mirrors the reference C++/CLI wrapper surface (ref:
// binding/C#/MultiversoCLR/MultiversoCLR.h:13-46) as a portable .NET
// P/Invoke binding over the flat C ABI (libmultiverso_c.so — see
// multiverso_tpu/capi/c_api.h). Unlike the reference's Windows-only CLR
// project this compiles anywhere .NET runs; tables are float32 (the C ABI's
// element type; the reference CLR wrapper likewise marshalled through the
// float C API for its eleType="float" path).
//
// NetBind/NetConnect front the jax.distributed cluster rendezvous (rank 0's
// endpoint becomes the coordinator), matching MV_NetBind/MV_NetConnect in
// the Python API; call both before Init on multi-host deployments.

using System;
using System.Collections.Generic;
using System.Runtime.InteropServices;

namespace MultiversoTpu
{
    internal static class Native
    {
        private const string Lib = "multiverso_c"; // libmultiverso_c.so

        [DllImport(Lib)] internal static extern void MV_Init(IntPtr argc, IntPtr argv);
        [DllImport(Lib)] internal static extern void MV_ShutDown();
        [DllImport(Lib)] internal static extern void MV_Barrier();
        [DllImport(Lib)] internal static extern int MV_NumWorkers();
        [DllImport(Lib)] internal static extern int MV_WorkerId();
        [DllImport(Lib)] internal static extern int MV_ServerId();
        [DllImport(Lib)] internal static extern void MV_NetBind(
            int rank, [MarshalAs(UnmanagedType.LPStr)] string endpoint);
        [DllImport(Lib)] internal static extern void MV_NetConnect(
            int[] ranks,
            [In, MarshalAs(UnmanagedType.LPArray, ArraySubType = UnmanagedType.LPStr)] string[] endpoints,
            int n);

        [DllImport(Lib)] internal static extern void MV_NewArrayTable(int size, out IntPtr handler);
        [DllImport(Lib)] internal static extern void MV_GetArrayTable(IntPtr handler, float[] data, int size);
        [DllImport(Lib)] internal static extern void MV_AddArrayTable(IntPtr handler, float[] data, int size);
        [DllImport(Lib)] internal static extern void MV_AddAsyncArrayTable(IntPtr handler, float[] data, int size);

        [DllImport(Lib)] internal static extern void MV_NewMatrixTable(int numRow, int numCol, out IntPtr handler);
        [DllImport(Lib)] internal static extern void MV_GetMatrixTableAll(IntPtr handler, float[] data, int size);
        [DllImport(Lib)] internal static extern void MV_AddMatrixTableAll(IntPtr handler, float[] data, int size);
        [DllImport(Lib)] internal static extern void MV_AddAsyncMatrixTableAll(IntPtr handler, float[] data, int size);
        [DllImport(Lib)] internal static extern void MV_GetMatrixTableByRows(IntPtr handler, float[] data, int size, int[] rowIds, int rowIdsN);
        [DllImport(Lib)] internal static extern void MV_AddMatrixTableByRows(IntPtr handler, float[] data, int size, int[] rowIds, int rowIdsN);
        [DllImport(Lib)] internal static extern void MV_AddAsyncMatrixTableByRows(IntPtr handler, float[] data, int size, int[] rowIds, int rowIdsN);
    }

    /// <summary>1-D dense float table handle (ref CLR IWorkerTable analog).</summary>
    public sealed class ArrayTableHandler
    {
        private readonly IntPtr _handler;
        public int Size { get; }

        public ArrayTableHandler(int size, float[] initValue = null)
        {
            Size = size;
            Native.MV_NewArrayTable(size, out _handler);
            if (initValue != null)
            {
                if (initValue.Length != size)
                    throw new ArgumentException("initValue length must equal table size");
                // master-init protocol: worker 0 adds the value, others zeros,
                // so sync-mode per-round add accounting stays aligned.
                var data = MultiversoWrapper.WorkerId() == 0 ? initValue : new float[size];
                Native.MV_AddArrayTable(_handler, data, size);
            }
        }

        public float[] Get()
        {
            var buf = new float[Size];
            Native.MV_GetArrayTable(_handler, buf, Size);
            return buf;
        }

        public void Add(float[] delta, bool sync = false)
        {
            if (delta.Length != Size)
                throw new ArgumentException("delta length must equal table size");
            if (sync) Native.MV_AddArrayTable(_handler, delta, Size);
            else Native.MV_AddAsyncArrayTable(_handler, delta, Size);
        }
    }

    /// <summary>2-D row-addressable float table handle.</summary>
    public sealed class MatrixTableHandler
    {
        private readonly IntPtr _handler;
        public int NumRow { get; }
        public int NumCol { get; }

        public MatrixTableHandler(int numRow, int numCol, float[] initValue = null)
        {
            NumRow = numRow;
            NumCol = numCol;
            Native.MV_NewMatrixTable(numRow, numCol, out _handler);
            if (initValue != null)
            {
                if (initValue.Length != numRow * numCol)
                    throw new ArgumentException("initValue must have NumRow*NumCol elements");
                var data = MultiversoWrapper.WorkerId() == 0 ? initValue : new float[initValue.Length];
                Native.MV_AddMatrixTableAll(_handler, data, data.Length);
            }
        }

        public float[] Get()
        {
            var buf = new float[NumRow * NumCol];
            Native.MV_GetMatrixTableAll(_handler, buf, buf.Length);
            return buf;
        }

        public float[] Get(int[] rowIds)
        {
            var buf = new float[rowIds.Length * NumCol];
            Native.MV_GetMatrixTableByRows(_handler, buf, buf.Length, rowIds, rowIds.Length);
            return buf;
        }

        public void Add(float[] delta, bool sync = false)
        {
            if (delta.Length != NumRow * NumCol)
                throw new ArgumentException("delta must have NumRow*NumCol elements");
            if (sync) Native.MV_AddMatrixTableAll(_handler, delta, delta.Length);
            else Native.MV_AddAsyncMatrixTableAll(_handler, delta, delta.Length);
        }

        public void Add(int[] rowIds, float[] delta, bool sync = false)
        {
            if (delta.Length != rowIds.Length * NumCol)
                throw new ArgumentException("delta must have rowIds.Length*NumCol elements");
            if (sync) Native.MV_AddMatrixTableByRows(_handler, delta, delta.Length, rowIds, rowIds.Length);
            else Native.MV_AddAsyncMatrixTableByRows(_handler, delta, delta.Length, rowIds, rowIds.Length);
        }
    }

    /// <summary>Static facade mirroring the reference MultiversoWrapper
    /// (ref: MultiversoCLR.h:13-46): Init/Shutdown/Barrier/Rank/Size plus
    /// table_id-indexed CreateTable/Get/Add over float tables.</summary>
    public static class MultiversoWrapper
    {
        private static readonly List<MatrixTableHandler> Tables = new List<MatrixTableHandler>();

        [DllImport("libc", SetLastError = true)]
        private static extern int setenv(string name, string value, int overwrite);

        private static void SetNativeEnv(string name, string value)
        {
            // Environment.SetEnvironmentVariable only updates the managed
            // environment block on .NET Core/Linux; the embedded CPython
            // reads the native environ, so set both.
            Environment.SetEnvironmentVariable(name, value);
            try { setenv(name, value, 1); } catch (EntryPointNotFoundException) { }
        }

        public static void Init(int numTables = 0, bool sync = false)
        {
            // flags travel via MULTIVERSO_ARGS (the embedded runtime parses
            // them at MV_Init; the C ABI takes no argv from P/Invoke hosts)
            if (sync)
            {
                var existing = Environment.GetEnvironmentVariable("MULTIVERSO_ARGS");
                var args = string.IsNullOrEmpty(existing) ? "-sync=true"
                                                          : existing + " -sync=true";
                SetNativeEnv("MULTIVERSO_ARGS", args);
            }
            Native.MV_Init(IntPtr.Zero, IntPtr.Zero);
        }

        public static void Shutdown() => Native.MV_ShutDown();
        public static void Barrier() => Native.MV_Barrier();
        public static int Rank() => Native.MV_WorkerId();
        public static int Size() => Native.MV_NumWorkers();
        public static int WorkerId() => Native.MV_WorkerId();
        public static int ServerId() => Native.MV_ServerId();

        public static void CreateTable(int tableId, int rows, int cols, string eleType = "float")
        {
            if (eleType != "float")
                throw new NotSupportedException("the C ABI exposes float32 tables");
            while (Tables.Count <= tableId) Tables.Add(null);
            Tables[tableId] = new MatrixTableHandler(rows, cols);
        }

        public static void CreateTables(int[] rows, int[] cols, string[] eleTypes)
        {
            for (int i = 0; i < rows.Length; i++)
                CreateTable(i, rows[i], cols[i], eleTypes[i]);
        }

        public static void Get(int tableId, float[] value) =>
            Array.Copy(Tables[tableId].Get(), value, value.Length);

        public static void Get(int tableId, int rowId, float[] value) =>
            Array.Copy(Tables[tableId].Get(new[] { rowId }), value, value.Length);

        public static void Add(int tableId, float[] update) =>
            Tables[tableId].Add(update, sync: true);

        public static void Add(int tableId, int rowId, float[] value) =>
            Tables[tableId].Add(new[] { rowId }, value, sync: true);

        public static bool NetBind(int rank, string endpoint)
        {
            Native.MV_NetBind(rank, endpoint);
            return true;
        }

        public static bool NetConnect(int[] ranks, string[] endpoints)
        {
            if (ranks.Length != endpoints.Length)
                throw new ArgumentException("ranks/endpoints length mismatch");
            Native.MV_NetConnect(ranks, endpoints, ranks.Length);
            return true;
        }

        public static void NetFinalize() { }
    }
}
