// C# binding smoke test — the reference's multi-worker arithmetic
// invariants through the P/Invoke binding (same assertions as
// binding/lua/test.lua and ref Test/test_array_table.cpp:26-47).
//
// Build & run (see tests/test_csharp_binding.py for the CI harness):
//   mcs -out:smoke.exe SmokeTest.cs Multiverso.cs
//   LD_LIBRARY_PATH=<dir of libmultiverso_c.so> PYTHONPATH=<repo> mono smoke.exe

using System;
using MultiversoTpu;

public static class SmokeTest
{
    private static void Check(bool cond, string msg)
    {
        if (!cond)
        {
            Console.Error.WriteLine("FAIL: " + msg);
            Environment.Exit(1);
        }
    }

    private static bool Approx(float a, float b)
    {
        return Math.Abs(a - b) < 1e-4 * Math.Max(1.0, Math.Abs(b));
    }

    public static void Main()
    {
        MultiversoWrapper.Init();
        int nw = MultiversoWrapper.Size();
        // In the reference each worker PROCESS is a client; this embedded
        // single host is ONE client — MV_NumWorkers() reports SPMD mesh
        // slices, not extra adders (README "Deviations"). Multi-client
        // runs = one host per process under jax.distributed.
        const int nClients = 1;
        Console.WriteLine(string.Format(
            "workers={0} worker_id={1} server_id={2}",
            nw, MultiversoWrapper.WorkerId(), MultiversoWrapper.ServerId()));

        // Array table round trip: after `iters` rounds in which every
        // client adds `delta` once, each slot holds iters*delta*nClients
        // (ref: Test/test_array_table.cpp:26-47 form)
        const int size = 64, iters = 3;
        const float delta = 2.5f;
        var at = new ArrayTableHandler(size);
        var d = new float[size];
        for (int k = 0; k < size; k++) d[k] = delta;
        for (int i = 0; i < iters; i++)
        {
            at.Add(d, sync: true);
            MultiversoWrapper.Barrier();
        }
        var got = at.Get();
        Check(Approx(got[0], iters * delta * nClients),
              string.Format("array invariant: got {0} want {1}",
                            got[0], iters * delta * nClients));

        // Matrix table: whole-table and row-set ops
        var mt = new MatrixTableHandler(10, 4);
        var all = new float[40];
        for (int k = 0; k < 40; k++) all[k] = 1.0f;
        mt.Add(all, sync: true);
        var m = mt.Get();
        Check(Approx(m[0], nClients), "matrix whole-table invariant");

        mt.Add(new[] { 3 }, new float[] { 9, 9, 9, 9 }, sync: true);
        var r = mt.Get(new[] { 3 });
        Check(Approx(r[0], 10f * nClients), "matrix row invariant");

        MultiversoWrapper.Barrier();
        MultiversoWrapper.Shutdown();
        Console.WriteLine("csharp binding test OK");
    }
}
