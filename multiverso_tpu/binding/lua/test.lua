--- Lua binding self-test (ref: binding/lua/test.lua).
--
-- Run:  MULTIVERSO_LIB=/path/to/libmultiverso_c.so \
--       luajit -e "package.path='multiverso_tpu/binding/lua/?.lua;'..
--                  'multiverso_tpu/binding/lua/?/init.lua;'..package.path" test.lua
--
-- Asserts the reference's multi-worker arithmetic invariant: after `iters`
-- rounds in which every worker adds `delta` once, each array slot holds
-- iters * delta * num_workers (ref: Test/test_array_table.cpp:26-47 form).

local mv = require 'multiverso'

local function approx(a, b)
    return math.abs(a - b) < 1e-4 * math.max(1, math.abs(b))
end

mv.init()
local nw = mv.num_workers()
print(('workers=%d worker_id=%d server_id=%d'):format(
    nw, mv.worker_id(), mv.server_id()))

-- Array table round trip
local size, iters, delta = 64, 3, 2.5
local at = mv.ArrayTableHandler.new(size)
for i = 1, iters do
    local d = {}
    for k = 1, size do d[k] = delta end
    at:add(d, true)
    mv.barrier()
end
local got = at:get()
local g1 = mv.util.has_torch and got[1] or got[1]
assert(approx(tonumber(g1), iters * delta * nw),
       ('array invariant: got %s want %s'):format(tonumber(g1), iters * delta * nw))

-- Matrix table: whole-table and row-set ops
local rows, cols = 10, 4
local mt = mv.MatrixTableHandler.new(rows, cols)
local all = {}
for k = 1, rows * cols do all[k] = 1.0 end
mt:add(all, nil, true)
local m = mt:get()
local m11 = mv.util.has_torch and m[1][1] or m[1][1]
assert(approx(tonumber(m11), nw), 'matrix whole-table invariant')

mt:add({ 9, 9, 9, 9 }, { 3 }, true)  -- row id 3 (0-based)
local r = mt:get({ 3 })
local r1 = mv.util.has_torch and r[1][1] or r[1][1]
assert(approx(tonumber(r1), nw + 9 * nw), 'matrix row invariant')

mv.barrier()
mv.shutdown()
print('lua binding test OK')
