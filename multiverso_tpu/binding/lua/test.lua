--- Lua binding self-test (ref: binding/lua/test.lua).
--
-- Run:  MULTIVERSO_LIB=/path/to/libmultiverso_c.so \
--       luajit -e "package.path='multiverso_tpu/binding/lua/?.lua;'..
--                  'multiverso_tpu/binding/lua/?/init.lua;'..package.path" test.lua
--
-- Asserts the reference's arithmetic invariant (ref:
-- Test/test_array_table.cpp:26-47 form): after `iters` rounds in which
-- every CLIENT adds `delta` once, each array slot holds
-- iters * delta * n_clients. In the reference each worker process is a
-- client; in the embedded runtime this single host is ONE client — the
-- mesh workers MV_NumWorkers() reports are SPMD batch slices, not extra
-- adders (README "Deviations" #1/#2). Multi-client runs = one script
-- instance per process under jax.distributed.

local mv = require 'multiverso'

local function approx(a, b)
    return math.abs(a - b) < 1e-4 * math.max(1, math.abs(b))
end

mv.init()
local nw = mv.num_workers()
local n_clients = 1  -- single-process self-test
print(('workers=%d worker_id=%d server_id=%d'):format(
    nw, mv.worker_id(), mv.server_id()))

-- Array table round trip
local size, iters, delta = 64, 3, 2.5
local at = mv.ArrayTableHandler.new(size)
for i = 1, iters do
    local d = {}
    for k = 1, size do d[k] = delta end
    at:add(d, true)
    mv.barrier()
end
local got = at:get()
local g1 = mv.util.has_torch and got[1] or got[1]
local want = iters * delta * n_clients
assert(approx(tonumber(g1), want),
       ('array invariant: got %s want %s'):format(tonumber(g1), want))

-- Matrix table: whole-table and row-set ops
local rows, cols = 10, 4
local mt = mv.MatrixTableHandler.new(rows, cols)
local all = {}
for k = 1, rows * cols do all[k] = 1.0 end
mt:add(all, nil, true)
local m = mt:get()
local m11 = mv.util.has_torch and m[1][1] or m[1][1]
assert(approx(tonumber(m11), n_clients), 'matrix whole-table invariant')

mt:add({ 9, 9, 9, 9 }, { 3 }, true)  -- row id 3 (0-based)
local r = mt:get({ 3 })
local r1 = mv.util.has_torch and r[1][1] or r[1][1]
assert(approx(tonumber(r1), 10 * n_clients), 'matrix row invariant')

mv.barrier()
mv.shutdown()
print('lua binding test OK')
