--- 2-D row-addressable float table handle (ref: binding/lua/MatrixTableHandler.lua).

local ffi = require 'ffi'
local util = require 'multiverso.util'

ffi.cdef[[
    void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out);
    void MV_GetMatrixTableAll(TableHandler handler, float* data, int size);
    void MV_AddMatrixTableAll(TableHandler handler, float* data, int size);
    void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data, int size);
    void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size,
                                 int row_ids[], int row_ids_n);
    void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size,
                                 int row_ids[], int row_ids_n);
    void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data, int size,
                                      int row_ids[], int row_ids_n);
]]

local MatrixTableHandler = {}
MatrixTableHandler.__index = MatrixTableHandler

function MatrixTableHandler.new(num_row, num_col, init_value)
    local mv = require 'multiverso'
    local self = setmetatable({}, MatrixTableHandler)
    self._num_row, self._num_col = num_row, num_col
    self._size = num_row * num_col
    self._handler = ffi.new('TableHandler[1]')
    mv.libmv.MV_NewMatrixTable(
        ffi.new('int', num_row), ffi.new('int', num_col), self._handler)
    if init_value ~= nil then
        local cdata, n = util.to_cdata(init_value)
        assert(n == self._size, 'init_value must have num_row*num_col elements')
        if mv.worker_id() ~= 0 then
            cdata = ffi.new('float[?]', n)  -- zeros, keeps sync rounds aligned
        end
        mv.libmv.MV_AddMatrixTableAll(self._handler[0], cdata, n)
    end
    return self
end

--- Get the whole table (row_ids == nil) or a set of rows (1-based Lua array
-- of 0-based row ids, matching the reference's C-side indexing).
function MatrixTableHandler:get(row_ids)
    local mv = require 'multiverso'
    if row_ids == nil then
        local cdata = ffi.new('float[?]', self._size)
        mv.libmv.MV_GetMatrixTableAll(self._handler[0], cdata, self._size)
        return util.from_cdata(cdata, self._num_row, self._num_col)
    end
    local ids, n = util.to_cdata(row_ids, 'int')
    local cdata = ffi.new('float[?]', n * self._num_col)
    mv.libmv.MV_GetMatrixTableByRows(
        self._handler[0], cdata, n * self._num_col, ids, n)
    return util.from_cdata(cdata, n, self._num_col)
end

function MatrixTableHandler:add(data, row_ids, sync)
    local mv = require 'multiverso'
    local cdata, n = util.to_cdata(data)
    if row_ids == nil then
        assert(n == self._size, 'delta must have num_row*num_col elements')
        if sync then
            mv.libmv.MV_AddMatrixTableAll(self._handler[0], cdata, n)
        else
            mv.libmv.MV_AddAsyncMatrixTableAll(self._handler[0], cdata, n)
        end
    else
        local ids, nid = util.to_cdata(row_ids, 'int')
        assert(n == nid * self._num_col, 'delta must have #row_ids*num_col elements')
        if sync then
            mv.libmv.MV_AddMatrixTableByRows(self._handler[0], cdata, n, ids, nid)
        else
            mv.libmv.MV_AddAsyncMatrixTableByRows(self._handler[0], cdata, n, ids, nid)
        end
    end
end

return MatrixTableHandler
