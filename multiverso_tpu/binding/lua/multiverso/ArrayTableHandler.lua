--- 1-D dense float table handle (ref: binding/lua/ArrayTableHandler.lua).

local ffi = require 'ffi'
local util = require 'multiverso.util'

ffi.cdef[[
    void MV_NewArrayTable(int size, TableHandler* out);
    void MV_GetArrayTable(TableHandler handler, float* data, int size);
    void MV_AddArrayTable(TableHandler handler, float* data, int size);
    void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size);
]]

local ArrayTableHandler = {}
ArrayTableHandler.__index = ArrayTableHandler

--- Create a table of `size` float32s. `init_value` (optional) follows the
-- reference master-init protocol: worker 0 sync-adds the value, every other
-- worker sync-adds zeros so the sync server's per-round add accounting stays
-- aligned across workers (ref: ArrayTableHandler.lua:26-37).
function ArrayTableHandler.new(size, init_value)
    local mv = require 'multiverso'
    local self = setmetatable({}, ArrayTableHandler)
    self._size = size
    self._handler = ffi.new('TableHandler[1]')
    mv.libmv.MV_NewArrayTable(ffi.new('int', size), self._handler)
    if init_value ~= nil then
        local cdata, n = util.to_cdata(init_value)
        assert(n == size, 'init_value length must equal table size')
        if mv.worker_id() ~= 0 then
            cdata = ffi.new('float[?]', n)  -- zeros
        end
        mv.libmv.MV_AddArrayTable(self._handler[0], cdata, n)
    end
    return self
end

function ArrayTableHandler:get()
    local mv = require 'multiverso'
    local cdata = ffi.new('float[?]', self._size)
    mv.libmv.MV_GetArrayTable(self._handler[0], cdata, self._size)
    return util.from_cdata(cdata, self._size)
end

--- Add `data` (delta). `sync=true` blocks until the update is applied.
function ArrayTableHandler:add(data, sync)
    local mv = require 'multiverso'
    local cdata, n = util.to_cdata(data)
    assert(n == self._size, 'delta length must equal table size')
    if sync then
        mv.libmv.MV_AddArrayTable(self._handler[0], cdata, n)
    else
        mv.libmv.MV_AddAsyncArrayTable(self._handler[0], cdata, n)
    end
end

return ArrayTableHandler
