--- Data marshalling helpers for the Lua binding (ref: binding/lua/util.lua).
--
-- Converts between C arrays and whatever the host program uses: plain Lua
-- number arrays always work; torch tensors are used when torch is loaded.

local ffi = require 'ffi'

local util = {}

local has_torch, torch = pcall(require, 'torch')
util.has_torch = has_torch

local ctype_of = { float = 'float[?]', int = 'int[?]', double = 'double[?]' }

--- Flatten `data` (Lua array, possibly nested one level, or torch tensor)
-- into a freshly allocated C array of `data_type`. Returns cdata, length.
function util.to_cdata(data, data_type)
    data_type = data_type or 'float'
    if has_torch and torch.isTensor(data) then
        local t = data:contiguous():float()
        local n = t:nElement()
        local c = ffi.new(ctype_of[data_type], n)
        ffi.copy(c, t:data(), n * ffi.sizeof(data_type))
        return c, n
    end
    -- plain Lua table; allow one level of nesting (matrix as rows)
    local flat = {}
    for i = 1, #data do
        local v = data[i]
        if type(v) == 'table' then
            for j = 1, #v do flat[#flat + 1] = v[j] end
        else
            flat[#flat + 1] = v
        end
    end
    local c = ffi.new(ctype_of[data_type], #flat)
    for i = 1, #flat do c[i - 1] = flat[i] end
    return c, #flat
end

--- Convert a C array back to the host representation: a torch FloatTensor
-- when torch is available, else a plain Lua array. `rows`/`cols` reshape
-- (cols == nil -> 1-D of length rows).
function util.from_cdata(cdata, rows, cols)
    if has_torch then
        local n = cols and rows * cols or rows
        local t = torch.FloatTensor(n)
        ffi.copy(t:data(), cdata, n * ffi.sizeof('float'))
        if cols then return t:reshape(rows, cols) end
        return t
    end
    if cols then
        local out = {}
        for r = 1, rows do
            local row = {}
            for c = 1, cols do row[c] = cdata[(r - 1) * cols + (c - 1)] end
            out[r] = row
        end
        return out
    end
    local out = {}
    for i = 1, rows do out[i] = cdata[i - 1] end
    return out
end

return util
