--- LuaJIT binding for the TPU-native Multiverso framework.
--
-- Mirrors the reference Lua/Torch binding surface (ref:
-- binding/lua/init.lua:7-67) over the flat C ABI of libmultiverso_c.so
-- (multiverso_tpu/capi/c_api.h). Unlike the reference it does NOT require
-- torch: plain Lua number arrays work everywhere, and torch tensors are
-- accepted transparently when torch is installed.
--
-- Library lookup order:
--   1. MULTIVERSO_LIB environment variable (full path to libmultiverso_c.so)
--   2. package.cpath search for "libmultiverso_c"
--   3. plain ffi.load("multiverso_c") (system linker paths)

local ffi = require 'ffi'

local mv = {}

ffi.cdef[[
    typedef void* TableHandler;
    void MV_Init(int* argc, char* argv[]);
    void MV_ShutDown();
    void MV_Barrier();
    int MV_NumWorkers();
    int MV_WorkerId();
    int MV_ServerId();
]]

local function load_library()
    local env = os.getenv('MULTIVERSO_LIB')
    if env ~= nil and env ~= '' then
        return ffi.load(env, true)
    end
    local path = package.searchpath and
        package.searchpath('libmultiverso_c', package.cpath, '')
    if path ~= nil then
        return ffi.load(path, true)
    end
    local ok, lib = pcall(ffi.load, 'multiverso_c', true)
    if ok then return lib end
    error([[libmultiverso_c.so not found.
Build it (python -m multiverso_tpu.capi) and point MULTIVERSO_LIB at it,
or place it on package.cpath / the system linker path.]])
end

mv.libmv = load_library()

mv.util = require 'multiverso.util'
mv.ArrayTableHandler = require 'multiverso.ArrayTableHandler'
mv.MatrixTableHandler = require 'multiverso.MatrixTableHandler'

--- Start the runtime. `opts` may be a boolean (sync mode, reference
-- signature) or a table of `-key=value` flag strings / key=value pairs.
function mv.init(opts)
    local args = { 'multiverso' }  -- argv[0] placeholder, consumed by parser
    if type(opts) == 'boolean' then
        if opts then args[#args + 1] = '-sync=true' end
    elseif type(opts) == 'table' then
        for k, v in pairs(opts) do
            if type(k) == 'number' then
                args[#args + 1] = tostring(v)
            else
                args[#args + 1] = string.format('-%s=%s', k, tostring(v))
            end
        end
    end
    local argc = ffi.new('int[1]', #args)
    local argv = ffi.new('char*[?]', #args)
    local keep = {}  -- anchor cdata until MV_Init returns
    for i = 1, #args do
        local buf = ffi.new('char[?]', #args[i] + 1)
        ffi.copy(buf, args[i])
        keep[i] = buf
        argv[i - 1] = buf
    end
    mv.libmv.MV_Init(argc, argv)
end

function mv.barrier() mv.libmv.MV_Barrier() end
function mv.shutdown() mv.libmv.MV_ShutDown() end
function mv.num_workers() return mv.libmv.MV_NumWorkers() end
function mv.worker_id() return mv.libmv.MV_WorkerId() end
function mv.server_id() return mv.libmv.MV_ServerId() end

return mv
