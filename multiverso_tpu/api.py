"""Public ``MV_*`` API surface.

Parity with the reference public API (ref: include/multiverso/multiverso.h:9-65,
src/multiverso.cpp:11-78). ``MV_NetBind`` / ``MV_NetConnect`` (ref:
multiverso.h:47-65, the ZMQ explicit-endpoint path) configure the multi-host
rendezvous: call both before ``MV_Init`` and they seed
``jax.distributed.initialize`` coordination instead of opening sockets
directly (XLA owns the fabric).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from multiverso_tpu.runtime import runtime
from multiverso_tpu.utils.configure import SetCMDFlag

__all__ = [
    "MV_CreateTable",
    "MV_Init",
    "MV_ShutDown",
    "MV_Barrier",
    "MV_Rank",
    "MV_Size",
    "MV_NumWorkers",
    "MV_NumServers",
    "MV_WorkerId",
    "MV_ServerId",
    "MV_SetFlag",
    "MV_Aggregate",
    "MV_NetBind",
    "MV_NetConnect",
]


def MV_Init(argv: Optional[Sequence[str]] = None, **kwargs: Any) -> List[str]:
    """Start the runtime (ref: src/multiverso.cpp:11-16). Returns leftover argv."""
    return runtime().start(argv=argv, **kwargs)


def MV_ShutDown(finalize: bool = True) -> None:
    runtime().shut_down(finalize)


def MV_Barrier() -> None:
    runtime().barrier()


def MV_Rank() -> int:
    return runtime().rank


def MV_Size() -> int:
    return runtime().size


def MV_NumWorkers() -> int:
    return runtime().num_workers


def MV_NumServers() -> int:
    return runtime().num_servers


def MV_WorkerId() -> int:
    return runtime().worker_id


def MV_ServerId() -> int:
    return runtime().server_id


def MV_SetFlag(name: str, value: Any) -> None:
    SetCMDFlag(name, value)


def MV_Aggregate(per_worker: Any):
    """Model-averaging allreduce over the worker axis (ref: src/multiverso.cpp:53-56)."""
    return runtime().aggregate(per_worker)


def MV_CreateTable(option):
    """Create a sharded table from its option record (ref:
    include/multiverso/multiverso.h:35-41)."""
    from multiverso_tpu.tables.base import create_table

    return create_table(option)


def MV_NetBind(rank: int, endpoint: str) -> None:
    """Declare this process's rank/endpoint before cluster wiring (ref:
    include/multiverso/multiverso.h:47-56). TPU-native: records the identity
    for the ``MV_NetConnect`` rendezvous — there is no socket to bind, XLA
    owns the fabric once the cluster is formed."""
    from multiverso_tpu.parallel import multihost

    multihost.net_bind(rank, endpoint)


def MV_NetConnect(ranks: Sequence[int], endpoints: Sequence[str]) -> None:
    """Wire the cluster from an explicit endpoint list (ref:
    include/multiverso/multiverso.h:57-65 — the CNTK-style ZMQ deployment).
    TPU-native: rank 0's endpoint becomes the ``jax.distributed``
    coordinator; call before ``MV_Init``."""
    from multiverso_tpu.parallel import multihost

    multihost.net_connect(ranks, endpoints)
