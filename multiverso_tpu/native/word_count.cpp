// word_count — standalone vocabulary builder (preprocessing tool).
//
// Native equivalent of the reference's WordEmbedding preprocessing binary
// (ref: Applications/WordEmbedding/preprocess/word_count.cpp + stopword
// list): streams whitespace-tokenized corpora, counts words, filters by
// min_count and an optional stopword file, and writes "word count" lines
// sorted by descending count — the vocab format Dictionary.load consumes.
//
// Usage: word_count -out VOCAB [-min_count N] [-stopwords FILE] CORPUS...

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <iostream>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

void CountStream(std::istream& in,
                 std::unordered_map<std::string, uint64_t>* counts) {
  std::string word;
  while (in >> word) ++(*counts)[word];
}

}  // namespace

int main(int argc, char** argv) {
  std::string out_path;
  std::string stop_path;
  uint64_t min_count = 5;
  std::vector<std::string> inputs;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-out") == 0 && i + 1 < argc) {
      out_path = argv[++i];
    } else if (std::strcmp(argv[i], "-min_count") == 0 && i + 1 < argc) {
      min_count = std::strtoull(argv[++i], nullptr, 10);
    } else if (std::strcmp(argv[i], "-stopwords") == 0 && i + 1 < argc) {
      stop_path = argv[++i];
    } else {
      inputs.push_back(argv[i]);
    }
  }
  if (out_path.empty() || inputs.empty()) {
    std::fprintf(stderr,
                 "usage: word_count -out VOCAB [-min_count N] "
                 "[-stopwords FILE] CORPUS...\n");
    return 2;
  }

  std::unordered_set<std::string> stop;
  if (!stop_path.empty()) {
    std::ifstream sf(stop_path);
    if (!sf) {
      std::fprintf(stderr, "cannot open stopword file %s\n", stop_path.c_str());
      return 1;
    }
    std::string w;
    while (sf >> w) stop.insert(w);
  }

  std::unordered_map<std::string, uint64_t> counts;
  for (const auto& path : inputs) {
    std::ifstream f(path);
    if (!f) {
      std::fprintf(stderr, "cannot open corpus %s\n", path.c_str());
      return 1;
    }
    CountStream(f, &counts);
  }

  std::vector<std::pair<std::string, uint64_t>> kept;
  kept.reserve(counts.size());
  for (auto& kv : counts) {
    if (kv.second >= min_count && !stop.count(kv.first)) {
      kept.emplace_back(std::move(kv.first), kv.second);
    }
  }
  // descending count, ties by word for determinism
  std::sort(kept.begin(), kept.end(), [](const auto& a, const auto& b) {
    return a.second != b.second ? a.second > b.second : a.first < b.first;
  });

  std::ofstream out(out_path);
  if (!out) {
    std::fprintf(stderr, "cannot write %s\n", out_path.c_str());
    return 1;
  }
  for (const auto& kv : kept) out << kv.first << ' ' << kv.second << '\n';
  std::fprintf(stderr, "word_count: %zu/%zu words kept (min_count=%llu)\n",
               kept.size(), counts.size(),
               static_cast<unsigned long long>(min_count));
  return 0;
}
