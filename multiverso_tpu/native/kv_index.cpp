// kv_index.cpp — batched u64 key -> dense slot resolution for KV tables.
//
// TPU-native replacement for the reference's per-key host hash walks
// (ref: include/multiverso/table/kv_table.h:48-65 unordered_map lookups;
// Applications/LogisticRegression/src/util/hopscotch_hash.h:1-385 hopscotch
// table backing the FTRL sparse store). The device side keeps values in one
// sharded HBM array addressed by *dense slots*; this index is the host
// control plane mapping arbitrary 64-bit feature ids to those slots, batched
// (one C call per minibatch instead of one dict lookup per key).
//
// Open addressing, linear probing, power-of-two capacity, splitmix64 hash
// finalizer, grow at 70% load. Dense slot ids are assigned in first-seen
// order and never move (rehash relocates hash cells, not slots), so device
// arrays only ever append.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct KvIndex {
  std::vector<uint64_t> cell_key;  // hash cells
  std::vector<int64_t> cell_slot;  // -1 = empty
  std::vector<uint64_t> dense;     // slot -> key, insertion order
  uint64_t mask = 0;

  explicit KvIndex(int64_t initial) {
    uint64_t cap = 64;
    while ((int64_t)cap < initial * 2) cap <<= 1;
    cell_key.assign(cap, 0);
    cell_slot.assign(cap, -1);
    mask = cap - 1;
  }

  static uint64_t hash(uint64_t x) {  // splitmix64 finalizer
    x += 0x9e3779b97f4a7c15ULL;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    return x ^ (x >> 31);
  }

  void grow() {
    uint64_t ncap = (mask + 1) << 1;
    std::vector<uint64_t> nk(ncap, 0);
    std::vector<int64_t> ns(ncap, -1);
    uint64_t nmask = ncap - 1;
    for (uint64_t i = 0; i <= mask; ++i) {
      if (cell_slot[i] < 0) continue;
      uint64_t j = hash(cell_key[i]) & nmask;
      while (ns[j] >= 0) j = (j + 1) & nmask;
      nk[j] = cell_key[i];
      ns[j] = cell_slot[i];
    }
    cell_key.swap(nk);
    cell_slot.swap(ns);
    mask = nmask;
  }

  // slot for key; creates if absent and create!=0, else -1
  int64_t resolve1(uint64_t key, int create) {
    uint64_t j = hash(key) & mask;
    while (true) {
      int64_t s = cell_slot[j];
      if (s < 0) {
        if (!create) return -1;
        int64_t slot = (int64_t)dense.size();
        cell_key[j] = key;
        cell_slot[j] = slot;
        dense.push_back(key);
        if (dense.size() * 10 > (mask + 1) * 7) grow();
        return slot;
      }
      if (cell_key[j] == key) return s;
      j = (j + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

void* mv_kv_index_new(int64_t initial_capacity) {
  return new KvIndex(initial_capacity < 1 ? 1 : initial_capacity);
}

void mv_kv_index_free(void* h) { delete (KvIndex*)h; }

int64_t mv_kv_index_size(void* h) {
  return (int64_t)((KvIndex*)h)->dense.size();
}

// Batched resolve: out_slots[i] = slot of keys[i] (-1 if absent and !create).
// Returns the number of newly created slots.
int64_t mv_kv_index_resolve(void* h, const uint64_t* keys, int64_t n,
                            int create, int64_t* out_slots) {
  KvIndex* ix = (KvIndex*)h;
  int64_t before = (int64_t)ix->dense.size();
  for (int64_t i = 0; i < n; ++i) out_slots[i] = ix->resolve1(keys[i], create);
  return (int64_t)ix->dense.size() - before;
}

// Dump keys in slot order (caller allocates size() entries). Returns count.
int64_t mv_kv_index_keys(void* h, uint64_t* out) {
  KvIndex* ix = (KvIndex*)h;
  std::memcpy(out, ix->dense.data(), ix->dense.size() * sizeof(uint64_t));
  return (int64_t)ix->dense.size();
}

}  // extern "C"
