// Native corpus batcher for WordEmbedding — the host-side hot path.
//
// TPU-native equivalent of the reference's per-thread sentence parsing
// (ref: Applications/WordEmbedding/src/wordembedding.cpp ParseSentence/Parse,
// reader.cpp tokenizer loops): where the reference interleaves scalar window
// walks with training, here the generator runs on host CPU producing
// fixed-shape int32 batches that feed the jitted TPU step, overlapped via the
// ASyncBuffer prefetcher.
//
// Semantics preserved from word2vec/the reference:
//   - per-center dynamic window shrink b ~ U[0, window) (effective window
//     = window - b), matching wordembedding.cpp's window sampling;
//   - frequency subsampling via per-word keep probabilities (computed in
//     Python from the -sample flag formula — util.h:45-66);
//   - sentence breaks (id < 0) are never crossed as centers or contexts.
//
// id stream: int32, -1 marks sentence boundaries. RNG: xorshift64 (seeded
// per call) so a (seed, start) pair reproduces a batch exactly.

#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

inline uint64_t xorshift64(uint64_t* s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  *s = x;
  return x;
}

inline float uniform01(uint64_t* s) {
  return static_cast<float>((xorshift64(s) >> 11) * (1.0 / 9007199254740992.0));
}

}  // namespace

extern "C" {

// Skip-gram (center, context) pair generation.
// Returns the number of pairs written (<= cap); *next_pos is the resume
// position in the id stream (call again from there for the next batch).
long long we_skipgram_pairs(const int32_t* ids, long long n, long long start,
                            int window, const float* keep, uint64_t seed,
                            int32_t* centers, int32_t* contexts,
                            long long cap, long long* next_pos) {
  uint64_t rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  long long out = 0;
  long long pos = start;
  for (; pos < n; ++pos) {
    int32_t w = ids[pos];
    if (w < 0) continue;  // sentence break
    if (keep && uniform01(&rng) >= keep[w]) continue;  // subsampled out
    if (out + 2 * static_cast<long long>(window) > cap) break;  // batch full
    int b = window > 1 ? static_cast<int>(xorshift64(&rng) % window) : 0;
    int eff = window - b;
    // left side: stop at a sentence break, don't cross it
    for (int off = -1; off >= -eff; --off) {
      long long c = pos + off;
      if (c < 0 || ids[c] < 0) break;
      centers[out] = w;
      contexts[out] = ids[c];
      ++out;
    }
    // right side
    for (int off = 1; off <= eff; ++off) {
      long long c = pos + off;
      if (c >= n || ids[c] < 0) break;
      centers[out] = w;
      contexts[out] = ids[c];
      ++out;
    }
  }
  *next_pos = pos;
  return out;
}

// CBOW batch generation: one row per kept center word; context row padded
// with -1 (the jitted step masks them).
long long we_cbow_batch(const int32_t* ids, long long n, long long start,
                        int window, const float* keep, uint64_t seed,
                        int32_t* targets, int32_t* ctx, long long cap,
                        long long* next_pos) {
  uint64_t rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  const int w2 = 2 * window;
  long long out = 0;
  long long pos = start;
  for (; pos < n && out < cap; ++pos) {
    int32_t w = ids[pos];
    if (w < 0) continue;
    if (keep && uniform01(&rng) >= keep[w]) continue;
    int b = window > 1 ? static_cast<int>(xorshift64(&rng) % window) : 0;
    int eff = window - b;
    int32_t* row = ctx + out * w2;
    int k = 0;
    for (int off = -1; off >= -eff; --off) {
      long long c = pos + off;
      if (c < 0 || ids[c] < 0) break;
      row[k++] = ids[c];
    }
    for (int off = 1; off <= eff; ++off) {
      long long c = pos + off;
      if (c >= n || ids[c] < 0) break;
      row[k++] = ids[c];
    }
    if (k == 0) continue;  // no usable context
    for (; k < w2; ++k) row[k] = -1;
    targets[out] = w;
    ++out;
  }
  *next_pos = pos;
  return out;
}

// Alias-method negative sampling (unigram^0.75 tables built in Python —
// sampler._build_alias): out[i] = idx if u < prob[idx] else alias[idx].
// Replaces the numpy sample_np hot loop in the batch producer.
long long we_alias_sample(const float* prob, const int32_t* alias,
                          long long vocab, long long n, uint64_t seed,
                          int32_t* out) {
  uint64_t rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  for (long long i = 0; i < n; ++i) {
    const int32_t idx = static_cast<int32_t>(xorshift64(&rng) % vocab);
    out[i] = (uniform01(&rng) < prob[idx]) ? idx : alias[idx];
  }
  return n;
}

// Sort metadata for the sorted-scatter device step (skipgram.presort_updates
// semantics): stable counting sort over row ids — O(N + V) vs numpy's
// O(N log N) argsort — plus weighted per-row counts for row-mean scaling.
// scale[j] (sorted order) = w/1 (raw_mode) or w / weighted_count(row).
// Returns 0, or -1 if any id is negative.
long long we_presort(const int32_t* ids, const float* weights, long long n,
                     int raw_mode, int32_t* perm_out, int32_t* sorted_out,
                     float* scale_out) {
  int32_t max_id = 0;
  for (long long j = 0; j < n; ++j) {
    if (ids[j] < 0) return -1;
    if (ids[j] > max_id) max_id = ids[j];
  }
  // counting sort is O(N + V); when the id range dwarfs the batch (huge
  // vocab, small batch) it loses to the caller's O(N log N) numpy fallback
  // and would pin V-sized thread_local buffers — decline instead
  if (static_cast<long long>(max_id) > 32 * n) return -1;
  static thread_local std::vector<long long> offsets;
  static thread_local std::vector<double> wcnt;
  offsets.assign(static_cast<size_t>(max_id) + 2, 0);
  for (long long j = 0; j < n; ++j) offsets[ids[j] + 1]++;
  for (long long v = 1; v <= max_id + 1; ++v) offsets[v] += offsets[v - 1];
  if (!raw_mode) {
    wcnt.assign(static_cast<size_t>(max_id) + 1, 0.0);
    for (long long j = 0; j < n; ++j)
      wcnt[ids[j]] += weights ? weights[j] : 1.0;
  }
  for (long long j = 0; j < n; ++j) {
    const int32_t id = ids[j];
    const long long pos = offsets[id]++;
    perm_out[pos] = static_cast<int32_t>(j);
    sorted_out[pos] = id;
    const double w = weights ? weights[j] : 1.0;
    if (raw_mode) {
      scale_out[pos] = static_cast<float>(w);
    } else {
      const double c = wcnt[id];
      scale_out[pos] = static_cast<float>(w / (c > 1.0 ? c : 1.0));
    }
  }
  return 0;
}

// Whole-batch NS finalize in one call (the single-core host hot path):
// negatives via alias draws, outputs assembly [target | negs], and presort
// metadata for both tables. Equivalent to sampler.sample_np + concatenate +
// 2x we_presort, without the per-step Python/ctypes round trips.
long long we_ns_finalize(const int32_t* centers, const int32_t* targets,
                         long long b, int negatives, const float* prob,
                         const int32_t* alias, long long vocab, uint64_t seed,
                         int raw_mode,
                         int32_t* outputs,  // (b * (1+negatives))
                         int32_t* in_perm, int32_t* in_sort, float* in_scale,
                         int32_t* out_perm, int32_t* out_sort,
                         float* out_scale) {
  const int k1 = 1 + negatives;
  // the centers presort (n = b) is the tightest decline threshold and the
  // negatives draw from the full vocab — check before doing any work so a
  // declining call is ~free (the caller redoes everything in numpy)
  if (vocab > 32 * b) return -1;
  // input table rows = the center words; output table rows = target+negs
  if (we_presort(centers, nullptr, b, raw_mode, in_perm, in_sort, in_scale) != 0)
    return -1;
  uint64_t rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  for (long long i = 0; i < b; ++i) {
    int32_t* row = outputs + i * k1;
    row[0] = targets[i];
    for (int k = 1; k < k1; ++k) {
      const int32_t idx = static_cast<int32_t>(xorshift64(&rng) % vocab);
      row[k] = (uniform01(&rng) < prob[idx]) ? idx : alias[idx];
    }
  }
  return we_presort(outputs, nullptr, b * k1, raw_mode, out_perm, out_sort,
                    out_scale);
}

}  // extern "C"
