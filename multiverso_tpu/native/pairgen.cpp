// Native corpus batcher for WordEmbedding — the host-side hot path.
//
// TPU-native equivalent of the reference's per-thread sentence parsing
// (ref: Applications/WordEmbedding/src/wordembedding.cpp ParseSentence/Parse,
// reader.cpp tokenizer loops): where the reference interleaves scalar window
// walks with training, here the generator runs on host CPU producing
// fixed-shape int32 batches that feed the jitted TPU step, overlapped via the
// ASyncBuffer prefetcher.
//
// Semantics preserved from word2vec/the reference:
//   - per-center dynamic window shrink b ~ U[0, window) (effective window
//     = window - b), matching wordembedding.cpp's window sampling;
//   - frequency subsampling via per-word keep probabilities (computed in
//     Python from the -sample flag formula — util.h:45-66);
//   - sentence breaks (id < 0) are never crossed as centers or contexts.
//
// id stream: int32, -1 marks sentence boundaries. RNG: xorshift64 (seeded
// per call) so a (seed, start) pair reproduces a batch exactly.

#include <cstdint>

namespace {

inline uint64_t xorshift64(uint64_t* s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  *s = x;
  return x;
}

inline float uniform01(uint64_t* s) {
  return static_cast<float>((xorshift64(s) >> 11) * (1.0 / 9007199254740992.0));
}

}  // namespace

extern "C" {

// Skip-gram (center, context) pair generation.
// Returns the number of pairs written (<= cap); *next_pos is the resume
// position in the id stream (call again from there for the next batch).
long long we_skipgram_pairs(const int32_t* ids, long long n, long long start,
                            int window, const float* keep, uint64_t seed,
                            int32_t* centers, int32_t* contexts,
                            long long cap, long long* next_pos) {
  uint64_t rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  long long out = 0;
  long long pos = start;
  for (; pos < n; ++pos) {
    int32_t w = ids[pos];
    if (w < 0) continue;  // sentence break
    if (keep && uniform01(&rng) >= keep[w]) continue;  // subsampled out
    if (out + 2 * static_cast<long long>(window) > cap) break;  // batch full
    int b = window > 1 ? static_cast<int>(xorshift64(&rng) % window) : 0;
    int eff = window - b;
    // left side: stop at a sentence break, don't cross it
    for (int off = -1; off >= -eff; --off) {
      long long c = pos + off;
      if (c < 0 || ids[c] < 0) break;
      centers[out] = w;
      contexts[out] = ids[c];
      ++out;
    }
    // right side
    for (int off = 1; off <= eff; ++off) {
      long long c = pos + off;
      if (c >= n || ids[c] < 0) break;
      centers[out] = w;
      contexts[out] = ids[c];
      ++out;
    }
  }
  *next_pos = pos;
  return out;
}

// CBOW batch generation: one row per kept center word; context row padded
// with -1 (the jitted step masks them).
long long we_cbow_batch(const int32_t* ids, long long n, long long start,
                        int window, const float* keep, uint64_t seed,
                        int32_t* targets, int32_t* ctx, long long cap,
                        long long* next_pos) {
  uint64_t rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  const int w2 = 2 * window;
  long long out = 0;
  long long pos = start;
  for (; pos < n && out < cap; ++pos) {
    int32_t w = ids[pos];
    if (w < 0) continue;
    if (keep && uniform01(&rng) >= keep[w]) continue;
    int b = window > 1 ? static_cast<int>(xorshift64(&rng) % window) : 0;
    int eff = window - b;
    int32_t* row = ctx + out * w2;
    int k = 0;
    for (int off = -1; off >= -eff; --off) {
      long long c = pos + off;
      if (c < 0 || ids[c] < 0) break;
      row[k++] = ids[c];
    }
    for (int off = 1; off <= eff; ++off) {
      long long c = pos + off;
      if (c >= n || ids[c] < 0) break;
      row[k++] = ids[c];
    }
    if (k == 0) continue;  // no usable context
    for (; k < w2; ++k) row[k] = -1;
    targets[out] = w;
    ++out;
  }
  *next_pos = pos;
  return out;
}

}  // extern "C"
