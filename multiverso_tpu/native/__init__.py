"""Native (C++) components: build-on-demand ctypes loader.

The reference's data path is native C++ (SURVEY.md §2.7 Reader/Trainer); here
the host-side hot loops live in ``pairgen.cpp``, compiled lazily with g++
into a per-version cache directory and loaded via ctypes. A pure-Python
fallback keeps everything working (slower) when no compiler is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from multiverso_tpu.utils.log import Log

__all__ = [
    "pairgen_lib",
    "skipgram_pairs",
    "cbow_batch",
    "presort",
    "ns_finalize",
    "alias_sample",
    "have_native",
]

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_BUILD_LOCK = threading.Lock()


def build_native_lib(
    src_name: str,
    lib_name: str,
    src_dir: Optional[str] = None,
    cflags: Optional[list] = None,
    ldflags: Optional[list] = None,
    try_march_native: bool = True,
    executable: bool = False,
) -> Optional[str]:
    """Compile one C++ source into the gitignored ``native/_build/`` cache
    (rebuilt when the source is newer). Host-tuned first, portable fallback.
    ``executable=True`` builds a standalone binary instead of a cdylib."""
    src = os.path.join(src_dir or _THIS_DIR, src_name)
    out_dir = os.path.join(_THIS_DIR, "_build")
    os.makedirs(out_dir, exist_ok=True)
    lib_path = os.path.join(out_dir, lib_name)
    if os.path.exists(lib_path) and os.path.getmtime(lib_path) >= os.path.getmtime(src):
        return lib_path
    link_mode = [] if executable else ["-fPIC", "-shared"]
    base = (
        ["g++", "-O3", "-std=c++17"]
        + link_mode
        + ["-pthread"]
        + (cflags or [])
        + [src, "-o", lib_path]
        + (ldflags or [])
    )
    variants = (["-march=native"], []) if try_march_native else ([],)
    for extra in variants:
        cmd = base[:2] + extra + base[2:]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=180)
            Log.Info("[native] built %s", lib_path)
            return lib_path
        except (subprocess.SubprocessError, FileNotFoundError) as e:
            err = e
    detail = (getattr(err, "stderr", b"") or b"").decode(errors="replace")[:500]
    Log.Error(
        "[native] build of %s failed (%s %s); using python fallback",
        src_name, err, detail,
    )
    return None


def _build() -> Optional[str]:
    return build_native_lib("pairgen.cpp", "libwe_pairgen.so")


def pairgen_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _BUILD_LOCK:  # parallel producers race the first lazy build
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        path = _build()
        if path:
            lib = ctypes.CDLL(path)
            LL, I32P, F32P, U64 = (
                ctypes.c_longlong,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                ctypes.c_uint64,
            )
            lib.we_skipgram_pairs.restype = LL
            lib.we_skipgram_pairs.argtypes = [
                I32P, LL, LL, ctypes.c_int, ctypes.c_void_p, U64,
                I32P, I32P, LL, ctypes.POINTER(LL),
            ]
            lib.we_cbow_batch.restype = LL
            lib.we_cbow_batch.argtypes = [
                I32P, LL, LL, ctypes.c_int, ctypes.c_void_p, U64,
                I32P, I32P, LL, ctypes.POINTER(LL),
            ]
            lib.we_presort.restype = LL
            lib.we_presort.argtypes = [
                I32P, ctypes.c_void_p, LL, ctypes.c_int, I32P, I32P, F32P,
            ]
            lib.we_alias_sample.restype = LL
            lib.we_alias_sample.argtypes = [F32P, I32P, LL, LL, U64, I32P]
            lib.we_ns_finalize.restype = LL
            lib.we_ns_finalize.argtypes = [
                I32P, I32P, LL, ctypes.c_int, F32P, I32P, LL, U64,
                ctypes.c_int, I32P, I32P, I32P, F32P, I32P, I32P, F32P,
            ]
            _LIB = lib
    return _LIB


def have_native() -> bool:
    return pairgen_lib() is not None


def _keep_ptr(keep: Optional[np.ndarray]):
    if keep is None:
        return None
    return keep.ctypes.data_as(ctypes.c_void_p)


# ------------------------------------------------------------ python fallback


def _xorshift64(s: int) -> int:
    s &= (1 << 64) - 1
    s ^= (s << 13) & ((1 << 64) - 1)
    s ^= s >> 7
    s ^= (s << 17) & ((1 << 64) - 1)
    return s & ((1 << 64) - 1)


def _py_skipgram(ids, n, start, window, keep, seed, centers, contexts, cap):
    rng = seed or 0x9E3779B97F4A7C15
    out = 0
    pos = start
    while pos < n:
        w = int(ids[pos])
        if w < 0:
            pos += 1
            continue
        if keep is not None:
            rng = _xorshift64(rng)
            if (rng >> 11) * (1.0 / 9007199254740992.0) >= keep[w]:
                pos += 1
                continue
        if out + 2 * window > cap:
            break
        if window > 1:
            rng = _xorshift64(rng)
            b = rng % window
        else:
            b = 0
        eff = window - b
        for off in range(-1, -eff - 1, -1):  # left side, stop at break
            c = pos + off
            if c < 0 or ids[c] < 0:
                break
            centers[out] = w
            contexts[out] = int(ids[c])
            out += 1
        for off in range(1, eff + 1):  # right side
            c = pos + off
            if c >= n or ids[c] < 0:
                break
            centers[out] = w
            contexts[out] = int(ids[c])
            out += 1
        pos += 1
    return out, pos


def _py_cbow(ids, n, start, window, keep, seed, targets, ctx, cap):
    rng = seed or 0x9E3779B97F4A7C15
    w2 = 2 * window
    out = 0
    pos = start
    while pos < n and out < cap:
        w = int(ids[pos])
        if w < 0:
            pos += 1
            continue
        if keep is not None:
            rng = _xorshift64(rng)
            if (rng >> 11) * (1.0 / 9007199254740992.0) >= keep[w]:
                pos += 1
                continue
        if window > 1:
            rng = _xorshift64(rng)
            b = rng % window
        else:
            b = 0
        eff = window - b
        k = 0
        for off in range(-1, -eff - 1, -1):
            c = pos + off
            if c < 0 or ids[c] < 0:
                break
            ctx[out, k] = int(ids[c])
            k += 1
        for off in range(1, eff + 1):
            c = pos + off
            if c >= n or ids[c] < 0:
                break
            ctx[out, k] = int(ids[c])
            k += 1
        if k == 0:
            pos += 1
            continue
        ctx[out, k:w2] = -1
        targets[out] = w
        out += 1
        pos += 1
    return out, pos


# ------------------------------------------------------------- public api


def skipgram_pairs(
    ids: np.ndarray,
    start: int,
    window: int,
    cap: int,
    keep: Optional[np.ndarray] = None,
    seed: int = 1,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Generate up to ``cap`` (center, context) pairs from ``ids[start:]``.
    Returns (centers, contexts, next_pos). Native C++ when available."""
    ids = np.ascontiguousarray(ids, np.int32)
    centers = np.empty(cap, np.int32)
    contexts = np.empty(cap, np.int32)
    lib = pairgen_lib()
    if lib is not None:
        next_pos = ctypes.c_longlong(0)
        n = lib.we_skipgram_pairs(
            ids, len(ids), start, window, _keep_ptr(keep), seed,
            centers, contexts, cap, ctypes.byref(next_pos),
        )
        return centers[:n], contexts[:n], next_pos.value
    n, pos = _py_skipgram(ids, len(ids), start, window, keep, seed, centers, contexts, cap)
    return centers[:n], contexts[:n], pos


def cbow_batch(
    ids: np.ndarray,
    start: int,
    window: int,
    cap: int,
    keep: Optional[np.ndarray] = None,
    seed: int = 1,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Generate up to ``cap`` CBOW rows: (targets, ctx (cap, 2*window) padded
    with -1, next_pos)."""
    ids = np.ascontiguousarray(ids, np.int32)
    targets = np.empty(cap, np.int32)
    ctx = np.empty((cap, 2 * window), np.int32)
    lib = pairgen_lib()
    if lib is not None:
        next_pos = ctypes.c_longlong(0)
        n = lib.we_cbow_batch(
            ids, len(ids), start, window, _keep_ptr(keep), seed,
            targets, ctx, cap, ctypes.byref(next_pos),
        )
        return targets[:n], ctx[:n], next_pos.value
    n, pos = _py_cbow(ids, len(ids), start, window, keep, seed, targets, ctx, cap)
    return targets[:n], ctx[:n], pos


def presort(
    ids_flat: np.ndarray,
    weights: Optional[np.ndarray] = None,
    raw_mode: bool = False,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Native stable counting-sort metadata (perm, sorted_ids, scale) for the
    sorted-scatter step — O(N+V) vs numpy argsort's O(N log N). Returns None
    when the native library is unavailable or ids contain negatives (callers
    fall back to the numpy path in skipgram.presort_updates)."""
    lib = pairgen_lib()
    if lib is None:
        return None
    ids_flat = np.ascontiguousarray(ids_flat.reshape(-1), np.int32)
    n = len(ids_flat)
    if weights is not None:
        weights = np.ascontiguousarray(weights.reshape(-1), np.float32)
        wptr = weights.ctypes.data_as(ctypes.c_void_p)
    else:
        wptr = None
    perm = np.empty(n, np.int32)
    sorted_ids = np.empty(n, np.int32)
    scale = np.empty(n, np.float32)
    rc = lib.we_presort(ids_flat, wptr, n, int(raw_mode), perm, sorted_ids, scale)
    if rc != 0:
        return None
    return perm, sorted_ids, scale


def ns_finalize(
    centers: np.ndarray,
    targets: np.ndarray,
    negatives: int,
    prob: np.ndarray,
    alias: np.ndarray,
    seed: int,
    raw_mode: bool = False,
) -> Optional[dict]:
    """One-call NS batch finalize: outputs [target|negs] + presort metadata
    for both embedding tables (input rows = centers, output rows = outputs).
    Returns the batch-dict fields, or None when the native library is
    unavailable."""
    lib = pairgen_lib()
    if lib is None:
        return None
    if len(prob) > 32 * len(targets):
        return None  # counting-sort decline threshold; skip the allocations
    centers = np.ascontiguousarray(centers, np.int32)
    targets = np.ascontiguousarray(targets, np.int32)
    prob = np.ascontiguousarray(prob, np.float32)
    alias = np.ascontiguousarray(alias, np.int32)
    b = len(targets)
    k1 = 1 + negatives
    outputs = np.empty((b, k1), np.int32)
    in_perm = np.empty(b, np.int32)
    in_sort = np.empty(b, np.int32)
    in_scale = np.empty(b, np.float32)
    out_perm = np.empty(b * k1, np.int32)
    out_sort = np.empty(b * k1, np.int32)
    out_scale = np.empty(b * k1, np.float32)
    rc = lib.we_ns_finalize(
        centers, targets, b, negatives, prob, alias, len(prob), seed or 1,
        int(raw_mode), outputs.reshape(-1), in_perm, in_sort, in_scale,
        out_perm, out_sort, out_scale,
    )
    if rc != 0:
        return None
    return {
        "outputs": outputs,
        "in_perm": in_perm, "in_sort": in_sort, "in_scale": in_scale,
        "out_perm": out_perm, "out_sort": out_sort, "out_scale": out_scale,
    }


def alias_sample(
    prob: np.ndarray, alias: np.ndarray, n: int, seed: int
) -> Optional[np.ndarray]:
    """Native alias-method draws (vocab = len(prob)); None without the lib."""
    lib = pairgen_lib()
    if lib is None:
        return None
    prob = np.ascontiguousarray(prob, np.float32)
    alias = np.ascontiguousarray(alias, np.int32)
    out = np.empty(n, np.int32)
    rc = lib.we_alias_sample(prob, alias, len(prob), n, seed or 1, out)
    if rc != n:  # error convention parity with presort/ns_finalize wrappers
        return None
    return out
