"""ctypes surface over the native host-runtime library (``runtime.cpp``).

``MtQueue`` / ``Waiter`` / ``BlobArena`` are C++ rebuilds of the reference's
host-side primitives (ref: include/multiverso/util/mt_queue.h:19-146,
util/waiter.h:9-33, util/allocator.h:14-61, blob.h:13-53). Their TPU-era job
is the host data pipeline: ctypes releases the GIL during calls, so a native
producer thread (pairgen, readers) and the device-feeder thread hand off
buffers through ``MtQueue`` with real parallelism.

Pure-Python fallbacks (``queue.Queue``-based) keep everything working when no
compiler is present; ``have_native_runtime()`` reports which one you got.
"""

from __future__ import annotations

import ctypes
import queue as _pyqueue
import threading
from typing import Optional

import numpy as np

import multiverso_tpu.analysis.mvtsan as _mvtsan
from multiverso_tpu.native import build_native_lib
from multiverso_tpu.utils.log import CHECK

__all__ = ["MtQueue", "Waiter", "BlobArena", "have_native_runtime"]

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        path = build_native_lib("runtime.cpp", "libmv_runtime.so")
        if path:
            lib = ctypes.CDLL(path)
            u64, i64, i32, vp = (
                ctypes.c_uint64,
                ctypes.c_longlong,
                ctypes.c_int,
                ctypes.c_void_p,
            )
            for name, res, args in [
                ("mvq_create", vp, []),
                ("mvq_push", i32, [vp, u64]),
                ("mvq_pop", i32, [vp, ctypes.POINTER(u64), i64]),
                ("mvq_try_pop", i32, [vp, ctypes.POINTER(u64)]),
                ("mvq_exit", None, [vp]),
                ("mvq_size", i64, [vp]),
                ("mvq_alive", i32, [vp]),
                ("mvq_destroy", None, [vp]),
                ("mvw_create", vp, [i32]),
                ("mvw_wait", i32, [vp, i64]),
                ("mvw_notify", None, [vp]),
                ("mvw_reset", None, [vp, i32]),
                ("mvw_destroy", None, [vp]),
                ("mva_create", vp, [u64]),
                ("mva_alloc", vp, [vp, u64]),
                ("mva_ref", i32, [vp, vp]),
                ("mva_unref", i32, [vp, vp]),
                ("mva_bytes_allocated", u64, [vp]),
                ("mva_destroy", None, [vp]),
            ]:
                fn = getattr(lib, name)
                fn.restype = res
                fn.argtypes = args
            _LIB = lib
    return _LIB


def have_native_runtime() -> bool:
    return _lib() is not None


class MtQueue:
    """Blocking MPMC queue of uint64 handles with ``exit()`` poison
    (ref: mt_queue.h Push/Pop/TryPop/Exit/Alive contract)."""

    def __init__(self):
        lib = _lib()
        self._lib = lib
        if lib is not None:
            self._q = lib.mvq_create()
        else:
            self._q = _pyqueue.Queue()
            self._alive = True

    def push(self, value: int) -> bool:
        if _mvtsan._ACTIVE:
            # push→pop edge: the popper sees everything the pusher did.
            # The native queue has no tracked internals, so the edge is
            # recorded on the Python wrapper for both backends.
            _mvtsan.sync_release(_mvtsan.sync_of(self))
        if self._lib is not None:
            return bool(self._lib.mvq_push(self._q, value))
        if not self._alive:
            return False
        self._q.put(int(value))
        return True

    def pop(self, timeout_ms: int = -1) -> Optional[int]:
        """Blocks; returns None on exit-and-drained or timeout."""
        if self._lib is not None:
            out = ctypes.c_uint64()
            if self._lib.mvq_pop(self._q, ctypes.byref(out), timeout_ms):
                if _mvtsan._ACTIVE:
                    _mvtsan.sync_acquire(_mvtsan.sync_of(self))
                return out.value
            return None
        timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
        # poll step never exceeds the caller's timeout: the serving
        # batcher passes millisecond deadlines, and a flat 50 ms step
        # would quietly stretch its max_delay_s bound ~25x on hosts
        # without the native lib (the 50 ms ceiling only bounds how
        # stale the exit()-poison check can get while blocking forever)
        deadline_step = 0.05 if timeout is None else max(min(0.05, timeout), 1e-4)
        waited = 0.0
        while True:
            try:
                value = self._q.get(timeout=deadline_step)
                if _mvtsan._ACTIVE:
                    _mvtsan.sync_acquire(_mvtsan.sync_of(self))
                return value
            except _pyqueue.Empty:
                if not self._alive:
                    # exit-and-drained contract (native MtQueue::Pop drains
                    # remaining items after Exit): one final non-blocking
                    # check closes the put-then-exit race
                    try:
                        value = self._q.get_nowait()
                    except _pyqueue.Empty:
                        return None
                    if _mvtsan._ACTIVE:
                        _mvtsan.sync_acquire(_mvtsan.sync_of(self))
                    return value
                waited += deadline_step
                if timeout is not None and waited >= timeout:
                    return None

    def try_pop(self) -> Optional[int]:
        if self._lib is not None:
            out = ctypes.c_uint64()
            if self._lib.mvq_try_pop(self._q, ctypes.byref(out)):
                if _mvtsan._ACTIVE:
                    _mvtsan.sync_acquire(_mvtsan.sync_of(self))
                return out.value
            return None
        try:
            value = self._q.get_nowait()
        except _pyqueue.Empty:
            return None
        if _mvtsan._ACTIVE:
            _mvtsan.sync_acquire(_mvtsan.sync_of(self))
        return value

    def exit(self) -> None:
        if self._lib is not None:
            self._lib.mvq_exit(self._q)
        else:
            self._alive = False

    def size(self) -> int:
        if self._lib is not None:
            return self._lib.mvq_size(self._q)
        return self._q.qsize()

    def alive(self) -> bool:
        if self._lib is not None:
            return bool(self._lib.mvq_alive(self._q))
        return self._alive

    def __del__(self):
        if getattr(self, "_lib", None) is not None:
            self._lib.mvq_destroy(self._q)


class Waiter:
    """Counted-down latch (ref: waiter.h Wait/Notify/Reset)."""

    def __init__(self, count: int = 1):
        lib = _lib()
        self._lib = lib
        if lib is not None:
            self._w = lib.mvw_create(count)
        else:
            self._count = count
            self._cv = threading.Condition()

    def wait(self, timeout_ms: int = -1) -> bool:
        if self._lib is not None:
            ok = bool(self._lib.mvw_wait(self._w, timeout_ms))
        else:
            with self._cv:
                timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
                ok = self._cv.wait_for(
                    lambda: self._count <= 0, timeout
                )
        if ok and _mvtsan._ACTIVE:
            # latch edge: the waiter sees everything every notifier did
            _mvtsan.sync_acquire(_mvtsan.sync_of(self))
        return ok

    def notify(self) -> None:
        if _mvtsan._ACTIVE:
            _mvtsan.sync_release(_mvtsan.sync_of(self))
        if self._lib is not None:
            self._lib.mvw_notify(self._w)
        else:
            with self._cv:
                self._count -= 1
                self._cv.notify_all()

    def reset(self, count: int) -> None:
        if self._lib is not None:
            self._lib.mvw_reset(self._w, count)
        else:
            with self._cv:
                self._count = count

    def __del__(self):
        if getattr(self, "_lib", None) is not None:
            self._lib.mvw_destroy(self._w)


class BlobArena:
    """Ref-counted aligned blocks recycled through size-class free lists
    (SmartAllocator/Blob semantics). ``alloc`` returns a numpy uint8 view of
    the block; ``addr(view)``/``ref``/``unref`` manage its lifetime across
    threads without the GC in the loop."""

    def __init__(self, alignment: int = 64):
        lib = _lib()
        CHECK(lib is not None, "BlobArena requires the native runtime (g++)")
        self._lib = lib
        self._a = lib.mva_create(alignment)

    def alloc(self, size: int) -> np.ndarray:
        p = self._lib.mva_alloc(self._a, size)
        CHECK(p, "arena allocation failed")
        return np.ctypeslib.as_array(
            ctypes.cast(p, ctypes.POINTER(ctypes.c_uint8)), shape=(size,)
        )

    @staticmethod
    def addr(view: np.ndarray) -> int:
        return view.ctypes.data

    def ref(self, view_or_addr) -> None:
        ok = self._lib.mva_ref(self._a, ctypes.c_void_p(self._addr(view_or_addr)))
        CHECK(ok, "ref of unknown arena block")

    def unref(self, view_or_addr) -> int:
        """Returns the remaining refcount; at 0 the block is recycled —
        any numpy views into it must no longer be used."""
        rc = self._lib.mva_unref(self._a, ctypes.c_void_p(self._addr(view_or_addr)))
        CHECK(rc >= 0, "unref of unknown arena block")
        return rc

    def bytes_allocated(self) -> int:
        return self._lib.mva_bytes_allocated(self._a)

    @staticmethod
    def _addr(view_or_addr) -> int:
        if isinstance(view_or_addr, np.ndarray):
            return view_or_addr.ctypes.data
        return int(view_or_addr)

    def __del__(self):
        if getattr(self, "_lib", None) is not None:
            self._lib.mva_destroy(self._a)
