"""ctypes surface over the native sample-text parser (``textparse.cpp``).

``parse_sparse_chunk`` scans one raw text chunk into CSR arrays — the
LogisticRegression ingest hot path (ref: Applications/LogisticRegression/
src/reader.cpp text parsers). ``have_native_textparse()`` reports whether
the C++ path is live; callers fall back to the per-line Python parser.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from multiverso_tpu.native import build_native_lib

__all__ = ["have_native_textparse", "parse_sparse_chunk"]

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        path = build_native_lib("textparse.cpp", "libmv_textparse.so")
        if path:
            lib = ctypes.CDLL(path)
            LL = ctypes.c_longlong
            lib.lr_parse_sparse.restype = LL
            lib.lr_parse_sparse.argtypes = [
                ctypes.c_char_p, LL, ctypes.c_int,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                LL, LL, ctypes.POINTER(LL),
            ]
            _LIB = lib
    return _LIB


def have_native_textparse() -> bool:
    return _lib() is not None


def parse_sparse_chunk(
    chunk: bytes,
    with_weight: bool,
    max_samples: Optional[int] = None,
    max_nnz: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]]:
    """Parse sparse sample lines from ``chunk``. Returns
    ``(labels, weights, offsets, keys, values, consumed)`` in CSR layout
    (``offsets`` has n+1 entries), or None when the native lib is absent.
    ``consumed`` is the byte offset to resume from (last complete line).
    Malformed lines are skipped (the pure-Python parser raises instead).

    Output buffers are sized from the chunk itself by default (a sample or a
    feature token each need >= 2 bytes of text), so a full chunk can always
    parse in one call; results are compact copies, not views into oversized
    scratch buffers."""
    lib = _lib()
    if lib is None:
        return None
    if max_samples is None:
        max_samples = len(chunk) // 2 + 1
    if max_nnz is None:
        max_nnz = len(chunk) // 2 + 1
    labels = np.empty(max_samples, np.int32)
    weights = np.empty(max_samples, np.float32)
    offsets = np.empty(max_samples + 1, np.int64)
    keys = np.empty(max_nnz, np.int64)
    values = np.empty(max_nnz, np.float32)
    consumed = ctypes.c_longlong(0)
    n = lib.lr_parse_sparse(
        chunk, len(chunk), int(with_weight),
        labels, weights, offsets, keys, values,
        max_samples, max_nnz, ctypes.byref(consumed),
    )
    nnz = offsets[n]
    return (
        labels[:n].copy(),
        weights[:n].copy(),
        offsets[: n + 1].copy(),
        keys[:nnz].copy(),
        values[:nnz].copy(),
        consumed.value,
    )
