// Native sample-text parser for LogisticRegression — the host-side ingest
// hot path.
//
// TPU-native equivalent of the reference's background-thread text parsers
// (ref: Applications/LogisticRegression/src/reader.cpp "default"/"weight"
// parsers over reader.h:20-150): instead of per-line, per-token string
// objects, one call scans a raw text chunk and emits CSR-layout arrays
// (labels, weights, row offsets, keys, values) ready for numpy batching.
//
// Formats (ref: configure.h:56-68):
//   default: "label k:v k:v ..."     (sparse libsvm; v omitted -> 1.0)
//   weight:  "label:weight k:v ..."
//
// The chunk need not end on a line boundary: parsing stops at the last
// complete line and *consumed says where to resume.

#include <cstdint>
#include <cstdlib>

namespace {

inline bool is_space(char c) { return c == ' ' || c == '\t' || c == '\r'; }

// Minimal fast float parse (plain decimals, the common case in LR corpora).
// Exponent or other exotic forms re-parse via strtod on a bounded local
// copy of the token, so parsing can never cross the line boundary (strtod
// itself skips whitespace including '\n' and would otherwise eat the next
// line's label). On no progress, *out == token_start and 0.0 is returned.
inline double parse_float(const char* token_start, const char* end,
                          const char** out) {
  const char* p = token_start;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) neg = (*p++ == '-');
  double v = 0.0;
  bool any_digit = false;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10.0 + (*p++ - '0');
    any_digit = true;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      v += (*p++ - '0') * scale;
      scale *= 0.1;
      any_digit = true;
    }
  }
  if (any_digit && p < end && (*p == 'e' || *p == 'E')) {
    // exponent: strtod on a NUL-terminated copy bounded by the token
    char tmp[64];
    const char* tok_end = token_start;
    while (tok_end < end && !is_space(*tok_end) && *tok_end != '\n') ++tok_end;
    size_t n = (size_t)(tok_end - token_start);
    if (n >= sizeof(tmp)) n = sizeof(tmp) - 1;
    for (size_t i = 0; i < n; ++i) tmp[i] = token_start[i];
    tmp[n] = '\0';
    char* after = nullptr;
    v = std::strtod(tmp, &after);
    *out = token_start + (after - tmp);
    return v;
  }
  if (!any_digit) {
    *out = token_start;  // no progress: caller decides (malformed token)
    return 0.0;
  }
  *out = p;
  return neg ? -v : v;
}

// Integer parse; on no digit, *out == start (no progress).
inline long long parse_int(const char* start, const char* end,
                           const char** out) {
  const char* p = start;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) neg = (*p++ == '-');
  long long v = 0;
  bool any = false;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10 + (*p++ - '0');
    any = true;
  }
  *out = any ? p : start;
  return neg ? -v : v;
}

}  // namespace

extern "C" {

// Parse sparse sample lines from buf[0:len). Returns the number of samples
// written (<= max_samples); stops early when max_samples or max_nnz would
// overflow, or at the last complete line. *consumed = bytes of buf fully
// parsed (resume offset). offsets has max_samples+1 slots; offsets[0]=0.
long long lr_parse_sparse(const char* buf, long long len, int with_weight,
                          int32_t* labels, float* weights, int64_t* offsets,
                          int64_t* keys, float* values,
                          long long max_samples, long long max_nnz,
                          long long* consumed) {
  long long ns = 0;
  long long nnz = 0;
  long long line_start = 0;
  offsets[0] = 0;
  while (line_start < len && ns < max_samples) {
    // find end of line; incomplete trailing line (no '\n') is left for the
    // next chunk unless this is the final flush (caller passes it again
    // with the same data — we detect completeness only by '\n')
    long long eol = line_start;
    while (eol < len && buf[eol] != '\n') ++eol;
    if (eol >= len) break;  // incomplete line: resume here next call

    const char* p = buf + line_start;
    const char* end = buf + eol;
    while (p < end && is_space(*p)) ++p;
    if (p >= end) {  // blank line
      line_start = eol + 1;
      continue;
    }
    // label [:weight] — label parsed as float then truncated, matching the
    // Python fallback's int(float(tok)) (labels like "1.0" are legal)
    const char* q;
    double label_f = parse_float(p, end, &q);
    bool bad_line = (q == p);
    float weight = 1.0f;
    if (!bad_line && with_weight && q < end && *q == ':') {
      const char* w0 = q + 1;
      weight = (float)parse_float(w0, end, &q);
      if (q == w0) weight = 1.0f;  // empty weight -> default
    }
    p = q;
    // features
    long long row_nnz = 0;
    bool overflow = false;
    while (!bad_line) {
      while (p < end && is_space(*p)) ++p;
      if (p >= end) break;
      long long k = parse_int(p, end, &q);
      if (q == p) {  // unparseable token: drop the whole line
        bad_line = true;
        break;
      }
      float v = 1.0f;
      if (q < end && *q == ':') {
        const char* v0 = q + 1;
        v = (float)parse_float(v0, end, &q);
        if (q == v0) v = 1.0f;  // empty value ("k:") -> 1, like the fallback
      }
      p = q;
      if (nnz + row_nnz >= max_nnz) {
        overflow = true;
        break;
      }
      keys[nnz + row_nnz] = k;
      values[nnz + row_nnz] = v;
      ++row_nnz;
    }
    if (overflow) break;  // whole line resumes next call (larger caps)
    if (!bad_line) {
      labels[ns] = (int32_t)label_f;
      weights[ns] = weight;
      nnz += row_nnz;
      offsets[++ns] = nnz;
    }  // bad_line: skipped entirely, but consumed advances — no spin
    line_start = eol + 1;
  }
  *consumed = line_start;
  return ns;
}

}  // extern "C"
