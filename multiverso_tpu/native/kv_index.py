"""Batched u64 key -> dense slot index (ctypes over kv_index.cpp).

Host control plane for hash-keyed tables: the KV table and the unbounded-key
FTRL store resolve whole minibatches of 64-bit feature ids to dense HBM slots
in one native call (ref: the per-key unordered_map / hopscotch walks —
include/multiverso/table/kv_table.h:48-65,
Applications/LogisticRegression/src/util/hopscotch_hash.h). A vectorised
numpy fallback (open addressing with batched probe rounds) keeps the module
working without a compiler — still orders of magnitude faster than a
per-key Python dict walk.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

from multiverso_tpu.native import build_native_lib

__all__ = ["KVIndex"]

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_LOCK = threading.Lock()


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        path = build_native_lib("kv_index.cpp", "libmv_kv_index.so")
        if path:
            lib = ctypes.CDLL(path)
            LL = ctypes.c_longlong
            U64P = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
            I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.mv_kv_index_new.restype = ctypes.c_void_p
            lib.mv_kv_index_new.argtypes = [LL]
            lib.mv_kv_index_free.argtypes = [ctypes.c_void_p]
            lib.mv_kv_index_size.restype = LL
            lib.mv_kv_index_size.argtypes = [ctypes.c_void_p]
            lib.mv_kv_index_resolve.restype = LL
            lib.mv_kv_index_resolve.argtypes = [
                ctypes.c_void_p, U64P, LL, ctypes.c_int, I64P,
            ]
            lib.mv_kv_index_keys.restype = LL
            lib.mv_kv_index_keys.argtypes = [ctypes.c_void_p, U64P]
            _LIB = lib
        return _LIB


class _NumpyIndex:
    """Vectorised open-addressing fallback: batched probe rounds resolve a
    whole key array per numpy pass (no per-key Python loop)."""

    def __init__(self, initial: int):
        cap = 64
        while cap < initial * 2:
            cap <<= 1
        self._cell_key = np.zeros(cap, np.uint64)
        self._cell_slot = np.full(cap, -1, np.int64)
        self._dense: list = []  # slot -> key

    @staticmethod
    def _hash(x: np.ndarray) -> np.ndarray:
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))

    def __len__(self) -> int:
        return len(self._dense)

    def _grow(self) -> None:
        old_k, old_s = self._cell_key, self._cell_slot
        cap = len(old_k) << 1
        self._cell_key = np.zeros(cap, np.uint64)
        self._cell_slot = np.full(cap, -1, np.int64)
        live = old_s >= 0
        self._insert_cells(old_k[live], old_s[live])

    def _insert_cells(self, keys: np.ndarray, slots: np.ndarray) -> None:
        mask = np.uint64(len(self._cell_key) - 1)
        j = self._hash(keys) & mask
        pending = np.arange(len(keys))
        while len(pending):
            empty = self._cell_slot[j] < 0
            # place one pending key per distinct empty cell per round
            # (np.unique keeps the first occurrence per cell index)
            cells, first = np.unique(j[empty], return_index=True)
            pick = np.flatnonzero(empty)[first]
            self._cell_key[cells] = keys[pick]
            self._cell_slot[cells] = slots[pick]
            placed = np.zeros(len(pending), bool)
            placed[pick] = True
            pending = pending[~placed]
            keys, j = keys[~placed], j[~placed]
            slots = slots[~placed]
            j = (j + np.uint64(1)) & mask  # collided or occupied: step on
        # note: duplicate keys are the caller's responsibility (resolve dedups)

    def _lookup(self, keys: np.ndarray) -> np.ndarray:
        """Probe-only batch lookup: -1 for absent keys."""
        out = np.full(len(keys), -1, np.int64)
        mask = np.uint64(len(self._cell_key) - 1)
        j = self._hash(keys) & mask
        pending = np.arange(len(keys))
        while len(pending):
            ck = self._cell_key[j]
            cs = self._cell_slot[j]
            hit = (cs >= 0) & (ck == keys[pending])
            out[pending[hit]] = cs[hit]
            done = hit | (cs < 0)  # found, or empty cell => absent
            pending = pending[~done]
            j = (j[~done] + np.uint64(1)) & mask
        return out

    def resolve(self, keys: np.ndarray, create: bool) -> np.ndarray:
        # lookup first, then create ALL missing keys in first-seen array
        # order — the exact slot-order contract of the native backend (a
        # probe-round discovery order would depend on hash collisions)
        out = self._lookup(keys)
        if not create:
            return out
        missing = out < 0
        if missing.any():
            pos = np.flatnonzero(missing)
            uk, first = np.unique(keys[pos], return_index=True)
            order = np.argsort(pos[first], kind="stable")  # first-seen order
            base = len(self._dense)
            new_slots_sorted = np.empty(len(uk), np.int64)  # aligned with uk
            new_slots_sorted[order] = base + np.arange(len(uk))
            self._dense.extend(uk[order])
            # grow BEFORE inserting: a batch larger than the free cells
            # would otherwise probe a full table forever
            while len(self._dense) * 10 > len(self._cell_key) * 7:
                self._grow()
            self._insert_cells(uk, new_slots_sorted)
            out[pos] = new_slots_sorted[np.searchsorted(uk, keys[pos])]
        return out

    def keys(self) -> np.ndarray:
        return np.asarray(self._dense, np.uint64)


class KVIndex:
    """key(u64) -> dense slot, batched. Slots are assigned in first-seen
    order and never move; device value arrays only ever append."""

    def __init__(self, initial_capacity: int = 1024):
        lib = _lib()
        self._lib = lib
        if lib is not None:
            self._h = lib.mv_kv_index_new(int(initial_capacity))
        else:
            self._np = _NumpyIndex(int(initial_capacity))

    def __del__(self):
        if getattr(self, "_lib", None) is not None and getattr(self, "_h", None):
            self._lib.mv_kv_index_free(self._h)
            self._h = None

    def __len__(self) -> int:
        if self._lib is not None:
            return int(self._lib.mv_kv_index_size(self._h))
        return len(self._np)

    def resolve(self, keys, create: bool = False) -> np.ndarray:
        """Slots for ``keys`` (any integer dtype, viewed as u64); -1 for
        unknown keys when ``create`` is False. One native call per batch."""
        keys = np.ascontiguousarray(np.asarray(keys).reshape(-1))
        if keys.dtype != np.uint64:
            keys = keys.astype(np.int64).view(np.uint64)
        if self._lib is not None:
            out = np.empty(len(keys), np.int64)
            self._lib.mv_kv_index_resolve(
                self._h, keys, len(keys), 1 if create else 0, out
            )
            return out
        return self._np.resolve(keys, create)

    def keys(self) -> np.ndarray:
        """All keys in dense-slot order (uint64 view)."""
        if self._lib is not None:
            n = len(self)
            out = np.empty(n, np.uint64)
            if n:
                self._lib.mv_kv_index_keys(self._h, out)
            return out
        return self._np.keys()
