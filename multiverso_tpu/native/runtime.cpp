// Native host-runtime primitives: blocking MPMC queue, waiter latch,
// ref-counted blob arena.
//
// TPU-native rebuild of the reference's C++ host-side runtime plumbing:
//   - MtQueue<T>  (ref: include/multiverso/util/mt_queue.h:19-146) — the
//     mutex+condvar blocking queue with Exit() poison that backs every actor
//     mailbox and the WordEmbedding BlockQueue;
//   - Waiter      (ref: include/multiverso/util/waiter.h:9-33) — the
//     counted-down latch behind blocking table ops;
//   - SmartAllocator/Blob (ref: include/multiverso/util/allocator.h:14-61,
//     include/multiverso/blob.h:13-53) — aligned refcounted blocks recycled
//     through size-class free lists.
//
// On TPU the actor mailboxes are gone (XLA owns dispatch), but the host data
// pipeline is not: these primitives carry batch buffers from native producer
// threads (pairgen/readers, GIL released) to the feeder thread. Handles are
// opaque uint64 payloads; the queue never touches Python objects.
//
// C ABI only — consumed via ctypes (no pybind11 in the image).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

// ----------------------------------------------------------------- queue

struct MtQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<uint64_t> items;
  bool exited = false;

  bool Push(uint64_t v) {
    {
      std::lock_guard<std::mutex> lk(mu);
      if (exited) return false;
      items.push_back(v);
    }
    cv.notify_one();
    return true;
  }

  // Blocks until an item or Exit. Returns false on exit-and-drained
  // (mt_queue.h Pop contract: Exit() wakes everyone, Pop fails thereafter).
  bool Pop(uint64_t* out, long long timeout_ms) {
    std::unique_lock<std::mutex> lk(mu);
    auto ready = [&] { return !items.empty() || exited; };
    if (timeout_ms < 0) {
      cv.wait(lk, ready);
    } else if (!cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready)) {
      return false;  // timeout
    }
    if (items.empty()) return false;  // exited
    *out = items.front();
    items.pop_front();
    return true;
  }

  bool TryPop(uint64_t* out) {
    std::lock_guard<std::mutex> lk(mu);
    if (items.empty()) return false;
    *out = items.front();
    items.pop_front();
    return true;
  }

  void Exit() {
    {
      std::lock_guard<std::mutex> lk(mu);
      exited = true;
    }
    cv.notify_all();
  }

  long long Size() {
    std::lock_guard<std::mutex> lk(mu);
    return static_cast<long long>(items.size());
  }

  bool Alive() {
    std::lock_guard<std::mutex> lk(mu);
    return !exited;
  }
};

// ----------------------------------------------------------------- waiter

struct Waiter {
  std::mutex mu;
  std::condition_variable cv;
  int count;

  explicit Waiter(int n) : count(n) {}

  bool Wait(long long timeout_ms) {
    std::unique_lock<std::mutex> lk(mu);
    auto done = [&] { return count <= 0; };
    if (timeout_ms < 0) {
      cv.wait(lk, done);
      return true;
    }
    return cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), done);
  }

  void Notify() {
    {
      std::lock_guard<std::mutex> lk(mu);
      --count;
    }
    cv.notify_all();
  }

  void Reset(int n) {
    std::lock_guard<std::mutex> lk(mu);
    count = n;
  }
};

// ------------------------------------------------------------------ arena
//
// Size-class free-listed aligned blocks with refcount headers, recycled on
// release (SmartAllocator semantics). Block layout: [header][payload]; the
// handle given out is the payload address.

struct BlockHeader {
  std::atomic<int> refcount;
  uint64_t size_class;
};

struct Arena {
  std::mutex mu;
  size_t alignment;
  // size class -> free payload pointers
  std::unordered_map<uint64_t, std::vector<void*>> free_lists;
  // payload -> header (also serves as the live-block registry)
  std::unordered_map<void*, BlockHeader*> headers;
  size_t bytes_allocated = 0;  // cumulative malloc'd (not recycled) bytes

  explicit Arena(size_t align) : alignment(align < 8 ? 8 : align) {}

  ~Arena() {
    for (auto& kv : headers) {
      std::free(reinterpret_cast<char*>(kv.first) - header_pad());
    }
  }

  size_t header_pad() const {
    return (sizeof(BlockHeader) + alignment - 1) / alignment * alignment;
  }

  static uint64_t SizeClass(uint64_t n) {
    // next power of two, floor 64 (allocator.h free-list keyed by size)
    uint64_t c = 64;
    while (c < n) c <<= 1;
    return c;
  }

  void* Alloc(uint64_t n) {
    const uint64_t cls = SizeClass(n);
    {
      std::lock_guard<std::mutex> lk(mu);
      auto it = free_lists.find(cls);
      if (it != free_lists.end() && !it->second.empty()) {
        void* payload = it->second.back();
        it->second.pop_back();
        headers[payload]->refcount.store(1);
        return payload;
      }
    }
    const size_t pad = header_pad();
    char* raw = static_cast<char*>(std::aligned_alloc(
        alignment, (pad + cls + alignment - 1) / alignment * alignment));
    if (!raw) return nullptr;
    auto* hdr = reinterpret_cast<BlockHeader*>(raw);
    hdr->refcount.store(1);
    hdr->size_class = cls;
    void* payload = raw + pad;
    {
      std::lock_guard<std::mutex> lk(mu);
      headers[payload] = hdr;
      bytes_allocated += cls;
    }
    return payload;
  }

  bool Ref(void* payload) {
    std::lock_guard<std::mutex> lk(mu);
    auto it = headers.find(payload);
    if (it == headers.end()) return false;
    it->second->refcount.fetch_add(1);
    return true;
  }

  // Returns the post-decrement refcount, or -1 on unknown pointer. At zero
  // the block returns to its size-class free list (never to the OS).
  int Unref(void* payload) {
    std::lock_guard<std::mutex> lk(mu);
    auto it = headers.find(payload);
    if (it == headers.end()) return -1;
    int rc = it->second->refcount.fetch_sub(1) - 1;
    if (rc == 0) free_lists[it->second->size_class].push_back(payload);
    return rc;
  }
};

}  // namespace

extern "C" {

// queue
void* mvq_create() { return new MtQueue(); }
int mvq_push(void* q, uint64_t v) { return static_cast<MtQueue*>(q)->Push(v); }
int mvq_pop(void* q, uint64_t* out, long long timeout_ms) {
  return static_cast<MtQueue*>(q)->Pop(out, timeout_ms);
}
int mvq_try_pop(void* q, uint64_t* out) {
  return static_cast<MtQueue*>(q)->TryPop(out);
}
void mvq_exit(void* q) { static_cast<MtQueue*>(q)->Exit(); }
long long mvq_size(void* q) { return static_cast<MtQueue*>(q)->Size(); }
int mvq_alive(void* q) { return static_cast<MtQueue*>(q)->Alive(); }
void mvq_destroy(void* q) { delete static_cast<MtQueue*>(q); }

// waiter
void* mvw_create(int count) { return new Waiter(count); }
int mvw_wait(void* w, long long timeout_ms) {
  return static_cast<Waiter*>(w)->Wait(timeout_ms);
}
void mvw_notify(void* w) { static_cast<Waiter*>(w)->Notify(); }
void mvw_reset(void* w, int count) { static_cast<Waiter*>(w)->Reset(count); }
void mvw_destroy(void* w) { delete static_cast<Waiter*>(w); }

// arena
void* mva_create(uint64_t alignment) { return new Arena(alignment); }
void* mva_alloc(void* a, uint64_t size) { return static_cast<Arena*>(a)->Alloc(size); }
int mva_ref(void* a, void* p) { return static_cast<Arena*>(a)->Ref(p); }
int mva_unref(void* a, void* p) { return static_cast<Arena*>(a)->Unref(p); }
uint64_t mva_bytes_allocated(void* a) {
  Arena* arena = static_cast<Arena*>(a);
  std::lock_guard<std::mutex> lk(arena->mu);
  return arena->bytes_allocated;
}
void mva_destroy(void* a) { delete static_cast<Arena*>(a); }

}  // extern "C"
