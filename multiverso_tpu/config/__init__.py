"""Declarative configuration layer: the flag-constraint model.

``config.constraints`` is the single source of truth for cross-flag
implications and validity requirements.  Runtime validation
(``apply_implications`` / ``check_options``), the mvlint R12 rule, and
the generated DEPLOY.md constraint table all derive from the same
declarations — hand-rolled implication code anywhere else is lint drift.
"""

from multiverso_tpu.config import constraints

__all__ = ["constraints"]
