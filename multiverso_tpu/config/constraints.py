"""Single source of truth for cross-flag implications and requirements.

The reference's flag semantics couple flags to each other: a tiered
table budget re-routes training through the pipelined PS loop, wire
compression only exists on the pipelined path, the device pipeline and
the PS tables are mutually exclusive.  Before this module those rules
lived as hand-written ``if``/``CHECK`` blocks inside ``app.py`` — which
is exactly how the DEPLOY.md flag table and the code drifted apart.

Three consumers read these declarations and nothing else:

* **runtime validation** — ``WordEmbedding`` calls
  ``apply_implications`` (flag rewrites, with the same log lines the old
  inline block emitted) and ``check_options`` (hard ``CHECK``
  failures);
* **mvlint R12** — flags any module outside this one that re-implements
  an implication (writes to an implied flag on an options object, or a
  ``CHECK`` over a constrained flag pair), and any drift between these
  declarations and the generated DEPLOY.md block;
* **DEPLOY.md** — the "Flag constraints" section between the
  ``mvlint:flag-constraints`` markers is ``render_markdown()`` output,
  regenerated via ``python -m multiverso_tpu.analysis
  --constraint-table``.

Declarations are data, not code paths: an ``Implication`` names the
trigger flag, the forced flag, the forced value, and the guard under
which the rewrite (and its log line) applies; a ``Requirement`` names
the flags it couples and a predicate over ``(options, Env)``.  Keeping
the flag names as strings is what lets R12 and the doc generator reason
about the model without executing it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

__all__ = [
    "Env",
    "Implication",
    "Requirement",
    "IMPLICATIONS",
    "REQUIREMENTS",
    "apply_implications",
    "check_options",
    "constrained_flags",
    "implied_flags",
    "render_markdown",
    "MARKER_BEGIN",
    "MARKER_END",
]


@dataclasses.dataclass(frozen=True)
class Env:
    """Facts about the launch environment that requirements may read.

    Kept separate from the options object so the model stays importable
    (and testable) without jax: the caller samples the environment once
    and passes it in."""

    process_count: int = 1


@dataclasses.dataclass(frozen=True)
class Implication:
    """``trigger`` active (``when``) forces ``flag`` to ``value``.

    ``guard`` narrows the rewrite to the current-value states where it
    (and its log line) should apply — e.g. the depth bump only fires
    when the user left ``-ps_pipeline_depth`` at 0.  ``log`` is emitted
    through the caller-supplied logger exactly when the rewrite
    happens, preserving the historical inline-block messages."""

    name: str
    trigger: str
    when: Callable[[Any], bool]
    flag: str
    value: Any
    doc: str
    guard: Optional[Callable[[Any], bool]] = None
    log: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Requirement:
    """``predicate(options, env)`` must hold or the run is invalid.

    ``flags`` names every flag the predicate couples — that tuple is
    what R12 uses to claim ownership of the pair: a hand-written CHECK
    over the same flags anywhere else is drift."""

    name: str
    flags: Tuple[str, ...]
    predicate: Callable[[Any, Env], bool]
    message: Callable[[Any, Env], str]
    doc: str


# ---------------------------------------------------------------------------
# The model.  Order matters for implications: rewrites run top to
# bottom, and later guards read the values earlier rewrites produced.
# ---------------------------------------------------------------------------

IMPLICATIONS: Tuple[Implication, ...] = (
    Implication(
        name="tier_replaces_device_pipeline",
        trigger="table_tier_hbm_mb",
        when=lambda o: o.table_tier_hbm_mb > 0,
        flag="device_pipeline",
        value=False,
        guard=lambda o: o.device_pipeline,
        log=(
            "[WordEmbedding] -table_tier_hbm_mb: the fully "
            "HBM-resident device pipeline assumes the whole table "
            "fits — routing through the tiered PS block loop "
            "instead"
        ),
        doc=(
            "the HBM-resident device pipeline assumes the whole table "
            "fits; tiered runs route through the PS block loop instead"
        ),
    ),
    Implication(
        name="tier_implies_use_ps",
        trigger="table_tier_hbm_mb",
        when=lambda o: o.table_tier_hbm_mb > 0,
        flag="use_ps",
        value=True,
        doc=(
            "tiered tables train block-structured, so the run goes "
            "through the PS table path"
        ),
    ),
    Implication(
        name="tier_implies_pipelined_depth",
        trigger="table_tier_hbm_mb",
        when=lambda o: o.table_tier_hbm_mb > 0,
        flag="ps_pipeline_depth",
        value=1,
        guard=lambda o: o.ps_pipeline_depth == 0,
        log=(
            "[WordEmbedding] -table_tier_hbm_mb: raising "
            "-ps_pipeline_depth to 1 so row faults ride the comms "
            "thread under training"
        ),
        doc=(
            "row faults must ride the comms thread under training, so "
            "depth 0 is raised to 1"
        ),
    ),
    Implication(
        name="tier_disables_sparse_pull",
        trigger="table_tier_hbm_mb",
        when=lambda o: o.table_tier_hbm_mb > 0,
        flag="ps_sparse_pull",
        value=False,
        guard=lambda o: o.ps_sparse_pull,
        doc=(
            "the HBM cache subsumes the dirty-row client cache (and a "
            "second full-table host mirror would double host RAM)"
        ),
    ),
)

REQUIREMENTS: Tuple[Requirement, ...] = (
    Requirement(
        name="device_pipeline_xor_use_ps",
        flags=("device_pipeline", "use_ps"),
        predicate=lambda o, e: not (o.device_pipeline and o.use_ps),
        message=lambda o, e: (
            "-device_pipeline and -use_ps are mutually exclusive "
            "(fused HBM tables vs parameter-server tables)"
        ),
        doc="mutually exclusive (fused HBM tables vs PS tables)",
    ),
    Requirement(
        name="row_mean_exact_needs_device_pipeline",
        flags=("scale_mode", "device_pipeline"),
        predicate=lambda o, e: (
            o.scale_mode != "row_mean_exact" or o.device_pipeline
        ),
        message=lambda o, e: (
            "-scale_mode=row_mean_exact exists only for -device_pipeline "
            "(the host presort path computes realized counts already — "
            "use row_mean there)"
        ),
        doc=(
            "`row_mean_exact` exists only on the device pipeline; the "
            "host presort path computes realized counts already"
        ),
    ),
    Requirement(
        name="walk_domain",
        flags=("walk",),
        predicate=lambda o, e: o.walk in ("perm", "iid"),
        message=lambda o, e: (
            "-walk must be 'perm' or 'iid', got '%s'" % o.walk
        ),
        doc="must be `perm` or `iid`",
    ),
    Requirement(
        name="ps_pipeline_depth_nonnegative",
        flags=("ps_pipeline_depth",),
        predicate=lambda o, e: o.ps_pipeline_depth >= 0,
        message=lambda o, e: (
            "-ps_pipeline_depth must be >= 0, got %d" % o.ps_pipeline_depth
        ),
        doc="must be >= 0",
    ),
    Requirement(
        name="ps_pipeline_depth_max_positive",
        flags=("ps_pipeline_depth_max",),
        predicate=lambda o, e: o.ps_pipeline_depth_max >= 1,
        message=lambda o, e: (
            "-ps_pipeline_depth_max must be >= 1, got %d"
            % o.ps_pipeline_depth_max
        ),
        doc="must be >= 1 (the auto controller's widest staleness bound)",
    ),
    Requirement(
        name="ps_depth_decide_rounds_positive",
        flags=("ps_depth_decide_rounds",),
        predicate=lambda o, e: o.ps_depth_decide_rounds >= 1,
        message=lambda o, e: (
            "-ps_depth_decide_rounds must be >= 1, got %d"
            % o.ps_depth_decide_rounds
        ),
        doc="must be >= 1 (controller decision cadence in PS rounds)",
    ),
    Requirement(
        name="ps_depth_auto_within_max",
        flags=("ps_pipeline_depth", "ps_pipeline_depth_max"),
        predicate=lambda o, e: (
            not getattr(o, "ps_depth_auto", False)
            or 1 <= o.ps_pipeline_depth <= o.ps_pipeline_depth_max
        ),
        message=lambda o, e: (
            "-ps_pipeline_depth=auto starts at depth %d, outside "
            "[1, -ps_pipeline_depth_max=%d] — raise the max or set an "
            "explicit depth" % (o.ps_pipeline_depth, o.ps_pipeline_depth_max)
        ),
        doc=(
            "`auto` keeps the effective depth within "
            "[1, `-ps_pipeline_depth_max`]; the starting depth must "
            "already lie in that range"
        ),
    ),
    Requirement(
        name="ps_compress_domain",
        flags=("ps_compress",),
        predicate=lambda o, e: o.ps_compress in ("none", "sparse", "1bit"),
        message=lambda o, e: (
            "-ps_compress must be none|sparse|1bit, got '%s'"
            % o.ps_compress
        ),
        doc="must be `none`, `sparse`, or `1bit`",
    ),
    Requirement(
        name="ps_compress_needs_pipelined_depth",
        flags=("ps_compress", "ps_pipeline_depth"),
        predicate=lambda o, e: (
            o.ps_compress == "none" or o.ps_pipeline_depth >= 1
        ),
        message=lambda o, e: (
            "-ps_compress applies to the pipelined PS path only: set "
            "-ps_pipeline_depth >= 1 (the depth-0 sync rounds stay the "
            "pinned bit-exact parity mode)"
        ),
        doc=(
            "compression applies to the pipelined PS path only "
            "(depth >= 1); depth-0 sync rounds stay the pinned "
            "bit-exact parity mode"
        ),
    ),
    Requirement(
        name="table_tier_nonnegative",
        flags=("table_tier_hbm_mb",),
        predicate=lambda o, e: o.table_tier_hbm_mb >= 0,
        message=lambda o, e: (
            "-table_tier_hbm_mb must be >= 0, got %s"
            % o.table_tier_hbm_mb
        ),
        doc="must be >= 0",
    ),
    Requirement(
        name="table_tier_single_process",
        flags=("table_tier_hbm_mb",),
        predicate=lambda o, e: (
            o.table_tier_hbm_mb == 0 or e.process_count == 1
        ),
        message=lambda o, e: (
            "-table_tier_hbm_mb requires a single process: the host "
            "tier is process-local RAM (multi-process scale-out shards "
            "rows across ranks instead — drop the flag or the extra "
            "ranks)"
        ),
        doc=(
            "requires a single process: the host tier is process-local "
            "RAM (multi-process scale-out shards rows across ranks "
            "instead)"
        ),
    ),
    Requirement(
        name="device_ckpt_single_process",
        flags=("checkpoint_dir", "device_pipeline"),
        predicate=lambda o, e: (
            not (o.checkpoint_dir and o.device_pipeline)
            or e.process_count == 1
        ),
        message=lambda o, e: (
            "-checkpoint_dir on the device pipeline requires a "
            "single process (multi-process training goes through "
            "-use_ps, whose checkpoints are quorum-committed)"
        ),
        doc=(
            "device-pipeline checkpoints require a single process "
            "(multi-process training goes through `-use_ps`, whose "
            "checkpoints are quorum-committed)"
        ),
    ),
    Requirement(
        name="device_ckpt_steps_only",
        flags=("checkpoint_dir", "device_pipeline",
               "checkpoint_every_seconds"),
        predicate=lambda o, e: (
            not (o.checkpoint_dir and o.device_pipeline)
            or o.checkpoint_every_seconds == 0
        ),
        message=lambda o, e: (
            "-checkpoint_every_seconds is wall-clock driven and "
            "would perturb the device pipeline's deterministic "
            "resume; use -checkpoint_every_steps (dispatch calls)"
        ),
        doc=(
            "wall-clock checkpoints would perturb the device "
            "pipeline's deterministic resume; use "
            "`-checkpoint_every_steps`"
        ),
    ),
)


# ---------------------------------------------------------------------------
# Runtime API
# ---------------------------------------------------------------------------

def apply_implications(options: Any, log: Optional[Callable[[str], None]] = None
                       ) -> Tuple[str, ...]:
    """Rewrite ``options`` in place per ``IMPLICATIONS``; returns the
    names of the implications that fired.  ``log`` (e.g. ``Log.Info``)
    receives each fired implication's message, when it has one."""
    fired = []
    for imp in IMPLICATIONS:
        if not imp.when(options):
            continue
        if imp.guard is not None and not imp.guard(options):
            continue
        if imp.log and log is not None:
            log(imp.log)
        setattr(options, imp.flag, imp.value)
        fired.append(imp.name)
    return tuple(fired)


def check_options(options: Any, env: Optional[Env] = None,
                  check: Optional[Callable[[bool, str], None]] = None) -> None:
    """Enforce every ``Requirement``.  ``check`` defaults to raising
    ``ValueError``; the app passes ``utils.log.CHECK`` so violations die
    the same way the old inline block did."""
    env = env if env is not None else Env()
    if check is None:
        def check(cond: bool, msg: str) -> None:
            if not cond:
                raise ValueError(msg)
    for req in REQUIREMENTS:
        ok = bool(req.predicate(options, env))
        check(ok, req.message(options, env) if not ok else req.name)


def implied_flags() -> Tuple[str, ...]:
    """Flags some implication writes — the set R12 claims write
    ownership of: an options-object assignment to one of these outside
    this module is drift."""
    return tuple(sorted({imp.flag for imp in IMPLICATIONS}))


def constrained_flags() -> Tuple[str, ...]:
    """Every flag the model mentions (triggers, targets, requirement
    members) — must all exist in the MV flag registry."""
    names = set()
    for imp in IMPLICATIONS:
        names.add(imp.trigger)
        names.add(imp.flag)
    for req in REQUIREMENTS:
        names.update(req.flags)
    return tuple(sorted(names))


def requirement_flag_pairs() -> Tuple[Tuple[str, ...], ...]:
    """The multi-flag couplings requirements own, as sorted tuples.  A
    hand-written CHECK over one of these exact flag sets outside this
    module re-implements the model and is R12 drift."""
    return tuple(sorted(
        {tuple(sorted(req.flags)) for req in REQUIREMENTS if len(req.flags) > 1}
    ))


# ---------------------------------------------------------------------------
# Documentation rendering (DEPLOY.md "Flag constraints" block)
# ---------------------------------------------------------------------------

MARKER_BEGIN = "<!-- mvlint:flag-constraints:begin -->"
MARKER_END = "<!-- mvlint:flag-constraints:end -->"


def render_markdown() -> str:
    """The generated DEPLOY.md block, markers included.  R12 compares
    the checked-in block against this text byte-for-byte; regenerate
    with ``python -m multiverso_tpu.analysis --constraint-table``."""
    lines = [
        MARKER_BEGIN,
        "Generated from `multiverso_tpu/config/constraints.py` by",
        "`python -m multiverso_tpu.analysis --constraint-table` — edit",
        "the model, not this block (mvlint R12 flags drift).",
        "",
        "**Implications** (applied in order before validation):",
        "",
        "| when | forces | why |",
        "|---|---|---|",
    ]
    for imp in IMPLICATIONS:
        val = repr(imp.value) if not isinstance(imp.value, bool) else str(imp.value)
        lines.append(
            f"| `-{imp.trigger}` active | `-{imp.flag}` = `{val}` | {imp.doc} |"
        )
    lines += [
        "",
        "**Requirements** (violations fail startup with `CHECK`):",
        "",
        "| flags | rule |",
        "|---|---|",
    ]
    for req in REQUIREMENTS:
        flags = " + ".join(f"`-{f}`" for f in req.flags)
        lines.append(f"| {flags} | {req.doc} |")
    lines.append(MARKER_END)
    return "\n".join(lines)
