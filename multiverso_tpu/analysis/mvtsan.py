"""mvtsan — hybrid lockset + vector-clock race detector for the
repo's own threaded runtime (the dynamic complement of mvlint R9).

Armed via ``-debug_race_detector`` / ``MV_RACE_DETECTOR=1`` (same
env-derived-default pattern as the PR 8 guards: ``ResetFlagsToDefault``
cannot disarm a suite that exported the env var). Disarmed, the entire
subsystem costs the callers one module-bool read per hook — no
descriptors are installed and no threading primitive is patched.

Armed, three things happen:

* **Instrumentation plan** — mvlint's ProjectGraph proves which
  (class, attr) pairs are reachable from more than one thread entry
  (:mod:`multiverso_tpu.analysis.instrument`); only those attributes
  get a data descriptor feeding the detector. Bounded overhead by
  construction, not blanket ``__setattr__`` wrapping.

* **Sync edges** — happens-before comes from the primitives the repo
  already owns: ``OrderedLock`` acquire/release, ``TaskPipe``
  submit→run and run→wait_result, ``ASyncBuffer`` fill→get,
  ``Waiter`` notify→wait, ``MtQueue`` push→pop (native path included),
  ``threading.Thread`` start/join, plus ``threading.Lock``/``RLock``/
  ``Event`` created after arming (the factories are patched so plain
  stdlib locks used by the runtime still order the clocks).
  Mutex hand-offs transfer the releaser's clock exactly (FastTrack
  style); queues/events/latches *merge* — an over-approximation that
  can only hide races, never invent them.

* **Verdicts** — a pair of unordered accesses races only under the
  same rules R9 applies statically, so static and dynamic findings
  agree on the same field: unordered write/write with no common lock
  races; a read-modify-write racing any access with no common lock
  races; a plain store racing a plain load is *publication* (exempt,
  GIL-atomic); writes serialized under one common lock make lock-free
  pure reads exempt (*writer-serialized publication*); and
  ``@collective_dispatch`` entries hold the same virtual lock R9
  credits them with.

Races surface as structured :class:`RaceReport` objects: both access
stacks, both thread names, both locksets, and the vector-clock
witness. They land in the obs flight recorder, in
``race-report-rank<p>.json`` (``MV_RACE_DIR``), and — through
``python -m multiverso_tpu.analysis --race-report`` — in mvlint's
Finding/baseline/pragma/SARIF machinery under rule id **D1**, where a
dynamic race and the static R9 verdict on the same field
cross-reference each other.

Schedule fuzz: ``MV_SCHED_FUZZ=<seed>`` shrinks
``sys.setswitchinterval`` and injects seeded sleeps at sync points so
the ci ``race`` stage explores more interleavings. The seed makes the
*jitter* reproducible, not the OS scheduler — a fuzzed run that found
a race is evidence, a fuzzed run that found none is not a proof.
"""

from __future__ import annotations

import atexit
import json
import os
import random
import sys
import threading
import time
import zlib
from contextlib import contextmanager
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

from multiverso_tpu.utils.configure import (
    GetFlag,
    MV_DEFINE_bool,
    mutation_count,
)

__all__ = [
    "RaceReport",
    "race_detector_enabled",
    "arm",
    "disarm",
    "maybe_arm_from_flags",
    "maybe_dump_from_flags",
    "is_armed",
    "publish",
    "join",
    "SyncClock",
    "sync_release",
    "sync_acquire",
    "virtual_lock",
    "lock_acquired",
    "lock_released",
    "reports",
    "reset",
    "stats",
    "dump_reports",
    "findings_from_reports",
    "InstrumentedAttr",
]

# env-derived default, like -debug_thread_guards: the race ci stage and
# armed test runs export MV_RACE_DETECTOR=1, and the default must
# survive ResetFlagsToDefault()
MV_DEFINE_bool(
    "debug_race_detector",
    os.environ.get("MV_RACE_DETECTOR", "") == "1",
    "arm mvtsan, the lockset + vector-clock dynamic race detector: "
    "instruments the shared attributes mvlint's plan proves "
    "cross-thread and reports unordered conflicting accesses as "
    "RaceReports (see analysis/RULES.md: Dynamic analysis)",
)

_enabled_cache: Optional[bool] = None
_enabled_gen = -1


def race_detector_enabled() -> bool:
    """Cached against the flag registry's mutation counter — the
    disarmed hot path never takes the registry mutex (the
    ``guards_enabled()`` pattern)."""
    global _enabled_cache, _enabled_gen
    gen = mutation_count()
    if _enabled_cache is None or _enabled_gen != gen:
        _enabled_cache = bool(GetFlag("debug_race_detector"))
        _enabled_gen = gen
    return _enabled_cache


# module-level armed bool: every sync hook in utils/native/guards reads
# this ONE attribute and bails — the entire disarmed cost of the hooks
_ACTIVE = False


def is_armed() -> bool:
    return _ACTIVE


# --------------------------------------------------------- thread state

_tls = threading.local()
_tid_mutex = threading.Lock()
_next_tid = 0
MAX_REPORTS = 200


class _ThreadState:
    __slots__ = ("tid", "clock", "locks", "busy", "rng", "name")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.clock: Dict[int, int] = {tid: 1}
        self.locks: List[Tuple[str, int]] = []  # (name, uid) stack
        self.busy = False  # reentrancy guard for detector internals
        self.rng: Optional[random.Random] = None
        self.name = name


def _state() -> _ThreadState:
    st = getattr(_tls, "st", None)
    if st is None:
        global _next_tid
        with _tid_mutex:
            _next_tid += 1
            tid = _next_tid
        # threading.current_thread() is OFF LIMITS here: during thread
        # bootstrap it would fabricate a _DummyThread whose __init__
        # sets a (tracked) Event → sync_release → _state → recursion.
        # _active is registration-only — None during bootstrap, and
        # the run() wrapper fixes the name up right after.
        cur = threading._active.get(threading.get_ident())
        st = _ThreadState(
            tid, cur.name if cur is not None else f"thread-{tid}"
        )
        if _fuzz_seed is not None:
            st.rng = random.Random(_fuzz_seed ^ (tid * 0x9E3779B9))
        _tls.st = st
        # spawner → child edge: Thread.start (patched) stashed the
        # parent's clock on the thread object (the run() wrapper also
        # joins it — this covers states born before run())
        parent = getattr(cur, "_mv_hb_parent", None) \
            if cur is not None else None
        if parent:
            _join_into(st, parent)
    return st


def _join_into(st: _ThreadState, clock: Dict[int, int]) -> None:
    mine = st.clock
    for t, c in clock.items():
        if mine.get(t, 0) < c:
            mine[t] = c


def publish() -> Optional[Dict[int, int]]:
    """Snapshot the calling thread's clock for a happens-before edge
    and advance its own component (the snapshot names a distinct
    epoch). Returns ``None`` disarmed — ``join(None)`` no-ops, so call
    sites stay one line."""
    if not _ACTIVE:
        return None
    st = _state()
    if st.busy:
        return None
    snap = dict(st.clock)
    st.clock[st.tid] += 1
    _counters["sync_publish"] += 1
    return snap


def join(clock: Optional[Dict[int, int]]) -> None:
    """Acquire side of an edge: element-wise max into the calling
    thread's clock."""
    if not _ACTIVE or not clock:
        return
    st = _state()
    if st.busy:
        return
    _join_into(st, clock)
    _counters["sync_join"] += 1
    _maybe_fuzz(st)


class SyncClock:
    """Per-sync-object clock cell (one per MtQueue / Waiter / tracked
    lock). Lock hand-offs *replace* (exact, FastTrack); queue/latch
    traffic *merges* (sound over-approximation for multi-producer)."""

    __slots__ = ("clock",)

    def __init__(self):
        self.clock: Optional[Dict[int, int]] = None


def sync_release(cell: SyncClock, merge: bool = True) -> None:
    snap = publish()
    if snap is None:
        return
    if merge and cell.clock:
        base = cell.clock
        for t, c in snap.items():
            if base.get(t, 0) < c:
                base[t] = c
    else:
        cell.clock = snap


def sync_acquire(cell: SyncClock) -> None:
    if not _ACTIVE:
        return
    join(cell.clock)


def sync_of(obj: Any, slot: str = "_mv_sync") -> SyncClock:
    """Lazily attach a SyncClock to ``obj`` (GIL-atomic setdefault —
    safe to call from racing hookpoints)."""
    cell = obj.__dict__.get(slot)
    if cell is None:
        cell = obj.__dict__.setdefault(slot, SyncClock())
    return cell


# ------------------------------------------------------------- locksets

_lock_uid_counter = 0


def _next_lock_uid() -> int:
    global _lock_uid_counter
    with _tid_mutex:
        _lock_uid_counter += 1
        return _lock_uid_counter


def lock_acquired(cell: SyncClock, name: str, uid: int) -> None:
    """An owned lock (OrderedLock or a tracked stdlib lock) was
    acquired: join its clock (exact transfer) and push it on the
    calling thread's lockset."""
    if not _ACTIVE:
        return
    st = _state()
    if st.busy:
        return
    if cell.clock:
        _join_into(st, cell.clock)
    st.locks.append((name, uid))
    _counters["lock_edges"] += 1
    _maybe_fuzz(st)


def lock_released(cell: SyncClock, name: str, uid: int) -> None:
    """Release side: publish the clock INTO the lock (call while still
    holding it) and pop the lockset entry."""
    if not _ACTIVE:
        return
    st = _state()
    if st.busy:
        return
    snap = dict(st.clock)
    st.clock[st.tid] += 1
    cell.clock = snap  # exact hand-off: acquirer's join saw history
    locks = st.locks
    for i in range(len(locks) - 1, -1, -1):
        if locks[i][1] == uid:
            del locks[i]
            break


@contextmanager
def virtual_lock(name: str):
    """Treat a code region as serialized by a virtual lock — the
    runtime mirror of R9's ``@collective_dispatch`` credit (the guard
    pins those entries to one thread, so the decorator IS the
    synchronization)."""
    if not _ACTIVE:
        yield
        return
    st = _state()
    key = (name, 0)
    st.locks.append(key)
    try:
        yield
    finally:
        for i in range(len(st.locks) - 1, -1, -1):
            if st.locks[i] == key:
                del st.locks[i]
                break


# -------------------------------------------------------- schedule fuzz

_fuzz_seed: Optional[int] = None
_fuzz_prev_interval: Optional[float] = None


def _install_fuzz() -> None:
    global _fuzz_seed, _fuzz_prev_interval
    spec = os.environ.get("MV_SCHED_FUZZ", "")
    if not spec:
        return
    _fuzz_seed = int(spec) if spec.isdigit() else zlib.crc32(
        spec.encode("utf-8")
    )
    _fuzz_prev_interval = sys.getswitchinterval()
    # tiny switch interval: force the interpreter to preempt between
    # bytecodes far more often, widening the explored interleavings
    sys.setswitchinterval(1e-5)


def _uninstall_fuzz() -> None:
    global _fuzz_seed, _fuzz_prev_interval
    if _fuzz_prev_interval is not None:
        sys.setswitchinterval(_fuzz_prev_interval)
    _fuzz_seed = None
    _fuzz_prev_interval = None


def _maybe_fuzz(st: _ThreadState) -> None:
    rng = st.rng
    if rng is not None and rng.random() < 0.05:
        time.sleep(rng.random() * 5e-4)


# ------------------------------------------------------------ reporting

class RaceReport:
    """One detected race: the two unordered accesses, with thread
    names, short stacks, locksets, and the vector-clock witness."""

    __slots__ = ("cls", "attr", "kind", "path", "line",
                 "a_thread", "a_where", "a_locks",
                 "b_thread", "b_where", "b_locks",
                 "vc_current", "vc_prior", "static")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RaceReport":
        return cls(**{k: d.get(k) for k in cls.__slots__})

    def message(self) -> str:
        a_at = self.a_where[0] if self.a_where else "?"
        b_at = self.b_where[0] if self.b_where else "?"
        return (
            f"{self.cls}.{self.attr}: {self.kind} — "
            f"{self.a_thread!r} ({a_at}) unordered with "
            f"{self.b_thread!r} ({b_at}); locks "
            f"{sorted(self.a_locks or [])} vs "
            f"{sorted(self.b_locks or [])}; "
            f"vc witness {self.vc_prior} ⋠ {self.vc_current}"
            + (f"; static verdict: {self.static}" if self.static else "")
        )


_reports: List[RaceReport] = []
_reported_keys: set = set()
_report_mutex = threading.Lock()
_counters: Dict[str, int] = {
    "accesses": 0, "sync_publish": 0, "sync_join": 0,
    "lock_edges": 0, "races": 0,
}
_repo_root = ""


def reports() -> List[RaceReport]:
    return list(_reports)


def reset() -> None:
    """Forget reports and counters (test isolation). Armed state and
    instrumentation are untouched."""
    with _report_mutex:
        _reports.clear()
        _reported_keys.clear()
        for k in _counters:
            _counters[k] = 0


def _where(skip: int, limit: int = 4) -> List[str]:
    out: List[str] = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return out
    while f is not None and len(out) < limit:
        fname = f.f_code.co_filename
        if _repo_root and fname.startswith(_repo_root):
            fname = fname[len(_repo_root):].lstrip(os.sep)
        out.append(
            f"{fname}:{f.f_lineno} in {f.f_code.co_name}"
        )
        f = f.f_back
    return out


def _emit(report: RaceReport) -> None:
    key = (report.cls, report.attr, report.kind)
    with _report_mutex:
        if key in _reported_keys or len(_reports) >= MAX_REPORTS:
            return
        _reported_keys.add(key)
        _reports.append(report)
        _counters["races"] += 1
    try:
        from multiverso_tpu.obs.flight import recorder

        recorder.record(
            "race_report", cls=report.cls, attr=report.attr,
            kind=report.kind, a_thread=report.a_thread,
            b_thread=report.b_thread, where=report.a_where[:1],
        )
    except Exception:  # noqa: BLE001 — never mask the report
        pass
    print(f"mvtsan: RACE {report.message()}", file=sys.stderr)


# ------------------------------------------------- instrumented attrs

_NO_DEFAULT = object()


class _Shadow:
    """Per-(instance, attr) race metadata, stored in the instance
    ``__dict__`` under a non-identifier key so lifetime and GC are the
    object's own. Field updates are GIL-atomic dict/slot ops; a torn
    interleaving can at worst drop one historical access (a missed
    race), never a false positive or a crash."""

    __slots__ = ("w_tid", "w_clk", "w_name", "w_where", "w_locks",
                 "w_rmw", "w_common", "reads")

    def __init__(self):
        self.w_tid: Optional[int] = None
        self.w_clk = 0
        self.w_name = ""
        self.w_where: List[str] = []
        self.w_locks: FrozenSet = frozenset()
        self.w_rmw = False
        # running ∩ of every write's lockset: non-empty == the writes
        # are serialized by one common lock (writer-serialized
        # publication, R9's exemption)
        self.w_common: Optional[FrozenSet] = None
        # tid -> (clk, thread name, where, lockset)
        self.reads: Dict[int, Tuple[int, str, List[str], FrozenSet]] = {}


class InstrumentedAttr:
    """Data descriptor the instrumentation plan installs per shared
    (class, attr). Values live where they always did — the instance
    ``__dict__`` — so pickling, ``vars()`` and reprs stay sane; the
    descriptor only observes."""

    __slots__ = ("cls_name", "attr", "relpath", "entry", "default",
                 "shadow_key")

    def __init__(self, cls_name: str, attr: str, relpath: str,
                 entry=None, default=_NO_DEFAULT):
        self.cls_name = cls_name
        self.attr = attr
        self.relpath = relpath
        self.entry = entry  # instrument.PlanEntry (static verdict)
        self.default = default
        self.shadow_key = "\x00mv:" + attr

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if _ACTIVE:
            _on_access(self, obj, False)
        try:
            return obj.__dict__[self.attr]
        except KeyError:
            if self.default is not _NO_DEFAULT:
                return self.default
            raise AttributeError(
                f"{type(obj).__name__!r} object has no attribute "
                f"{self.attr!r}"
            ) from None

    def __set__(self, obj, value):
        if _ACTIVE:
            _on_access(self, obj, True)
        obj.__dict__[self.attr] = value

    def __delete__(self, obj):
        if _ACTIVE:
            _on_access(self, obj, True)
        try:
            del obj.__dict__[self.attr]
        except KeyError:
            raise AttributeError(self.attr) from None


def _static_note(desc: InstrumentedAttr) -> str:
    e = desc.entry
    if e is None:
        return ""
    if e.classification == "race":
        return (f"race (mvlint R9 finding at {e.relpath}:{e.line} — "
                "dynamic confirmation of the static report)")
    return (f"{e.classification} at {e.relpath}:{e.line} "
            "(statically exempt — dynamic schedule contradicts the "
            "static model; check for an untracked sync path)")


def _on_access(desc: InstrumentedAttr, obj, is_write: bool) -> None:
    st = _state()
    if st.busy:
        return
    st.busy = True
    try:
        _counters["accesses"] += 1
        d = obj.__dict__
        sh = d.get(desc.shadow_key)
        if sh is None:
            sh = d.setdefault(desc.shadow_key, _Shadow())
        locks = frozenset(st.locks)
        my_clk = st.clock[st.tid]
        _maybe_fuzz(st)
        if not is_write:
            # read racing a prior RMW write? plain store vs plain load
            # is publication (GIL-atomic) — exempt, like R9
            w_tid = sh.w_tid
            if (w_tid is not None and w_tid != st.tid
                    and st.clock.get(w_tid, 0) < sh.w_clk
                    and sh.w_rmw
                    and not (locks & sh.w_locks)
                    and not sh.w_common):
                _emit(RaceReport(
                    cls=desc.cls_name, attr=desc.attr,
                    kind="read racing a read-modify-write",
                    path=desc.relpath, line=_line_of(desc),
                    a_thread=st.name, a_where=_where(3),
                    a_locks=_lock_names(locks),
                    b_thread=sh.w_name, b_where=list(sh.w_where),
                    b_locks=_lock_names(sh.w_locks),
                    vc_current=dict(st.clock),
                    vc_prior=f"{w_tid}@{sh.w_clk}",
                    static=_static_note(desc),
                ))
            sh.reads[st.tid] = (my_clk, st.name, _where(3), locks)
            return
        # ---- write path
        rmw = st.tid in sh.reads  # this thread read since last write
        # single-owner phase: the attribute has only ever been touched
        # by this thread (constructor / pre-publication setup). Such
        # writes are program-ordered, so they don't constrain the
        # writers' common-lock intersection — the dynamic mirror of R9
        # excluding __init__ accesses from the static buckets.
        single_owner = (
            (sh.w_tid is None or sh.w_tid == st.tid)
            and all(t == st.tid for t in sh.reads)
        )
        if single_owner:
            w_common = sh.w_common
        else:
            w_common = locks if sh.w_common is None else \
                (sh.w_common & locks)
        w_tid = sh.w_tid
        if (w_tid is not None and w_tid != st.tid
                and st.clock.get(w_tid, 0) < sh.w_clk
                and not (locks & sh.w_locks)):
            _emit(RaceReport(
                cls=desc.cls_name, attr=desc.attr,
                kind="unordered write-write",
                path=desc.relpath, line=_line_of(desc),
                a_thread=st.name, a_where=_where(3),
                a_locks=_lock_names(locks),
                b_thread=sh.w_name, b_where=list(sh.w_where),
                b_locks=_lock_names(sh.w_locks),
                vc_current=dict(st.clock),
                vc_prior=f"{w_tid}@{sh.w_clk}",
                static=_static_note(desc),
            ))
        if rmw and not w_common:
            for r_tid, (r_clk, r_name, r_where, r_locks) in \
                    list(sh.reads.items()):
                if r_tid == st.tid:
                    continue
                if st.clock.get(r_tid, 0) >= r_clk:
                    continue  # ordered before this write
                if locks & r_locks:
                    continue  # common lock covers the pair
                _emit(RaceReport(
                    cls=desc.cls_name, attr=desc.attr,
                    kind="read-modify-write racing a read",
                    path=desc.relpath, line=_line_of(desc),
                    a_thread=st.name, a_where=_where(3),
                    a_locks=_lock_names(locks),
                    b_thread=r_name, b_where=list(r_where),
                    b_locks=_lock_names(r_locks),
                    vc_current=dict(st.clock),
                    vc_prior=f"{r_tid}@{r_clk}",
                    static=_static_note(desc),
                ))
                break
        sh.w_tid = st.tid
        sh.w_clk = my_clk
        sh.w_name = st.name
        sh.w_where = _where(3)
        sh.w_locks = locks
        sh.w_rmw = rmw
        sh.w_common = w_common
        sh.reads = {}
    finally:
        st.busy = False


def _lock_names(locks: FrozenSet) -> List[str]:
    return sorted(
        name if uid == 0 else f"{name}#{uid}" for name, uid in locks
    )


def _line_of(desc: InstrumentedAttr) -> int:
    return desc.entry.line if desc.entry is not None else 0


# -------------------------------------------------- threading patches

_patches: List[Tuple[Any, str, Any]] = []


def _patch(obj: Any, name: str, new: Any) -> None:
    _patches.append((obj, name, getattr(obj, name)))
    setattr(obj, name, new)


def _unpatch_all() -> None:
    while _patches:
        obj, name, orig = _patches.pop()
        try:
            setattr(obj, name, orig)
        except (AttributeError, TypeError):
            pass


class _TrackedLock:
    """``threading.Lock()`` replacement handed out while armed: exact
    clock hand-off on release→acquire plus lockset membership. The
    factories are patched at ``arm()`` — locks created before arming
    stay plain (arm early: the race drills arm before building any app
    object)."""

    _mv_kind = "Lock"

    def __init__(self):
        self._inner = _ORIG["lock"]()
        self._mv_sync = SyncClock()
        self._mv_name = self._mv_kind
        self._mv_uid = _next_lock_uid()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            lock_acquired(self._mv_sync, self._mv_name, self._mv_uid)
        return ok

    acquire_lock = acquire

    def release(self):
        lock_released(self._mv_sync, self._mv_name, self._mv_uid)
        self._inner.release()

    release_lock = release

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def _at_fork_reinit(self):
        self._inner._at_fork_reinit()

    def __repr__(self):
        return f"<mvtsan tracked {self._mv_kind} of {self._inner!r}>"


class _TrackedRLock(_TrackedLock):
    _mv_kind = "RLock"

    def __init__(self):
        self._inner = _ORIG["rlock"]()
        self._mv_sync = SyncClock()
        self._mv_name = self._mv_kind
        self._mv_uid = _next_lock_uid()

    # Condition protocol: wait() parks via _release_save and returns
    # via _acquire_restore — the clock must ride both, or a waiter
    # would appear to hold history it released
    def _release_save(self):
        lock_released(self._mv_sync, self._mv_name, self._mv_uid)
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        lock_acquired(self._mv_sync, self._mv_name, self._mv_uid)

    def _is_owned(self):
        return self._inner._is_owned()


_ORIG: Dict[str, Any] = {}


def _patch_threading() -> None:
    _ORIG["lock"] = threading.Lock
    _ORIG["rlock"] = threading.RLock
    orig_event = threading.Event
    orig_start = threading.Thread.start
    orig_join = threading.Thread.join

    class _TrackedEvent(orig_event):
        """set()→wait() publication edge (merge: multiple setters)."""

        def __init__(self):
            super().__init__()
            self._mv_sync = SyncClock()

        def set(self):
            sync_release(self._mv_sync, merge=True)
            super().set()

        def wait(self, timeout: Optional[float] = None):
            got = super().wait(timeout)
            if got:
                sync_acquire(self._mv_sync)
            return got

    def _tracked_start(self):
        if _ACTIVE:
            # spawner → child: the child's first detector touch joins
            # this snapshot (_state); wrap run() so joiners can join
            # the child's FINAL clock
            self._mv_hb_parent = publish()
            orig_run = self.run

            def _mv_run():
                # the child's state may have been born mid-bootstrap
                # (before _active registration) with a placeholder
                # name and no parent edge — fix both here
                st = _state()
                st.name = self.name
                join(self._mv_hb_parent)
                try:
                    orig_run()
                finally:
                    self._mv_hb_final = publish()

            self.run = _mv_run
        return orig_start(self)

    def _tracked_join(self, timeout: Optional[float] = None):
        orig_join(self, timeout)
        if _ACTIVE and not self.is_alive():
            join(getattr(self, "_mv_hb_final", None))

    _patch(threading, "Lock", lambda: _TrackedLock())
    _patch(threading, "RLock", lambda: _TrackedRLock())
    _patch(threading, "Event", _TrackedEvent)
    _patch(threading.Thread, "start", _tracked_start)
    _patch(threading.Thread, "join", _tracked_join)


# ---------------------------------------------------------- arm / dump

_atexit_registered = False


def arm(plan: Any = "auto",
        paths: Optional[List[str]] = None) -> int:
    """Arm the detector: build/load the instrumentation plan, install
    the attribute descriptors, patch the threading factories, start
    the fuzz hook if requested. Idempotent. ``plan=None`` arms the
    engine without static instrumentation (fixture tests instrument
    their own classes via ``instrument.instrument_class``). Returns
    the number of instrumented attributes."""
    global _ACTIVE, _repo_root, _atexit_registered
    from multiverso_tpu.analysis import instrument

    if _ACTIVE:
        return instrument.instrumented_count()
    plan_obj = None
    if plan == "auto":
        plan_path = os.environ.get("MV_RACE_PLAN", "")
        if plan_path and os.path.exists(plan_path):
            plan_obj = instrument.load_plan(plan_path)
        else:
            plan_obj = instrument.build_plan(paths)
    elif plan is not None:
        plan_obj = plan
    installed = 0
    if plan_obj is not None:
        _repo_root = plan_obj.root or _repo_root
        installed, _skipped = instrument.apply_plan(plan_obj)
    if not _repo_root:
        _repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
    _patch_threading()
    _install_fuzz()
    try:
        from multiverso_tpu.utils.dashboard import Dashboard

        Dashboard.add_section(
            "race_detector",
            lambda: [f"{k}={v}" for k, v in sorted(stats().items())],
            snapshot=stats,
        )
    except Exception:  # noqa: BLE001 — obs is optional at arm time
        pass
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(_atexit_dump)
    _ACTIVE = True
    return installed


def disarm() -> None:
    """Tear everything down (test isolation): descriptors out,
    threading factories restored, fuzz interval restored. Reports are
    kept until ``reset()``."""
    global _ACTIVE
    from multiverso_tpu.analysis import instrument

    _ACTIVE = False
    instrument.remove_all()
    _unpatch_all()
    _uninstall_fuzz()
    try:
        from multiverso_tpu.utils.dashboard import Dashboard

        Dashboard.remove_section("race_detector")
    except Exception:  # noqa: BLE001
        pass


def maybe_arm_from_flags() -> bool:
    """Runtime.start / conftest hook: arm iff the flag (or its env
    default) says so. One cached-bool check when off."""
    if race_detector_enabled() and not _ACTIVE:
        arm()
        return True
    return False


def stats() -> Dict[str, Any]:
    from multiverso_tpu.analysis import instrument

    out: Dict[str, Any] = dict(_counters)
    out["armed"] = _ACTIVE
    out["instrumented_attrs"] = instrument.instrumented_count()
    out["reports"] = len(_reports)
    if _fuzz_seed is not None:
        out["fuzz_seed"] = _fuzz_seed
    return out


def dump_reports(directory: str, rank: int = 0) -> str:
    """Write ``race-report-rank<p>.json`` — the artifact the ci race
    stage gates on and ``--race-report`` re-reads through the
    baseline/pragma machinery."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"race-report-rank{rank}.json")
    payload = {
        "schema": 1,
        "stats": stats(),
        "reports": [r.to_dict() for r in _reports],
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    print(
        f"mvtsan: race report ({len(_reports)} finding(s)) -> {path}",
        file=sys.stderr,
    )
    return path


def _guess_rank() -> int:
    # sys.modules, not an import: the package __init__ re-exports the
    # runtime() FUNCTION under the submodule's name (so `from
    # multiverso_tpu import runtime` yields the function), and a dump
    # from a process that never started the runtime must not drag the
    # whole jax stack in just to learn it has no rank
    try:
        rt_mod = sys.modules.get("multiverso_tpu.runtime")
        if rt_mod is not None:
            rt = rt_mod.runtime()
            if rt.started:
                return rt.rank
    except Exception:  # noqa: BLE001
        pass
    for var in ("MV_RANK", "RANK"):
        v = os.environ.get(var, "")
        if v.isdigit():
            return int(v)
    return 0


def maybe_dump_from_flags(directory: Optional[str] = None,
                          rank: Optional[int] = None) -> Optional[str]:
    """End-of-train / containment hook (the ``tracer`` dump pattern):
    when armed and ``MV_RACE_DIR`` (or ``directory``) names a target,
    write the rank's report file — empty reports included, so the ci
    gate can distinguish "clean run" from "never armed"."""
    if not _ACTIVE:
        return None
    directory = directory or os.environ.get("MV_RACE_DIR", "")
    if not directory:
        return None
    return dump_reports(directory, _guess_rank() if rank is None
                        else rank)


def _atexit_dump() -> None:
    if not _ACTIVE:
        return
    try:
        maybe_dump_from_flags()
    except Exception:  # noqa: BLE001
        pass
    if _reports:
        print(
            f"mvtsan: {len(_reports)} race report(s) at exit — "
            "see race-report-rank*.json (MV_RACE_DIR) or the flight "
            "recorder; triage: DEPLOY.md 'Race detector'",
            file=sys.stderr,
        )


# -------------------------------------------------- Finding conversion

def findings_from_reports(report_dicts: List[Dict[str, Any]]) -> List:
    """RaceReports → mvlint Findings under rule id **D1**, so the
    baseline/pragma/SARIF machinery (and the empty-baseline contract)
    applies to dynamic findings exactly as to static ones."""
    from multiverso_tpu.analysis.mvlint import Finding

    out = []
    for d in report_dicts:
        r = RaceReport.from_dict(d)
        out.append(Finding(
            "D1", r.path or "<unknown>", int(r.line or 0),
            r.message(),
            "order the accesses through an owned sync primitive "
            "(OrderedLock / TaskPipe / ASyncBuffer / Waiter) or prove "
            "publication; fix the code, do not suppress "
            "(analysis/RULES.md: Dynamic analysis)",
        ))
    return out
