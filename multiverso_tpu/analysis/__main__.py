"""CLI: ``python -m multiverso_tpu.analysis [paths...]``.

Exit codes: 0 = clean (no unsuppressed findings), 1 = findings, 2 = bad
invocation/baseline. ``--json`` emits the machine-readable summary the
bench leg records; ``--sarif OUT.json`` additionally writes a SARIF
2.1.0 log for CI annotation surfaces; ``--diff REF`` lints the whole
tree but reports only findings in files changed since the git ref (the
pre-push fast path — it also engages the on-disk parse cache, so only
changed files are re-parsed); ``--flag-table`` regenerates the DEPLOY.md
flag reference from the AST (no imports executed) and
``--constraint-table`` renders the flag-constraint block from
``config/constraints.py`` (the single source of truth R12 checks
against); ``--shared-state-report`` renders the mvtsan instrumentation
plan as a table — every (class, attr, guarding locks, reaching
threads) the ProjectGraph proves shared; ``--race-report FILE...``
re-reads ``race-report-rank*.json`` dumps from an armed run through
the same baseline/pragma/SARIF machinery as static findings (rule
**D1**) — the ci ``race`` stage's gate.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import subprocess
import sys

from multiverso_tpu.analysis import mvlint


def _changed_paths(ref: str, root: str) -> list:
    """Repo-relative ``.py`` paths changed vs ``ref`` (committed diff +
    working-tree edits + untracked files) — what ``--diff`` restricts
    finding emission to. The PARSE still covers the full tree: a changed
    callee can create a finding in an unchanged caller, and rules R6-R9
    resolve calls across files."""
    out = set()
    cmds = [
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ]
    for cmd in cmds:
        proc = subprocess.run(
            cmd, cwd=root, capture_output=True, text=True, check=True
        )
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                out.add(line.replace(os.sep, "/"))
    return sorted(out)


def _flag_table(paths) -> str:
    """Markdown table of every ``MV_DEFINE_*`` flag (AST scan)."""
    rows = []
    for fp in mvlint._iter_py_files(paths):
        with open(fp, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=fp)
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else getattr(
                fn, "attr", ""
            )
            if not name.startswith("MV_DEFINE_"):
                continue
            a0 = node.args[0]
            if not (isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)):
                continue
            typ = name.replace("MV_DEFINE_", "")
            default = ""
            if len(node.args) > 1:
                try:
                    default = ast.unparse(node.args[1])
                except Exception:  # noqa: BLE001
                    default = "?"
            help_ = ""
            if len(node.args) > 2 and isinstance(node.args[2], ast.Constant):
                help_ = str(node.args[2].value)
            for kw in node.keywords:
                if kw.arg == "help" and isinstance(kw.value, ast.Constant):
                    help_ = str(kw.value.value)
                if kw.arg == "default":
                    try:
                        default = ast.unparse(kw.value)
                    except Exception:  # noqa: BLE001
                        default = "?"
            help_ = " ".join(help_.split())
            if len(help_) > 160:
                help_ = help_[:157] + "..."
            rows.append((a0.value, typ, default, help_))
    rows.sort()
    out = ["| flag | type | default | meaning |",
           "|---|---|---|---|"]
    for name, typ, default, help_ in rows:
        out.append(
            f"| `-{name}` | {typ} | `{default}` | "
            f"{help_.replace('|', '/')} |"
        )
    return "\n".join(out)


def _rule_metadata() -> list:
    """SARIF ``tool.driver.rules`` — id + one-line description pulled
    from each rule function's docstring (no second source of truth)."""
    from multiverso_tpu.analysis import rules as rules_mod

    seen = {}
    for rule_fn in rules_mod.ALL_RULES:
        m = mvlint._RULE_ID_RE.search(rule_fn.__name__)
        rid = f"R{m.group(1)}" if m else rule_fn.__name__
        doc = (rule_fn.__doc__ or "").strip().splitlines()
        seen.setdefault(rid, doc[0] if doc else rid)
    # D1 is the dynamic detector's rule id (RaceReport → Finding via
    # mvtsan.findings_from_reports) — same SARIF log, different engine
    seen.setdefault(
        "D1",
        "mvtsan dynamic race: two unordered accesses to shared state "
        "with no common lock (analysis/RULES.md: Dynamic analysis)",
    )
    return [
        {"id": rid, "shortDescription": {"text": text}}
        for rid, text in sorted(seen.items())
    ]


def _sarif(result) -> dict:
    """Minimal SARIF 2.1.0 log: one run, one result per live finding."""
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "mvlint",
                "informationUri": "analysis/RULES.md",
                "rules": _rule_metadata(),
            }},
            "results": [
                {
                    "ruleId": f.rule,
                    "level": "error",
                    "message": {"text": f.message
                                + (f" (hint: {f.hint})" if f.hint else "")},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {"startLine": max(f.line, 1)},
                        },
                    }],
                }
                for f in result.findings
            ],
        }],
    }


def _race_report_main(args, paths) -> int:
    """``--race-report``: gate on dynamic RaceReports. Loads the rank
    dumps an armed run wrote (``MV_RACE_DIR``), converts each report to
    a rule-D1 Finding, and pushes them through the SAME pragma/baseline
    suppression pass as static findings — so the repo's empty-baseline
    contract covers dynamic races too. Exit 0 only when every dump was
    written by an actually-armed process AND no unsuppressed race
    remains."""
    from multiverso_tpu.analysis import mvtsan

    reports: list = []
    dumps = 0
    for fp in args.race_report:
        try:
            with open(fp, encoding="utf-8") as fh:
                payload = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"mvlint: --race-report {fp}: {e}", file=sys.stderr)
            return 2
        if payload.get("schema") != 1:
            print(
                f"mvlint: --race-report {fp}: schema "
                f"{payload.get('schema')!r} != 1", file=sys.stderr,
            )
            return 2
        if not payload.get("stats", {}).get("armed"):
            # a dump from a disarmed process means the drill never
            # actually ran under the detector — a false green, fail loud
            print(
                f"mvlint: --race-report {fp}: process was not armed "
                "(MV_RACE_DETECTOR did not take) — refusing to gate on "
                "it", file=sys.stderr,
            )
            return 2
        dumps += 1
        reports.extend(payload.get("reports", []))
    findings = mvtsan.findings_from_reports(reports)
    root = mvlint._find_repo_root(paths[0] if paths else ".")
    modules: dict = {}
    for f in findings:
        if f.path in modules:
            continue
        full = os.path.join(root, f.path)
        if not os.path.isfile(full):
            continue
        try:
            with open(full, encoding="utf-8") as fh:
                modules[f.path] = mvlint.Module(full, f.path, fh.read())
        except (SyntaxError, ValueError, OSError):
            continue
    baseline_path = args.baseline or os.path.join(
        os.path.dirname(os.path.abspath(mvlint.__file__)),
        "baseline.toml",
    )
    try:
        baseline = mvlint.load_baseline(baseline_path)
    except ValueError as e:
        print(f"mvlint: {e}", file=sys.stderr)
        return 2
    live, suppressed = mvlint._apply_suppressions(
        findings, modules, baseline
    )
    if args.sarif:
        result = mvlint.LintResult(
            findings=live, suppressed=suppressed, files=len(modules),
            runtime_s=0.0,
        )
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(_sarif(result), fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.json:
        print(json.dumps({
            "dumps": dumps,
            "reports": len(reports),
            "findings": len(live),
            "suppressed": len(suppressed),
        }))
    else:
        for f in live:
            print(f.render())
        if args.verbose:
            for f in suppressed:
                print(f"[suppressed: {f.suppressed_by}] {f.render()}")
        print(
            f"mvtsan: {len(live)} race finding(s) "
            f"({len(suppressed)} suppressed) across {dumps} rank "
            "dump(s)"
        )
    return 1 if live else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m multiverso_tpu.analysis",
        description="mvlint: repo-aware static analysis (see "
                    "analysis/RULES.md)",
    )
    ap.add_argument("paths", nargs="*", default=["multiverso_tpu"],
                    help="files/directories to lint")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: analysis/baseline.toml)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary on stdout")
    ap.add_argument("--diff", metavar="REF", default=None,
                    help="report findings only for files changed vs this "
                         "git ref (full tree still parsed — cross-file "
                         "rules stay sound)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print suppressed findings")
    ap.add_argument("--sarif", metavar="OUT", default=None,
                    help="also write a SARIF 2.1.0 log to this path "
                         "(CI annotation surfaces)")
    ap.add_argument("--flag-table", action="store_true",
                    help="emit the markdown MV_DEFINE flag reference "
                         "and exit")
    ap.add_argument("--constraint-table", action="store_true",
                    help="emit the markdown flag-constraint block from "
                         "config/constraints.py and exit")
    ap.add_argument("--shared-state-report", action="store_true",
                    help="render the mvtsan instrumentation plan: every "
                         "(class, attr, guarding locks, reaching "
                         "threads) the ProjectGraph proves shared")
    ap.add_argument("--race-report", metavar="FILE", nargs="+",
                    default=None,
                    help="gate on race-report-rank*.json dumps from an "
                         "armed run (rule D1 through the baseline/"
                         "pragma machinery); exit 1 on unsuppressed "
                         "races")
    args = ap.parse_args(argv)
    paths = args.paths or ["multiverso_tpu"]
    if args.race_report:
        return _race_report_main(args, paths)
    if args.shared_state_report:
        from multiverso_tpu.analysis import instrument

        plan = instrument.build_plan(paths)
        if args.json:
            print(json.dumps({
                "root": plan.root,
                "entries": [
                    dataclasses.asdict(e) for e in plan.entries
                ],
            }, indent=1, sort_keys=True))
        else:
            print(instrument.render_report(plan))
        return 0
    if args.flag_table:
        print(_flag_table(paths))
        return 0
    if args.constraint_table:
        from multiverso_tpu.config import constraints

        print(constraints.render_markdown())
        return 0
    cfg = mvlint.default_config(paths)
    if args.diff is not None:
        try:
            cfg.restrict_paths = _changed_paths(
                args.diff, cfg.repo_root or "."
            )
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"mvlint: --diff {args.diff}: {e}", file=sys.stderr)
            return 2
        # the pre-push fast path: unchanged files come out of the parse
        # cache (content-hash keyed), only the diff is re-parsed
        cfg.parse_cache_path = os.path.join(
            cfg.repo_root or ".", ".mvlint-cache.pkl"
        )
        if not cfg.restrict_paths:
            if args.json:
                print(json.dumps({
                    "files": 0, "findings": 0, "suppressed": 0,
                    "runtime_s": 0.0, "rules": {},
                }))
            else:
                print(f"mvlint: no .py files changed vs {args.diff}")
            return 0
    try:
        result = mvlint.run_lint(paths, config=cfg,
                                 baseline_path=args.baseline)
    except ValueError as e:  # malformed baseline
        print(f"mvlint: {e}", file=sys.stderr)
        return 2
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(_sarif(result), fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.json:
        per_rule: dict = {}
        for f in result.findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        print(json.dumps({
            "files": result.files,
            "findings": len(result.findings),
            "suppressed": len(result.suppressed),
            "runtime_s": round(result.runtime_s, 3),
            "rules": {r: per_rule[r] for r in sorted(per_rule)},
            "rule_times_s": {
                k: round(v, 4)
                for k, v in sorted(result.rule_times.items())
            },
            "files_cached": result.files_cached,
            "files_reparsed": result.files_reparsed,
        }))
    else:
        print(mvlint.format_findings(result, verbose=args.verbose))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
