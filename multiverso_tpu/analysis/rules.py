"""mvlint rules R1-R5 — the invariant classes PRs 2-7 paid for at runtime.

Each rule is a function ``(modules, config) -> [Finding]``. The rules are
deliberately repo-aware: they know the table entry points, the named
locks, the flag registry idioms (``MV_DEFINE_*`` / ``GetFlag`` /
``WEOptions.from_flags``) and the bit-exactness scopes. Approximations
are documented in ``analysis/RULES.md`` — every one errs toward the
runtime guards in :mod:`multiverso_tpu.analysis.guards` catching what
static analysis cannot.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from multiverso_tpu.analysis.mvlint import Finding, LintConfig, Module

# --------------------------------------------------------------- shared

# table collective entry points that MUST carry @collective_dispatch
# (file suffix -> class -> methods). Subclass overrides that call
# ``super()`` inherit the guard through the decorated base method.
REQUIRED_DISPATCH: Dict[str, Dict[str, Tuple[str, ...]]] = {
    "multiverso_tpu/tables/base.py": {
        "DenseTable": ("get_async", "add", "add_per_worker"),
    },
    "multiverso_tpu/tables/matrix_table.py": {
        "MatrixTable": (
            "get_rows_async", "get_rows_fixed", "add_rows",
            "get_rows_local", "add_rows_local", "add_rows_local_packed",
            "add_rows_per_worker", "round_bucket",
        ),
    },
    "multiverso_tpu/tables/kv_table.py": {
        "KVTable": ("get", "add", "get_local", "add_local"),
    },
    "multiverso_tpu/tables/sparse_matrix_table.py": {
        "SparseMatrixTable": ("get_stale_rows_local",),
    },
}

# modules whose own threads ARE the sanctioned dispatch machinery
THREAD_ENTRY_ALLOW = ("multiverso_tpu/utils/async_buffer.py",)

# R5 scope: bit-exactness contract modules (whole file) ...
EXACT_PATH_PARTS = ("multiverso_tpu/tables/", "multiverso_tpu/io/")
# ... plus the PS round loop inside the app (function-name prefixes)
EXACT_FUNCTION_PREFIXES = {
    "multiverso_tpu/models/wordembedding/app.py": (
        "_ps_", "_wc_", "_train_ps",
    ),
}

_LOCK_ATTR_RE = re.compile(r"lock|mutex|_mu$")


def _name_of_call(func: ast.AST) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _unparse(node: Optional[ast.AST]) -> str:
    if node is None:
        return ""
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display only
        return ""


def _has_dispatch_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _name_of_call(target) == "collective_dispatch" or (
            isinstance(target, ast.Name)
            and target.id == "collective_dispatch"
        ):
            return True
    return False


def _called_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            n = _name_of_call(node.func)
            if n:
                out.add(n)
    return out


# ------------------------------------------------------------------- R1

def rule_r1_collective_dispatch(
    modules: Sequence[Module], cfg: LintConfig, graph=None
) -> List[Finding]:
    """v2: reachability runs on the interprocedural call graph
    (analysis/dataflow.py) — typed receivers resolve ``self._t.get(...)``
    through the ``self._t = KVTable(...)`` binding, which is what retired
    the old AMBIGUOUS_DISPATCH_NAMES exclusion list: generic names like
    ``get``/``add`` now propagate only through a *typed* receiver or a
    repo-unique definition, never by bare name."""
    from multiverso_tpu.analysis import rules_spmd

    findings: List[Finding] = []

    # coverage: the known table entry points must be tagged
    for suffix, classes in REQUIRED_DISPATCH.items():
        for m in modules:
            if not m.relpath.endswith(suffix):
                continue
            for cls, methods in classes.items():
                for meth in methods:
                    fn = m.lookup_method(cls, meth)
                    if fn is not None and not _has_dispatch_decorator(fn):
                        findings.append(Finding(
                            "R1", m.relpath, fn.lineno,
                            f"table collective entry point {cls}.{meth} "
                            "is not tagged @collective_dispatch",
                            "decorate it with analysis.guards."
                            "collective_dispatch so the thread-identity "
                            "guard covers it",
                        ))

    # rogue thread entries: Thread targets / ASyncBuffer fill actions
    # that can reach a tagged entry point through the call graph.
    # TaskPipe submissions are the sanctioned dispatch channel and are
    # exempt here (R9 still treats their closures as thread-side).
    sink_uids = {
        fn.uid for fn in graph.funcs.values()
        if _has_dispatch_decorator(fn.node)
    }
    sink_names = {
        fn.uid: fn.qualname for fn in graph.funcs.values()
        if fn.uid in sink_uids
    }
    for spawner, call, kind, entry in graph.thread_entries():
        if kind == "pipe_submit":
            continue
        m = spawner.module
        if any(m.relpath.endswith(a) for a in THREAD_ENTRY_ALLOW):
            continue
        what = "threading.Thread target" if kind == "thread_target" \
            else "ASyncBuffer fill action"
        hit = _graph_reach_sinks(graph, entry, sink_uids, rules_spmd)
        if hit:
            names = sorted({sink_names[u] for u in hit})
            findings.append(Finding(
                "R1", m.relpath, call.lineno,
                f"{what} {entry.qualname!r} can reach collective "
                f"dispatch {names} off the comms/training thread",
                "route the collective through the PS comms TaskPipe "
                "(pipe.submit) or wrap a documented sync point in "
                "allow_collective_dispatch(reason)",
            ))
    return findings


rule_r1_collective_dispatch.needs_graph = True


def _graph_reach_sinks(graph, entry, sink_uids, rules_spmd) -> Set[int]:
    """Sinks reachable from ``entry`` over the call graph, skipping
    calls lexically inside ``with allow_collective_dispatch(...)``
    blocks (the documented sync-point escape hatch)."""
    hits: Set[int] = set()
    seen: Set[int] = set()
    stack = [entry]
    while stack:
        fn = stack.pop()
        if fn.uid in seen:
            continue
        seen.add(fn.uid)
        if fn.uid in sink_uids:
            hits.add(fn.uid)
            continue  # the decorated entry re-checks at runtime anyway
        allowed = rules_spmd.allow_region_node_ids(graph, fn)
        for call, resolved in graph.calls_in(fn):
            if id(call) in allowed:
                continue
            stack.extend(resolved)
    return hits


# ------------------------------------------------------------------- R2

def _lock_ids_of_with(
    node: ast.With, cls: str, modstem: str
) -> List[str]:
    out = []
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Attribute) and _LOCK_ATTR_RE.search(expr.attr):
            owner = cls or modstem
            out.append(f"{owner}.{expr.attr}")
        elif isinstance(expr, ast.Name) and _LOCK_ATTR_RE.search(expr.id):
            out.append(f"{modstem}.{expr.id}")
    return out


def rule_r2_lock_order(
    modules: Sequence[Module], cfg: LintConfig
) -> List[Finding]:
    # pass 1: per-function transitive may-acquire sets (same-module)
    direct: Dict[int, Set[str]] = {}
    fn_meta: Dict[int, Tuple[Module, str, ast.AST]] = {}
    for m in modules:
        modstem = os.path.splitext(os.path.basename(m.relpath))[0]
        for name, defs in m.functions.items():
            for cls, fn in defs:
                acq: Set[str] = set()
                for node in ast.walk(fn):
                    if isinstance(node, ast.With):
                        acq.update(_lock_ids_of_with(node, cls, modstem))
                    elif (
                        isinstance(node, ast.Call)
                        and _name_of_call(node.func) == "acquire"
                        and isinstance(node.func, ast.Attribute)
                    ):
                        recv = node.func.value
                        if isinstance(recv, ast.Attribute) and \
                                _LOCK_ATTR_RE.search(recv.attr):
                            acq.add(f"{cls or modstem}.{recv.attr}")
                        elif isinstance(recv, ast.Name) and \
                                _LOCK_ATTR_RE.search(recv.id):
                            acq.add(f"{modstem}.{recv.id}")
                direct[id(fn)] = acq
                fn_meta[id(fn)] = (m, name, fn)

    trans: Dict[int, Set[str]] = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for fid, (m, _name, fn) in fn_meta.items():
            for n in _called_names(fn):
                for _cls, callee in m.functions.get(n, ()):
                    extra = trans.get(id(callee), set()) - trans[fid]
                    if extra:
                        trans[fid] |= extra
                        changed = True

    # pass 2: edges = lexical nesting + calls under a held lock
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def visit(node: ast.AST, held: List[str], m: Module, cls: str,
              modstem: str) -> None:
        if isinstance(node, ast.With):
            ids = _lock_ids_of_with(node, cls, modstem)
            for lid in ids:
                for h in held:
                    if h != lid:
                        edges.setdefault((h, lid), (m.relpath, node.lineno))
            new_held = held + ids
            for child in node.body:
                visit(child, new_held, m, cls, modstem)
            return
        if isinstance(node, ast.Call) and held:
            n = _name_of_call(node.func)
            for _c, callee in m.functions.get(n, ()):
                for lid in trans.get(id(callee), ()):
                    for h in held:
                        if h != lid:
                            edges.setdefault(
                                (h, lid), (m.relpath, node.lineno)
                            )
        if isinstance(node, ast.ClassDef):
            cls = node.name
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            held = []  # a def's body runs later, on its own stack
        for child in ast.iter_child_nodes(node):
            visit(child, held, m, cls, modstem)

    for m in modules:
        modstem = os.path.splitext(os.path.basename(m.relpath))[0]
        visit(m.tree, [], m, "", modstem)

    # pass 3: cycles in the acquisition-order graph
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    findings: List[Finding] = []
    reported: Set[frozenset] = set()

    def dfs(start: str) -> Optional[List[str]]:
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    return path + [start]
                if nxt not in path:
                    stack.append((nxt, path + [nxt]))
        return None

    for start in sorted(graph):
        cyc = dfs(start)
        if not cyc:
            continue
        key = frozenset(cyc)
        if key in reported:
            continue
        reported.add(key)
        first = min(
            (edges[(cyc[i], cyc[i + 1])] for i in range(len(cyc) - 1)
             if (cyc[i], cyc[i + 1]) in edges),
            key=lambda s: (s[0], s[1]),
        )
        findings.append(Finding(
            "R2", first[0], first[1],
            "lock-order cycle: " + " -> ".join(cyc),
            "pick ONE global order for these locks and acquire them in "
            "it everywhere (OrderedLock enforces the order at runtime "
            "under -debug_thread_guards)",
        ))
    return findings


# ------------------------------------------------------------------- R3

_DEFINE_FNS = {
    "MV_DEFINE_int", "MV_DEFINE_bool", "MV_DEFINE_string",
    "MV_DEFINE_double",
}
_AUX_READ_RE = re.compile(r"""(?:GetFlag|SetCMDFlag)\(\s*["'](\w+)["']""")


def rule_r3_flag_hygiene(
    modules: Sequence[Module], cfg: LintConfig
) -> List[Finding]:
    defs: Dict[str, Tuple[Module, int]] = {}
    uses: Set[str] = set()
    use_sites: List[Tuple[Module, int, str, bool]] = []
    for m in modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = _name_of_call(node.func)
            if cname in _DEFINE_FNS and node.args and isinstance(
                node.args[0], ast.Constant
            ) and isinstance(node.args[0].value, str):
                defs.setdefault(node.args[0].value, (m, node.lineno))
            elif cname in ("GetFlag", "SetCMDFlag") and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                name = node.args[0].value
                uses.add(name)
                has_default = cname == "GetFlag" and (
                    len(node.args) > 1 or bool(node.keywords)
                )
                use_sites.append((m, node.lineno, name, has_default))
        # the WEOptions.from_flags idiom: dataclass field names ARE flag
        # reads (GetFlag(f.name) in a loop the AST cannot unroll)
        for node in ast.walk(m.tree):
            if isinstance(node, ast.ClassDef) and any(
                isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef))
                and b.name == "from_flags"
                for b in node.body
            ):
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name
                    ):
                        uses.add(stmt.target.id)

    # reads living outside the linted tree (bench/tests/examples drive
    # flags too) — text-level scan of the configured aux roots
    for root in cfg.aux_read_roots:
        files = []
        if os.path.isdir(root):
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files += [
                    os.path.join(dirpath, f) for f in filenames
                    if f.endswith((".py", ".sh"))
                ]
        elif os.path.isfile(root):
            files.append(root)
        for fp in files:
            try:
                with open(fp, encoding="utf-8", errors="replace") as fh:
                    uses.update(_AUX_READ_RE.findall(fh.read()))
            except OSError:
                continue

    findings: List[Finding] = []
    for name, (m, line) in sorted(defs.items()):
        if name not in uses:
            findings.append(Finding(
                "R3", m.relpath, line,
                f"flag {name!r} is defined but never read "
                "(dead flag surface)",
                "wire a GetFlag read (or an explicit accepted-and-"
                "ignored log) or delete the definition",
            ))
    for m, line, name, has_default in use_sites:
        if name not in defs and not has_default:
            findings.append(Finding(
                "R3", m.relpath, line,
                f"flag {name!r} is read but never defined "
                "(GetFlag would raise KeyError)",
                "add the MV_DEFINE_* declaration next to the owning "
                "subsystem",
            ))

    # user-facing flags must be documented
    if cfg.doc_files:
        doc_text = ""
        for doc in cfg.doc_files:
            try:
                with open(doc, encoding="utf-8", errors="replace") as fh:
                    doc_text += fh.read()
            except OSError:
                continue
        for name, (m, line) in sorted(defs.items()):
            if not m.relpath.startswith("multiverso_tpu/"):
                continue
            if not re.search(rf"(^|[^\w-])--?{re.escape(name)}\b",
                             doc_text):
                findings.append(Finding(
                    "R3", m.relpath, line,
                    f"user-facing flag -{name} appears in neither "
                    "README.md nor DEPLOY.md",
                    "add it to the DEPLOY.md flag reference "
                    "(python -m multiverso_tpu.analysis --flag-table "
                    "regenerates the table)",
                ))
    return findings


# ------------------------------------------------------------------- R4

def rule_r4_thread_lifecycle(
    modules: Sequence[Module], cfg: LintConfig
) -> List[Finding]:
    findings: List[Finding] = []

    def scan(node: ast.AST, m: Module, cls_node: Optional[ast.ClassDef],
             fn_node: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            nxt_cls, nxt_fn = cls_node, fn_node
            if isinstance(child, ast.ClassDef):
                nxt_cls = child
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nxt_fn = child
            if isinstance(child, ast.Call) and \
                    _name_of_call(child.func) == "Thread":
                _check_thread(child, m, cls_node, fn_node, node, findings)
            scan(child, m, nxt_cls, nxt_fn)

    for m in modules:
        scan(m.tree, m, None, None)
    return findings


def _check_thread(call: ast.Call, m: Module,
                  cls_node: Optional[ast.ClassDef],
                  fn_node: Optional[ast.AST], parent: ast.AST,
                  findings: List[Finding]) -> None:
    daemon = False
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            daemon = bool(kw.value.value)
    # binding: walk up via source text — find the assignment statement
    # that contains this call (Assign targets), else the thread is
    # unbound (started inline, unjoinable)
    binding = ""
    scope = cls_node or m.tree
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            if any(call is c for c in ast.walk(node.value)):
                binding = _unparse(node.targets[0])
                break
    joined = _binding_joined(binding, scope) if binding else False
    if not daemon and not joined:
        findings.append(Finding(
            "R4", m.relpath, call.lineno,
            "non-daemon thread with no join on its exit paths "
            "(interpreter shutdown can hang on it)",
            "pass daemon=True and register a shutdown join "
            "(stop()/close()), or join it before every return",
        ))
    elif not joined:
        findings.append(Finding(
            "R4", m.relpath, call.lineno,
            f"thread {binding or '<unbound>'} is started but never "
            "joined (the ASyncBuffer/flusher bug class: an exit path "
            "that abandons a live worker)",
            "join it on every exit path, or store it and join in the "
            "owner's stop()/close()",
        ))


def _binding_joined(binding: str, scope: ast.AST) -> bool:
    """Does any ``X.join(...)`` in scope plausibly join this binding?
    One alias fixpoint: ``y = <expr mentioning binding>`` and
    ``for t in <expr mentioning binding-or-alias>`` extend the alias set."""
    token = binding.split(".")[-1]
    aliases = {binding, token}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(scope):
            src = None
            tgt = None
            if isinstance(node, ast.Assign):
                src = _unparse(node.value)
                tgt = _unparse(node.targets[0])
            elif isinstance(node, ast.For):
                src = _unparse(node.iter)
                tgt = _unparse(node.target)
            if src is None or tgt is None or tgt in aliases:
                continue
            if any(re.search(rf"\b{re.escape(a)}\b", src)
                   for a in aliases):
                aliases.add(tgt)
                aliases.add(tgt.split(".")[-1])
                changed = True
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr == "join":
            recv = _unparse(node.func.value)
            if recv in aliases or recv.split(".")[-1] in aliases:
                return True
    return False


# ------------------------------------------------------------------- R5

# Observability call forms whose ARGUMENTS are exempt inside exact-path
# scopes: the tracer/flight-recorder legitimately read clocks there
# (span timestamps, event wall stamps), and those readings annotate the
# timeline only — they never feed trained values, collectives or
# checkpoint payloads (analysis/RULES.md R5 "obs allowlist"). The call
# form must END in span/event/record AND its root name must actually be
# bound by a multiverso_tpu.obs import in the module — a local
# ``def event(...)`` (or a local ``recorder`` object) gets no exemption,
# and aliasing an obs call through another name forfeits it.
_OBS_METHOD_NAMES = {"span", "event", "record"}


def _obs_bound_names(m: Module) -> Set[str]:
    """Names this module binds to the obs package / its members."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "multiverso_tpu.obs" or \
                        a.name.startswith("multiverso_tpu.obs."):
                    out.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "multiverso_tpu":
                for a in node.names:
                    if a.name == "obs":
                        out.add(a.asname or "obs")
            elif node.module == "multiverso_tpu.obs" or \
                    node.module.startswith("multiverso_tpu.obs."):
                for a in node.names:
                    out.add(a.asname or a.name)
    return out


def _obs_allowed_nodes(root: ast.AST, obs_names: Set[str]) -> Set[int]:
    """ids of every node inside an obs span/event/record call (the call
    node itself included) — R5 skips findings anchored on them."""
    allowed: Set[int] = set()
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        text = _unparse(node.func)
        parts = text.split(".")
        if parts[-1] in _OBS_METHOD_NAMES and parts[0] in obs_names:
            for sub in ast.walk(node):
                allowed.add(id(sub))
    return allowed


_WALL_CLOCK = {
    "time.time", "time.time_ns", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.today", "datetime.date.today", "time.strftime",
}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and _name_of_call(node.func) in (
        "set", "frozenset"
    ):
        return True
    return False


def _r5_scope_nodes(m: Module) -> List[ast.AST]:
    if any(part in m.relpath for part in EXACT_PATH_PARTS) or \
            m.exact_marker:
        return [m.tree]
    for suffix, prefixes in EXACT_FUNCTION_PREFIXES.items():
        if m.relpath.endswith(suffix):
            out = []
            for name, defs in m.functions.items():
                if name.startswith(tuple(prefixes)):
                    out.extend(fn for _c, fn in defs)
            return out
    return []


def rule_r5_exact_paths(
    modules: Sequence[Module], cfg: LintConfig
) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        roots = _r5_scope_nodes(m)
        if not roots:
            continue
        # only flag receivers that really are the stdlib/numpy modules
        imported: Set[str] = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imported.add(a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                root = node.module.split(".")[0]
                for a in node.names:
                    if root in ("numpy", "random", "time", "datetime"):
                        imported.add(a.asname or a.name)
        seen: Set[int] = set()
        obs_names = _obs_bound_names(m)
        for root in roots:
            allowed = _obs_allowed_nodes(root, obs_names)
            for node in ast.walk(root):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                seen.add(id(node))
                if id(node) in allowed:
                    continue
                text = _unparse(node.func)
                base = text.split(".")[0]
                if text in _WALL_CLOCK and base in imported:
                    findings.append(Finding(
                        "R5", m.relpath, node.lineno,
                        f"wall-clock call {text}() inside a "
                        "bit-exactness scope (tables/io/PS loop)",
                        "use a caller-injected clock or "
                        "time.monotonic/perf_counter for stats; wall "
                        "time may never reach collective or checkpoint "
                        "payloads",
                    ))
                elif (
                    (text.startswith("np.random.")
                     or text.startswith("numpy.random."))
                    and base in imported
                    and not (
                        text.endswith("default_rng")
                        and (node.args or node.keywords)
                    )
                ) or (
                    base == "random" and base in imported
                    and text.startswith("random.")
                    and not text.endswith((".Random", ".seed"))
                ):
                    findings.append(Finding(
                        "R5", m.relpath, node.lineno,
                        f"global/unseeded RNG call {text}() inside a "
                        "bit-exactness scope",
                        "thread an explicit seeded Generator "
                        "(np.random.default_rng(seed)) through the "
                        "caller",
                    ))
                elif _name_of_call(node.func) in (
                    "list", "tuple", "asarray", "array", "fromiter",
                    "enumerate",
                ) and node.args and _is_set_expr(node.args[0]):
                    findings.append(Finding(
                        "R5", m.relpath, node.lineno,
                        "set materialized in iteration order inside a "
                        "bit-exactness scope (set order is hash-seed "
                        "dependent)",
                        "wrap it in sorted(...) before it can reach a "
                        "collective or checkpoint payload",
                    ))
            for node in ast.walk(root):
                if id(node) in allowed:
                    continue
                it = None
                if isinstance(node, ast.For):
                    it = node.iter
                elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                       ast.SetComp, ast.DictComp)):
                    it = node.generators[0].iter
                if it is not None and _is_set_expr(it):
                    findings.append(Finding(
                        "R5", m.relpath, node.lineno,
                        "iteration over a set inside a bit-exactness "
                        "scope (order is hash-seed dependent)",
                        "iterate sorted(the_set) instead",
                    ))
    return findings


from multiverso_tpu.analysis import rules_spmd as _spmd  # noqa: E402
from multiverso_tpu.analysis import rules_lifecycle as _life  # noqa: E402

ALL_RULES = (
    rule_r1_collective_dispatch,
    rule_r2_lock_order,
    rule_r3_flag_hygiene,
    rule_r4_thread_lifecycle,
    rule_r5_exact_paths,
    _spmd.rule_r6_rank_divergent_collective,
    _spmd.rule_r7_donation_aliasing,
    _spmd.rule_r8_retrace_churn,
    _spmd.rule_r9_cross_thread_state,
    _life.rule_r10_resource_typestate,
    _life.rule_r11_protocol_order,
    _life.rule_r12_flag_constraints,
)
