"""Static analysis (``mvlint``) + runtime concurrency guards.

``python -m multiverso_tpu.analysis multiverso_tpu/`` runs the five
repo-aware rules (R1 collective-dispatch-thread, R2 lock-order, R3 flag
hygiene, R4 thread lifecycle, R5 nondeterminism-in-exact-paths) described
in ``analysis/RULES.md``; the paired runtime guards live in
:mod:`multiverso_tpu.analysis.guards` behind ``-debug_thread_guards``.

This ``__init__`` stays import-light on purpose: the tables import the
guard decorators from here at module load, and must not drag the whole
AST engine (or anything heavier) with them.
"""

from multiverso_tpu.analysis.guards import (  # noqa: F401
    GuardViolation,
    OrderedLock,
    allow_collective_dispatch,
    collective_dispatch,
    register_comms_thread,
    register_training_thread,
    unregister_comms_thread,
)

__all__ = [
    "GuardViolation",
    "OrderedLock",
    "allow_collective_dispatch",
    "collective_dispatch",
    "register_comms_thread",
    "register_training_thread",
    "unregister_comms_thread",
    "run_lint",
]


def run_lint(*args, **kwargs):
    """Lazy forward to :func:`multiverso_tpu.analysis.mvlint.run_lint`."""
    from multiverso_tpu.analysis.mvlint import run_lint as _run

    return _run(*args, **kwargs)
