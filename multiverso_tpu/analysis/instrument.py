"""Static instrumentation plan for the mvtsan dynamic race detector.

The detector (:mod:`multiverso_tpu.analysis.mvtsan`) does NOT wrap
every Python attribute access — that would be a tracing profiler, not
a bounded-overhead debug mode. Instead mvlint's interprocedural
``ProjectGraph`` proves, per (class, attribute), which fields are
reachable from more than one thread entry (the same analysis behind
rule R9), and only those attributes get a data descriptor that feeds
the vector-clock engine. The plan carries the static verdict along —
``race`` entries cross-reference the R9 finding a dynamic RaceReport
confirms; ``writer-serialized``/``publication``/``lock-guarded``
entries are the exemption set the dynamic verdict must agree with.

The same plan, rendered as a table, is the
``python -m multiverso_tpu.analysis --shared-state-report`` CLI mode:
every (class, attr, guarding locks, reaching threads) triple the graph
knows about.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from multiverso_tpu.analysis import mvlint
from multiverso_tpu.analysis.dataflow import ProjectGraph
from multiverso_tpu.analysis.rules_spmd import (
    class_access_buckets,
    classify_attr,
    spmd_facts,
)

__all__ = [
    "PlanEntry",
    "Plan",
    "build_plan",
    "load_plan",
    "save_plan",
    "render_report",
    "apply_plan",
    "remove_all",
    "instrument_class",
    "instrumented_count",
]

PLAN_SCHEMA = 1


@dataclasses.dataclass(frozen=True)
class PlanEntry:
    """One instrumented (class, attribute) pair."""

    relpath: str          # module file, repo-relative
    cls: str              # class name
    attr: str             # attribute name
    classification: str   # AttrVerdict.classification
    locks: Tuple[str, ...]        # statically-proven common locks
    threads: Tuple[str, ...]      # thread entries reaching an accessor
    rmw: bool             # some write is a read-modify-write
    line: int             # representative access line (report anchor)

    @property
    def dotted_module(self) -> str:
        p = self.relpath[:-3] if self.relpath.endswith(".py") else \
            self.relpath
        return p.replace("/", ".")


@dataclasses.dataclass
class Plan:
    entries: List[PlanEntry]
    root: str = ""

    def by_key(self) -> Dict[Tuple[str, str], PlanEntry]:
        return {(e.cls, e.attr): e for e in self.entries}


def _reaching_threads(graph: ProjectGraph, facts,
                      acc_uids: set) -> Tuple[str, ...]:
    """Names of the thread entries whose reachable set intersects the
    accessor functions — the "who can touch this" column. Per-entry
    reachable sets are cached on the graph (one BFS per distinct
    entry, shared across all attributes)."""
    cache = getattr(graph, "_mv_entry_reach", None)
    if cache is None:
        cache = {}
        for _fn, _call, kind, entry in facts.thread_entries():
            label = f"{kind}:{entry.qualname}"
            if label not in cache:
                cache[label] = graph.reachable_set([entry])
        graph._mv_entry_reach = cache
    out = sorted(
        label for label, reach in cache.items() if reach & acc_uids
    )
    return tuple(out)


def build_plan(paths: Optional[Sequence[str]] = None) -> Plan:
    """Parse ``paths`` (default: the installed ``multiverso_tpu``
    package), build the ProjectGraph, and emit one entry per attribute
    the graph proves reachable from both a thread entry and main-side
    code. Reads-only and single-side attributes are omitted — they
    cannot race, and every skipped attribute is armed-mode overhead
    saved."""
    if paths is None:
        pkg_dir = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))
        paths = [pkg_dir]
    root = mvlint._find_repo_root(paths[0])
    modules: Dict[str, mvlint.Module] = {}
    for fp in mvlint._iter_py_files(paths):
        rel = os.path.relpath(fp, root)
        if rel.startswith(".."):
            rel = fp
        key = rel.replace(os.sep, "/")
        try:
            with open(fp, encoding="utf-8") as fh:
                src = fh.read()
            modules[key] = mvlint.Module(fp, rel, src)
        except (SyntaxError, ValueError, OSError):
            continue
    mods = list(modules.values())
    graph = ProjectGraph(mods)
    facts = spmd_facts(graph)
    tuids = facts.thread_uids()
    muids = facts.main_uids()
    entries: List[PlanEntry] = []
    for (relpath, clsname), attrs in sorted(
        class_access_buckets(mods, graph).items()
    ):
        for attr, accs in sorted(attrs.items()):
            v = classify_attr(accs, tuids, muids)
            if not v.cross_thread or v.classification in (
                "reads-only", "one-side"
            ):
                continue
            entries.append(PlanEntry(
                relpath=relpath,
                cls=clsname,
                attr=attr,
                classification=v.classification,
                locks=tuple(sorted(v.locks)),
                threads=_reaching_threads(
                    graph, facts, {a.fn.uid for a in accs}
                ),
                rmw=v.rmw,
                line=min(a.line for a in accs),
            ))
    return Plan(entries=entries, root=root)


def save_plan(plan: Plan, path: str) -> None:
    payload = {
        "schema": PLAN_SCHEMA,
        "root": plan.root,
        "entries": [dataclasses.asdict(e) for e in plan.entries],
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_plan(path: str) -> Plan:
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    if payload.get("schema") != PLAN_SCHEMA:
        raise ValueError(
            f"instrumentation plan {path}: schema "
            f"{payload.get('schema')!r} != {PLAN_SCHEMA}"
        )
    entries = [
        PlanEntry(
            relpath=e["relpath"], cls=e["cls"], attr=e["attr"],
            classification=e["classification"],
            locks=tuple(e["locks"]), threads=tuple(e["threads"]),
            rmw=bool(e["rmw"]), line=int(e["line"]),
        )
        for e in payload["entries"]
    ]
    return Plan(entries=entries, root=payload.get("root", ""))


def render_report(plan: Plan) -> str:
    """The ``--shared-state-report`` table: every (class, attr,
    guarding locks, reaching threads) triple the graph knows."""
    rows = [("class.attr", "verdict", "locks", "rmw",
             "reaching threads")]
    for e in sorted(plan.entries,
                    key=lambda e: (e.relpath, e.cls, e.attr)):
        rows.append((
            f"{e.cls}.{e.attr}",
            e.classification,
            ",".join(e.locks) or "-",
            "rmw" if e.rmw else "-",
            ", ".join(e.threads) or "-",
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    out = []
    for i, r in enumerate(rows):
        out.append("  ".join(
            [r[j].ljust(widths[j]) for j in range(4)] + [r[4]]
        ).rstrip())
        if i == 0:
            out.append("  ".join("-" * w for w in widths) + "  " + "-" * 16)
    n_race = sum(1 for e in plan.entries
                 if e.classification == "race")
    out.append("")
    out.append(
        f"{len(plan.entries)} shared attributes "
        f"({n_race} statically unguarded [R9], "
        f"{len(plan.entries) - n_race} exempt); "
        "instrumented by mvtsan when MV_RACE_DETECTOR=1 "
        "(analysis/RULES.md: Dynamic analysis)"
    )
    return "\n".join(out)


# ---------------------------------------------------- descriptor install
#
# Armed mode only: apply_plan swaps a data descriptor into each planned
# class for each planned attribute. The descriptor stores the value
# where it always lived (the instance ``__dict__``) and keeps the race
# shadow state next to it under a non-identifier key, so object
# lifetime carries the shadow with no global map and no id() reuse
# hazard. Disarmed processes never install anything — the production
# hot path cost of this module is zero.

_installed: List[Tuple[type, str, bool, object]] = []


def _resolve_class(entry: PlanEntry) -> Optional[type]:
    import importlib

    try:
        mod = importlib.import_module(entry.dotted_module)
    except Exception:  # noqa: BLE001 — scripts/examples may not import
        return None
    obj = getattr(mod, entry.cls, None)
    return obj if isinstance(obj, type) else None


_CONST_DEFAULTS = (int, float, str, bool, bytes, tuple, frozenset,
                   type(None))


def _instrument_one(cls: type, attr: str, entry: Optional[PlanEntry],
                    relpath: str) -> bool:
    import inspect

    from multiverso_tpu.analysis import mvtsan

    # slotted classes keep values in slot descriptors, not the
    # instance dict — our descriptor has nowhere to store
    if not any("__dict__" in k.__dict__ for k in cls.__mro__
               if k is not object):
        return False
    missing = object()
    try:
        existing = inspect.getattr_static(cls, attr)
    except AttributeError:
        existing = missing
    if existing is not missing and not isinstance(
        existing, _CONST_DEFAULTS
    ):
        # attr name collides with a method/property/slot descriptor
        # (own or inherited) — wrapping would change semantics, skip
        return False
    had_own = attr in cls.__dict__
    orig_own = cls.__dict__.get(attr)
    try:
        desc = mvtsan.InstrumentedAttr(
            cls.__name__, attr, relpath, entry,
            default=mvtsan._NO_DEFAULT if existing is missing
            else existing,
        )
        setattr(cls, attr, desc)
    except (AttributeError, TypeError):
        return False
    _installed.append((cls, attr, had_own, orig_own))
    return True


def apply_plan(plan: Plan) -> Tuple[int, List[PlanEntry]]:
    """Install descriptors for every resolvable plan entry. Returns
    (installed count, skipped entries). Import failures and descriptor
    collisions skip the entry rather than failing the arm — a partial
    plan still catches races on everything it covers."""
    installed = 0
    skipped: List[PlanEntry] = []
    for entry in plan.entries:
        cls = _resolve_class(entry)
        if cls is None or not _instrument_one(
            cls, entry.attr, entry, entry.relpath
        ):
            skipped.append(entry)
            continue
        installed += 1
    return installed, skipped


def instrument_class(cls: type, attrs: Sequence[str],
                     relpath: str = "<test>") -> int:
    """Directly instrument ``attrs`` on ``cls`` — the fixture/test
    entry point that bypasses the static plan."""
    n = 0
    for attr in attrs:
        if _instrument_one(cls, attr, None, relpath):
            n += 1
    return n


def remove_all(down_to: int = 0) -> None:
    """Uninstall descriptors apply_plan/instrument_class put in (test
    isolation and disarm). ``down_to`` keeps the first N installs — a
    test that instrumented its own fixture class on an already-armed
    session removes only its own additions."""
    while len(_installed) > down_to:
        cls, attr, had, orig = _installed.pop()
        try:
            if had:
                setattr(cls, attr, orig)
            else:
                delattr(cls, attr)
        except (AttributeError, TypeError):
            pass


def instrumented_count() -> int:
    return len(_installed)
