"""mvlint — repo-aware static analysis engine.

Pure stdlib (``ast`` + a minimal TOML-subset reader; the pinned
interpreter is 3.10, before ``tomllib``). The engine parses every target
file once, hands the module set to each rule in
:mod:`multiverso_tpu.analysis.rules`, filters the findings through inline
pragmas and the checked-in ``analysis/baseline.toml``, and renders
``path:line: RULE message`` lines with a one-line fix hint.

Suppression channels (both require a justification):

* inline: ``# mvlint: allow[R4] <why>`` on the finding line;
* baseline: a ``[[suppress]]`` entry in ``baseline.toml`` with ``rule``,
  ``path`` (substring of the repo-relative path), optional ``contains``
  (substring of the message) and a mandatory ``reason``.

The baseline starts — and should stay — empty: the repo lints clean, and
new findings are fixed, not suppressed (analysis/RULES.md).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import pickle
import re
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "Module",
    "LintConfig",
    "LintResult",
    "run_lint",
    "load_baseline",
    "format_findings",
]

_PRAGMA_RE = re.compile(r"#\s*mvlint:\s*allow\[(R\d+|\*)\]\s*(\S.*)?$")
_EXACT_MARKER_RE = re.compile(r"#\s*mvlint:\s*exact-module\b")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative (display) path
    line: int
    message: str
    hint: str = ""
    suppressed_by: str = ""  # "", "pragma", or the baseline reason

    def render(self) -> str:
        out = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class Module:
    """One parsed source file plus the lexical facts rules keep asking
    for: the raw lines (pragma scan), every function def (including
    nested) indexed by name, and class membership for methods."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.exact_marker = any(
            _EXACT_MARKER_RE.search(ln) for ln in self.lines[:30]
        )
        # name -> [(class_name or "", FunctionDef)]
        self.functions: Dict[str, List[Tuple[str, ast.AST]]] = {}
        self._index_functions()

    def _index_functions(self) -> None:
        def visit(node, cls: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self.functions.setdefault(child.name, []).append(
                        (cls, child)
                    )
                    visit(child, cls)
                else:
                    visit(child, cls)

        visit(self.tree, "")

    def lookup_method(self, cls: str, name: str) -> Optional[ast.AST]:
        for c, fn in self.functions.get(name, ()):
            if c == cls:
                return fn
        return None

    def pragma_for_line(self, line: int) -> Optional[Tuple[str, str]]:
        """``(rule, justification)`` if the line (or the line above it)
        carries an ``# mvlint: allow[...]`` pragma."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _PRAGMA_RE.search(self.lines[ln - 1])
                if m:
                    return m.group(1), (m.group(2) or "").strip()
        return None


@dataclasses.dataclass
class LintConfig:
    """Engine knobs. ``aux_read_roots`` widens rule R3's *read* index
    (flags may legitimately be consumed only by the bench/tests/deploy
    drivers); ``doc_files`` is where user-facing flags must be
    documented (empty disables the doc check — fixture runs)."""

    aux_read_roots: Sequence[str] = ()
    doc_files: Sequence[str] = ()
    repo_root: str = ""
    # --diff mode: when not None, findings are reported only for these
    # repo-relative paths. The parse and the interprocedural graph still
    # cover the FULL tree (a change in a callee can create a finding in
    # its caller's file — cross-file analysis must not go blind), only
    # the emission is restricted.
    restrict_paths: Optional[Sequence[str]] = None
    # incremental parse cache: a pickle of {relpath: (sha256, Module)}.
    # Parsing is the only thing cached — rules always re-run, so a rule
    # change needs no invalidation, only a content change does.
    parse_cache_path: Optional[str] = None


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Finding]
    files: int
    runtime_s: float
    # per-rule-id wall time ("R10" -> seconds; "graph" = ProjectGraph
    # construction, "parse" = file parsing) — the bench leg records it
    rule_times: Dict[str, float] = dataclasses.field(default_factory=dict)
    files_reparsed: int = 0
    files_cached: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        out.append(os.path.join(dirpath, f))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _find_repo_root(start: str) -> str:
    """Nearest ancestor holding the package marker — anchors relative
    display paths and the default doc/aux locations."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.isfile(os.path.join(cur, "multiverso_tpu", "__init__.py")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start if os.path.isdir(start) else ".")
        cur = parent


def default_config(paths: Sequence[str]) -> LintConfig:
    """The repo run's configuration: aux read roots + doc files resolved
    relative to the detected repo root, included only when present."""
    root = _find_repo_root(paths[0] if paths else ".")
    aux = [
        os.path.join(root, p)
        for p in ("tests", "examples", "deploy", "bench.py", "ci.sh")
        if os.path.exists(os.path.join(root, p))
    ]
    docs = [
        os.path.join(root, p)
        for p in ("README.md", "DEPLOY.md")
        if os.path.exists(os.path.join(root, p))
    ]
    return LintConfig(aux_read_roots=aux, doc_files=docs, repo_root=root)


# ----------------------------------------------------------- baseline.toml

_TOML_KV_RE = re.compile(r"""^([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*$""")


def load_baseline(path: str) -> List[Dict[str, str]]:
    """Read ``baseline.toml``'s ``[[suppress]]`` entries. Supported
    subset: ``[[suppress]]`` table headers with ``key = "string"`` lines
    and ``#`` comments — exactly what the suppression schema needs on a
    3.10 interpreter without ``tomllib`` (and valid TOML throughout, so
    real parsers read it too)."""
    if not os.path.exists(path):
        return []
    entries: List[Dict[str, str]] = []
    cur: Optional[Dict[str, str]] = None
    with open(path, encoding="utf-8") as fh:
        for ln, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[suppress]]":
                cur = {}
                entries.append(cur)
                continue
            m = _TOML_KV_RE.match(line)
            if m and cur is not None:
                cur[m.group(1)] = m.group(2).encode().decode(
                    "unicode_escape"
                )
                continue
            raise ValueError(
                f"{path}:{ln}: unsupported baseline syntax {line!r} "
                "(only [[suppress]] tables with string keys)"
            )
    for i, e in enumerate(entries):
        if not e.get("rule") or not e.get("path") or not e.get("reason"):
            raise ValueError(
                f"{path}: suppress entry #{i + 1} needs rule, path AND "
                "a justification reason"
            )
    return entries


def _apply_suppressions(
    findings: List[Finding],
    modules: Dict[str, Module],
    baseline: List[Dict[str, str]],
) -> Tuple[List[Finding], List[Finding]]:
    live: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        mod = modules.get(f.path)
        pragma = mod.pragma_for_line(f.line) if mod else None
        if pragma and pragma[0] in (f.rule, "*") and pragma[1]:
            f.suppressed_by = f"pragma: {pragma[1]}"
            suppressed.append(f)
            continue
        hit = None
        for e in baseline:
            if e["rule"] not in (f.rule, "*"):
                continue
            if e["path"] not in f.path:
                continue
            if e.get("contains") and e["contains"] not in f.message:
                continue
            hit = e
            break
        if hit is not None:
            f.suppressed_by = f"baseline: {hit['reason']}"
            suppressed.append(f)
        else:
            live.append(f)
    return live, suppressed


# ------------------------------------------------------------------ driver

# bump when the pickled Module shape changes (the cache stores parse
# results only — rules re-run every time, so rule edits need no bump)
_PARSE_CACHE_SCHEMA = 1

_RULE_ID_RE = re.compile(r"_r(\d+)")


def _load_parse_cache(path: str) -> Dict[str, Tuple[str, Module]]:
    try:
        with open(path, "rb") as fh:
            payload = pickle.load(fh)
        if payload.get("schema") == _PARSE_CACHE_SCHEMA:
            return payload["modules"]
    except Exception:  # noqa: BLE001 - any stale/corrupt cache: reparse
        pass
    return {}


def _save_parse_cache(path: str,
                      cache: Dict[str, Tuple[str, Module]]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            pickle.dump(
                {"schema": _PARSE_CACHE_SCHEMA, "modules": cache}, fh,
                protocol=pickle.HIGHEST_PROTOCOL,
            )
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 - cache is best-effort
        try:
            os.unlink(tmp)
        except OSError:
            pass


def run_lint(
    paths: Sequence[str],
    config: Optional[LintConfig] = None,
    baseline_path: Optional[str] = None,
) -> LintResult:
    from multiverso_tpu.analysis import rules as rules_mod

    t0 = time.perf_counter()
    cfg = config if config is not None else default_config(paths)
    root = cfg.repo_root or _find_repo_root(paths[0] if paths else ".")
    files = _iter_py_files(paths)
    modules: Dict[str, Module] = {}
    findings: List[Finding] = []
    rule_times: Dict[str, float] = {}
    cache: Dict[str, Tuple[str, Module]] = (
        _load_parse_cache(cfg.parse_cache_path)
        if cfg.parse_cache_path else {}
    )
    reused = 0
    reparsed = 0
    for fp in files:
        rel = os.path.relpath(fp, root)
        if rel.startswith(".."):
            rel = fp
        key = rel.replace(os.sep, "/")
        try:
            with open(fp, encoding="utf-8") as fh:
                src = fh.read()
            hit = cache.get(key) if cfg.parse_cache_path else None
            if hit is not None and hit[0] == hashlib.sha256(
                src.encode("utf-8")
            ).hexdigest():
                modules[key] = hit[1]
                reused += 1
                continue
            mod = Module(fp, rel, src)
            modules[key] = mod
            reparsed += 1
            if cfg.parse_cache_path:
                cache[key] = (
                    hashlib.sha256(src.encode("utf-8")).hexdigest(), mod
                )
        except (SyntaxError, ValueError) as e:
            # ValueError too: NUL bytes raise it (not SyntaxError) on
            # 3.10 — one unparseable file is a per-file R0 finding, not
            # an aborted run
            findings.append(Finding(
                "R0", key,
                getattr(e, "lineno", 0) or 0,
                f"unparseable source: {getattr(e, 'msg', None) or e}",
                "mvlint needs parseable sources",
            ))
    rule_times["parse"] = time.perf_counter() - t0
    if cfg.parse_cache_path:
        _save_parse_cache(cfg.parse_cache_path, cache)
    mods = list(modules.values())
    graph = None
    for rule_fn in rules_mod.ALL_RULES:
        t_rule = time.perf_counter()
        if getattr(rule_fn, "needs_graph", False):
            if graph is None:
                from multiverso_tpu.analysis.dataflow import ProjectGraph
                t_graph = time.perf_counter()
                graph = ProjectGraph(mods)
                dt = time.perf_counter() - t_graph
                rule_times["graph"] = dt
                t_rule += dt  # the graph is shared, not this rule's cost
            findings.extend(rule_fn(mods, cfg, graph))
        else:
            findings.extend(rule_fn(mods, cfg))
        m = _RULE_ID_RE.search(rule_fn.__name__)
        rid = f"R{m.group(1)}" if m else rule_fn.__name__
        rule_times[rid] = rule_times.get(rid, 0.0) \
            + (time.perf_counter() - t_rule)
    if cfg.restrict_paths is not None:
        keep = {p.replace(os.sep, "/") for p in cfg.restrict_paths}
        findings = [f for f in findings if f.path in keep]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    if baseline_path is None:
        baseline_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "baseline.toml"
        )
    baseline = load_baseline(baseline_path)
    live, suppressed = _apply_suppressions(findings, modules, baseline)
    return LintResult(
        findings=live,
        suppressed=suppressed,
        files=len(files),
        runtime_s=time.perf_counter() - t0,
        rule_times=rule_times,
        files_reparsed=reparsed,
        files_cached=reused,
    )


def format_findings(result: LintResult, verbose: bool = False) -> str:
    out = [f.render() for f in result.findings]
    if verbose:
        for f in result.suppressed:
            out.append(f"[suppressed: {f.suppressed_by}] {f.render()}")
    out.append(
        f"mvlint: {len(result.findings)} finding(s) "
        f"({len(result.suppressed)} suppressed) across "
        f"{result.files} file(s) in {result.runtime_s:.2f}s"
    )
    return "\n".join(out)
