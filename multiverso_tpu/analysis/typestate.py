"""Typestate engine for mvlint v3 (rules R10-R11).

R1-R9 reason about reachability and data races; the bug classes this
repo has actually paid for in PRs 6, 8, 9 and 12 were *protocol* bugs:
a resource whose finite-state machine (created -> armed -> finalized)
was driven out of order, or never driven to its final state on some
exit path.  This module checks those machines statically:

* a **per-function CFG** over statements, with explicit ``raise`` /
  ``assert`` edges and *continuation-aware* ``try/finally`` + ``with``
  lowering — the ``finally`` body is copied per continuation (normal,
  return, raise, break, continue), so a ``close()`` in a ``finally``
  dominates every exit without fabricating close-then-loop-again paths
  that would flag the pipelined PS loop's own idiom;
* a **resource dataflow**: each tracked binding carries a state set
  {UNARMED, OPEN, CLOSED, ESCAPED} through the CFG; a finalizer call
  moves OPEN to CLOSED, a ``use`` while possibly CLOSED is a
  use-after-finalize violation, OPEN reaching EXIT is a leak;
* **interprocedural must-call summaries** via the same fixpoint shape
  ``dataflow.py`` uses: a helper that finalizes its parameter on every
  exit path counts as a finalizer at its call sites, and a helper that
  unconditionally calls a *region* finalizer (``release_tables``)
  discharges the region at its call sites;
* **path queries** for the protocol-ordering rules: ``must_pass``
  (every ENTRY->target path crosses a blocker — stage->verify->commit,
  drain-dominates-save) and ``may_pending`` (gen/kill reachability —
  submitted-but-not-drained at a save site).

Everything is pure-``ast`` over ``dataflow.ProjectGraph`` facts;
nothing imports the code under analysis.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, \
    Sequence, Set, Tuple

from multiverso_tpu.analysis.dataflow import (
    FuncInfo, ProjectGraph, call_name, receiver_of,
)

__all__ = [
    "CFG",
    "build_cfg",
    "ResourceSpec",
    "Violation",
    "Summaries",
    "local_resources",
    "check_function",
    "must_pass",
    "may_pending",
    "nodes_where",
]

# resource states
UNARMED = "unarmed"
OPEN = "open"
CLOSED = "closed"
ESCAPED = "escaped"


# ---------------------------------------------------------------------------
# CFG
# ---------------------------------------------------------------------------

class CFG:
    """Statement-level control-flow graph of one function body.

    Nodes are ints; ``stmt_of[n]`` is the AST statement the node stands
    for (``None`` for ENTRY/EXIT and synthetic join nodes).  A statement
    can back several nodes — ``finally`` bodies are copied once per
    continuation kind — so queries go node -> stmt, and ``nodes_of``
    maps a statement back to every copy.  ``with_exit_vars[n]`` lists
    the context-manager variable names whose ``__exit__`` runs at node
    ``n`` (the ``with``/``finally`` recognition R10 needs)."""

    ENTRY = 0
    EXIT = 1

    def __init__(self) -> None:
        self.stmt_of: List[Optional[ast.stmt]] = [None, None]
        self.succ: List[Set[int]] = [set(), set()]
        self.nodes_of: Dict[int, List[int]] = {}  # id(stmt) -> nodes
        self.with_exit_vars: Dict[int, Tuple[str, ...]] = {}

    def new_node(self, stmt: Optional[ast.stmt]) -> int:
        n = len(self.stmt_of)
        self.stmt_of.append(stmt)
        self.succ.append(set())
        if stmt is not None:
            self.nodes_of.setdefault(id(stmt), []).append(n)
        return n

    def connect(self, frontier: Iterable[int], node: int) -> None:
        for f in frontier:
            self.succ[f].add(node)

    def preds(self) -> List[Set[int]]:
        out: List[Set[int]] = [set() for _ in self.stmt_of]
        for n, succs in enumerate(self.succ):
            for s in succs:
                out[s].add(n)
        return out


class _Frame:
    """One entry of the builder's unwind stack.

    ``finally`` and ``with`` frames carry a cleanup body that every
    continuation leaving the frame must execute; ``except`` frames
    catch in-flight raises; ``loop`` frames anchor break/continue."""

    __slots__ = ("kind", "stmts", "with_stmt", "with_vars",
                 "header", "after")

    def __init__(self, kind: str, *, stmts: Sequence[ast.stmt] = (),
                 with_stmt: Optional[ast.stmt] = None,
                 with_vars: Tuple[str, ...] = (),
                 header: int = -1, after: int = -1) -> None:
        self.kind = kind  # "finally" | "with" | "except" | "loop"
        self.stmts = list(stmts)
        self.with_stmt = with_stmt
        self.with_vars = with_vars
        self.header = header  # loop: continue target
        self.after = after    # loop: break target (join node)


class _Builder:
    def __init__(self, fn_node: ast.AST) -> None:
        self.cfg = CFG()
        self.frames: List[_Frame] = []
        body = getattr(fn_node, "body", [])
        frontier = self._seq(body, {CFG.ENTRY})
        self.cfg.connect(frontier, CFG.EXIT)

    # -- continuation routing -------------------------------------------

    def _cleanup_node(self, frame: _Frame, target: int) -> int:
        """A fresh copy of ``frame``'s cleanup whose exit goes to
        ``target``; returns the copy's entry node."""
        if frame.kind == "with":
            n = self.cfg.new_node(frame.with_stmt)
            self.cfg.with_exit_vars[n] = frame.with_vars
            self.cfg.succ[n].add(target)
            return n
        # finally: rebuild the body with fresh nodes.  The body runs
        # OUTSIDE the frame it cleans (a raise inside a finally leaves
        # through the outer frames), which the recursion models by the
        # frame already being popped conceptually — we splice around it
        # by temporarily dropping it from the stack.
        idx = self.frames.index(frame)
        saved = self.frames
        self.frames = saved[:idx]
        entry_mark = len(self.cfg.stmt_of)
        frontier = self._seq(frame.stmts, set())
        self.frames = saved
        if entry_mark == len(self.cfg.stmt_of):  # empty finally body
            return target
        self.cfg.connect(frontier, target)
        # entry is the first node the sequence created
        return entry_mark

    def _route(self, kind: str, jumpers: Set[int]) -> None:
        """Connect ``jumpers`` to the continuation ``kind`` ("return",
        "raise", "break", "continue") through every intervening cleanup
        frame (innermost first)."""
        cleanups: List[_Frame] = []
        target = CFG.EXIT
        for frame in reversed(self.frames):
            if frame.kind in ("finally", "with"):
                cleanups.append(frame)
            elif frame.kind == "except" and kind == "raise":
                # caught here: handler entries were wired when the try
                # body was built; an explicit raise just flows to them
                target = -1
                break
            elif frame.kind == "loop" and kind in ("break", "continue"):
                target = frame.after if kind == "break" else frame.header
                break
        if target == -1:
            return
        for frame in cleanups:  # innermost cleanup runs first
            target = self._cleanup_node(frame, target)
        self.cfg.connect(jumpers, target)

    def _handler_entries(self) -> List[int]:
        """Pending-handler entry nodes of the innermost except frame (a
        statement that may raise flows there), crossing with/finally
        cleanups on the way."""
        out: List[int] = []
        cleanups: List[_Frame] = []
        for frame in reversed(self.frames):
            if frame.kind in ("finally", "with"):
                cleanups.append(frame)
            elif frame.kind == "except":
                for entry in frame.stmts:  # reused: handler entry nodes
                    tgt = entry
                    for c in cleanups:
                        tgt = self._cleanup_node(c, tgt)
                    out.append(tgt)
                break
        return out

    # -- structure -------------------------------------------------------

    def _seq(self, stmts: Sequence[ast.stmt], frontier: Set[int]
             ) -> Set[int]:
        for stmt in stmts:
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _stmt(self, stmt: ast.stmt, frontier: Set[int]) -> Set[int]:
        cfg = self.cfg
        if isinstance(stmt, (ast.If,)):
            n = cfg.new_node(stmt)
            cfg.connect(frontier, n)
            out = self._seq(stmt.body, {n})
            out |= self._seq(stmt.orelse, {n}) if stmt.orelse else {n}
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            header = cfg.new_node(stmt)
            after = cfg.new_node(None)  # join for breaks + loop exit
            cfg.connect(frontier, header)
            self.frames.append(_Frame("loop", header=header, after=after))
            body_out = self._seq(stmt.body, {header})
            self.frames.pop()
            cfg.connect(body_out, header)  # back edge
            else_out = self._seq(stmt.orelse, {header}) if stmt.orelse \
                else {header}
            cfg.connect(else_out, after)
            return {after}
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            n = cfg.new_node(stmt)
            cfg.connect(frontier, n)
            wvars = tuple(
                v for item in stmt.items
                for v in _with_item_vars(item)
            )
            frame = _Frame("with", with_stmt=stmt, with_vars=wvars)
            self.frames.append(frame)
            body_out = self._seq(stmt.body, {n})
            self.frames.pop()
            exit_n = cfg.new_node(stmt)
            cfg.with_exit_vars[exit_n] = wvars
            cfg.connect(body_out, exit_n)
            return {exit_n}
        if isinstance(stmt, ast.Try):
            return self._try(stmt, frontier)
        if isinstance(stmt, ast.Return):
            n = cfg.new_node(stmt)
            cfg.connect(frontier, n)
            self._route("return", {n})
            return set()
        if isinstance(stmt, ast.Raise):
            n = cfg.new_node(stmt)
            cfg.connect(frontier, n)
            handlers = self._handler_entries()
            if handlers:
                for h in handlers:
                    cfg.succ[n].add(h)
            else:
                self._route("raise", {n})
            return set()
        if isinstance(stmt, ast.Assert):
            n = cfg.new_node(stmt)
            cfg.connect(frontier, n)
            handlers = self._handler_entries()
            if handlers:
                for h in handlers:
                    cfg.succ[n].add(h)
            else:
                self._route("raise", {n})
            return {n}  # and the passing case falls through
        if isinstance(stmt, ast.Break):
            n = cfg.new_node(stmt)
            cfg.connect(frontier, n)
            self._route("break", {n})
            return set()
        if isinstance(stmt, ast.Continue):
            n = cfg.new_node(stmt)
            cfg.connect(frontier, n)
            self._route("continue", {n})
            return set()
        # simple statement (incl. nested def/class headers)
        n = cfg.new_node(stmt)
        cfg.connect(frontier, n)
        return {n}

    def _try(self, stmt: ast.Try, frontier: Set[int]) -> Set[int]:
        cfg = self.cfg
        fin_frame = _Frame("finally", stmts=stmt.finalbody) \
            if stmt.finalbody else None
        # handler entry placeholders so body raises have a target
        handler_entries: List[int] = []
        exc_frame = None
        if stmt.handlers:
            handler_entries = [cfg.new_node(None) for _ in stmt.handlers]
            exc_frame = _Frame("except", stmts=handler_entries)
        if fin_frame is not None:
            self.frames.append(fin_frame)
        if exc_frame is not None:
            self.frames.append(exc_frame)
        body_mark = len(cfg.stmt_of)
        body_out = self._seq(stmt.body, set(frontier))
        body_nodes = range(body_mark, len(cfg.stmt_of))
        # any statement of the body may raise into the handlers
        for bn in body_nodes:
            for h in handler_entries:
                cfg.succ[bn].add(h)
        if handler_entries and frontier:
            # the first body statement may raise before running at all
            for f in frontier:
                for h in handler_entries:
                    cfg.succ[f].add(h)
        if exc_frame is not None:
            self.frames.pop()  # handlers do not catch their own raises
        out = self._seq(stmt.orelse, body_out) if stmt.orelse else body_out
        for entry, handler in zip(handler_entries, stmt.handlers):
            h_out = self._seq(handler.body, {entry})
            out |= h_out
        if fin_frame is not None:
            self.frames.pop()
            # normal continuation runs the finally once
            fin_entry_mark = len(cfg.stmt_of)
            fin_out = self._seq(stmt.finalbody, set())
            if fin_entry_mark == len(cfg.stmt_of):
                return out
            cfg.connect(out, fin_entry_mark)
            return fin_out
        return out


def _with_item_vars(item: ast.withitem) -> Tuple[str, ...]:
    names: List[str] = []
    if isinstance(item.optional_vars, ast.Name):
        names.append(item.optional_vars.id)
    if isinstance(item.context_expr, ast.Name):
        names.append(item.context_expr.id)
    return tuple(names)


# keyed by id() but holding the node itself: the reference pins the AST
# alive, so a cached id can never be recycled by a different node (tests
# run many lints in one process)
_CFG_CACHE: Dict[int, Tuple[ast.AST, CFG]] = {}


def build_cfg(fn_node: ast.AST) -> CFG:
    cached = _CFG_CACHE.get(id(fn_node))
    if cached is not None and cached[0] is fn_node:
        return cached[1]
    if len(_CFG_CACHE) > 8192:
        _CFG_CACHE.clear()
        _PRED_CACHE.clear()
    cfg = _Builder(fn_node).cfg
    _CFG_CACHE[id(fn_node)] = (fn_node, cfg)
    return cfg


# ---------------------------------------------------------------------------
# Statement event extraction
# ---------------------------------------------------------------------------

def _header_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a CFG node for ``stmt`` actually evaluates —
    compound statements contribute only their header (their bodies are
    separate nodes)."""
    if isinstance(stmt, ast.If):
        return [stmt.test]
    if isinstance(stmt, ast.While):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value else []
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, ast.Assert):
        return [e for e in (stmt.test, stmt.msg) if e is not None]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Try)):
        return []
    return [stmt]


def _walk_no_defs(roots: Iterable[ast.AST]) -> Iterable[ast.AST]:
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def node_calls(cfg: CFG, n: int) -> List[ast.Call]:
    stmt = cfg.stmt_of[n]
    if stmt is None:
        return []
    out = [c for c in _walk_no_defs(_header_exprs(stmt))
           if isinstance(c, ast.Call)]
    out.sort(key=lambda c: (c.lineno, c.col_offset))
    return out


def nodes_where(cfg: CFG, pred: Callable[[ast.Call], bool]) -> Set[int]:
    """Nodes containing at least one call matching ``pred``."""
    out: Set[int] = set()
    for n in range(len(cfg.stmt_of)):
        if any(pred(c) for c in node_calls(cfg, n)):
            out.add(n)
    return out


# ---------------------------------------------------------------------------
# Resource specs + dataflow
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """One protocol the typestate checker enforces.

    ``arm_methods`` empty means the resource is live from construction
    (TaskPipe spawns its worker in ``__init__``); otherwise it only
    needs finalizing once armed (a never-``start()``ed Thread needs no
    join).  ``region_finalizers`` discharge EVERY live resource of the
    spec at the call site regardless of receiver — the
    ``release_tables``-by-registry-diff idiom can't be tracked through
    a variable.  ``allow_escape`` controls whether passing the binding
    to an unresolved callee transfers ownership (True for thread-like
    resources; False for registry-pinned tables, where only an explicit
    release or a return discharges)."""

    rtype: str
    ctors: Tuple[str, ...]
    finalizers: Tuple[str, ...]
    uses: Tuple[str, ...] = ()
    arm_methods: Tuple[str, ...] = ()
    region_finalizers: Tuple[str, ...] = ()
    allow_escape: bool = True
    daemon_exempt: bool = False
    leak_hint: str = ""


@dataclasses.dataclass(frozen=True)
class Violation:
    kind: str          # "leak" | "use_after_finalize"
    spec: ResourceSpec
    var: str
    line: int
    detail: str


def _call_has_true_kwarg(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _ctor_call_of(value: ast.AST, spec: ResourceSpec
                  ) -> Optional[Tuple[ast.Call, bool]]:
    """``(ctor_call, armed_at_birth)`` if ``value`` constructs ``spec``
    — either plainly (``TaskPipe(...)``) or fluently through an arm
    method (``TableServer(...).start()``, which binds an already-armed
    resource)."""
    if not isinstance(value, ast.Call):
        return None
    if call_name(value.func) in spec.ctors:
        return value, not spec.arm_methods
    if isinstance(value.func, ast.Attribute) \
            and value.func.attr in spec.arm_methods \
            and isinstance(value.func.value, ast.Call) \
            and call_name(value.func.value.func) in spec.ctors:
        return value.func.value, True
    return None


def local_resources(graph: ProjectGraph, fn: FuncInfo, spec: ResourceSpec
                    ) -> List[Tuple[str, ast.stmt, ast.Call, bool]]:
    """``var = Ctor(...)`` bindings of ``spec`` owned by ``fn`` itself
    (``var, stmt, ctor_call, armed_at_birth`` tuples).  Multi-target
    assigns (``a = self._b = Ctor()``) escape at birth and are left to
    the class-level pairing checks."""
    out: List[Tuple[str, ast.stmt, ast.Call, bool]] = []
    for node in graph.own_nodes(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        hit = _ctor_call_of(node.value, spec)
        if hit is None:
            continue
        call, armed = hit
        if spec.daemon_exempt and _call_has_true_kwarg(call, "daemon"):
            continue
        out.append((node.targets[0].id, node, call, armed))
    return out


def _param_names(fn_node: ast.AST) -> List[str]:
    args = getattr(fn_node, "args", None)
    if args is None:
        return []
    names = [a.arg for a in args.posonlyargs + args.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names + [a.arg for a in args.kwonlyargs]


class Summaries:
    """Interprocedural must-call facts, one fixpoint per rule run.

    ``closes_param[uid]`` maps a function to the parameter names it
    finalizes (per spec rtype) on EVERY exit path; ``region_always``
    holds the functions that unconditionally reach a region finalizer.
    Both feed back into the intraprocedural transfer, so a
    ``_teardown(pipe)`` helper counts exactly like ``pipe.close()``."""

    def __init__(self, graph: ProjectGraph,
                 specs: Sequence[ResourceSpec]) -> None:
        self.graph = graph
        self.specs = list(specs)
        # uid -> rtype -> frozenset(param names always finalized)
        self.closes_param: Dict[int, Dict[str, FrozenSet[str]]] = {}
        # rtype -> set of uids that always region-finalize
        self.region_always: Dict[str, Set[int]] = {
            s.rtype: set() for s in specs
        }
        self._called_names: Dict[int, FrozenSet[str]] = {}
        self._fixpoint()

    def _names_called(self, fn: FuncInfo) -> FrozenSet[str]:
        cached = self._called_names.get(fn.uid)
        if cached is None:
            cached = frozenset(
                call_name(n.func) for n in self.graph.own_nodes(fn)
                if isinstance(n, ast.Call)
            )
            self._called_names[fn.uid] = cached
        return cached

    def _may_finalize(self, fn: FuncInfo, spec: ResourceSpec) -> bool:
        """Cheap prescreen: can this function possibly finalize anything
        of ``spec``, directly or through a currently-summarized callee?
        Monotone, so a False that turns True is caught next pass."""
        called = self._names_called(fn)
        if called & set(spec.finalizers + spec.region_finalizers):
            return True
        for callee in self.graph.callees(fn):
            if self.closes_param.get(callee.uid, {}).get(spec.rtype):
                return True
            if callee.uid in self.region_always.get(spec.rtype, ()):
                return True
        return False

    def _fixpoint(self) -> None:
        funcs = [
            fn for fn in self.graph.funcs.values()
            if not isinstance(fn.node, ast.Lambda)
        ]
        for _ in range(6):  # call chains deeper than this don't occur
            changed = False
            for fn in funcs:
                for spec in self.specs:
                    changed |= self._summarize(fn, spec)
            if not changed:
                return

    def _summarize(self, fn: FuncInfo, spec: ResourceSpec) -> bool:
        changed = False
        if not self._may_finalize(fn, spec):
            return False
        params = _param_names(fn.node)
        cfg = build_cfg(fn.node)
        closed: Set[str] = set()
        names_used = {
            n.id for n in self.graph.own_nodes(fn)
            if isinstance(n, ast.Name)
        }
        for p in params:
            if p not in names_used:
                continue
            states = _flow(self.graph, fn, cfg, spec, p,
                           start_nodes=(CFG.ENTRY,), summaries=self,
                           collect=None)
            exit_states = states.get(CFG.EXIT, frozenset())
            if exit_states and exit_states <= {CLOSED}:
                closed.add(p)
        prev = self.closes_param.setdefault(fn.uid, {})
        new = frozenset(closed)
        if prev.get(spec.rtype) != new:
            prev[spec.rtype] = new
            changed = True
        if spec.region_finalizers:
            states = _flow(self.graph, fn, cfg, spec, None,
                           start_nodes=(CFG.ENTRY,), summaries=self,
                           collect=None)
            exit_states = states.get(CFG.EXIT, frozenset())
            always = bool(exit_states) and exit_states <= {CLOSED}
            reg = self.region_always[spec.rtype]
            if always and fn.uid not in reg:
                reg.add(fn.uid)
                changed = True
        return changed

    # -- call-site queries ----------------------------------------------

    def call_finalizes_arg(self, fn: FuncInfo, call: ast.Call,
                           spec: ResourceSpec, var: str
                           ) -> Optional[bool]:
        """Does passing ``var`` to ``call`` finalize it?  True = yes on
        all callee paths; False = resolved callee does not; None = the
        callee is outside the scan (ownership unknown)."""
        callees = self.graph._resolve_name_or_attr(fn, call.func)
        if not callees:
            return None
        ok = False
        for callee in callees:
            params = _param_names(callee.node)
            summary = self.closes_param.get(callee.uid, {}).get(
                spec.rtype, frozenset()
            )
            name = None
            for i, a in enumerate(call.args):
                if isinstance(a, ast.Name) and a.id == var \
                        and i < len(params):
                    name = params[i]
            for kw in call.keywords:
                if isinstance(kw.value, ast.Name) and kw.value.id == var:
                    name = kw.arg
            if name is not None and name in summary:
                ok = True
        return ok

    def call_region_finalizes(self, fn: FuncInfo, call: ast.Call,
                              spec: ResourceSpec) -> bool:
        if call_name(call.func) in spec.region_finalizers:
            return True
        for callee in self.graph._resolve_name_or_attr(fn, call.func):
            if callee.uid in self.region_always.get(spec.rtype, ()):
                return True
        return False


def _name_in(expr: Optional[ast.AST], var: str) -> bool:
    if expr is None:
        return False
    return any(isinstance(n, ast.Name) and n.id == var
               for n in _walk_no_defs([expr]))


def _transfer(graph: ProjectGraph, fn: FuncInfo, cfg: CFG, n: int,
              spec: ResourceSpec, var: Optional[str],
              state: FrozenSet[str], summaries: Optional["Summaries"],
              collect: Optional[List[Violation]]) -> FrozenSet[str]:
    """One node's effect on one resource's state set.  ``var=None``
    tracks the whole *region* (only region finalizers apply)."""
    stmt = cfg.stmt_of[n]
    if var is not None and isinstance(stmt, ast.Assign) \
            and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name) \
            and stmt.targets[0].id == var:
        rebirth = _ctor_call_of(stmt.value, spec)
        if rebirth is not None:
            # re-running the creation (loop back edge): a FRESH
            # resource — the previous iteration's state must not bleed
            # into it
            return frozenset({OPEN if rebirth[1] else UNARMED})
    out = set(state)
    wvars = cfg.with_exit_vars.get(n)
    if wvars is not None:
        if var is not None and var in wvars and OPEN in out:
            out.discard(OPEN)
            out.add(CLOSED)
        return frozenset(out)
    if stmt is None:
        return frozenset(out)
    for call in node_calls(cfg, n):
        cn = call_name(call.func)
        recv = receiver_of(call.func)
        on_var = var is not None and isinstance(recv, ast.Name) \
            and recv.id == var
        if spec.region_finalizers and summaries is not None \
                and summaries.call_region_finalizes(fn, call, spec):
            if OPEN in out:
                out.discard(OPEN)
                out.add(CLOSED)
            continue
        if on_var:
            if cn in spec.finalizers:
                out.discard(OPEN)
                out.discard(UNARMED)
                out.add(CLOSED)
            elif cn in spec.arm_methods:
                if UNARMED in out:
                    out.discard(UNARMED)
                    out.add(OPEN)
            elif cn in spec.uses and CLOSED in out and collect is not None:
                collect.append(Violation(
                    "use_after_finalize", spec, var, call.lineno,
                    f"{var}.{cn}() is reachable after "
                    f"{var}.{spec.finalizers[0]}()",
                ))
            continue
        if var is not None and any(
            _name_in(a, var) for a in list(call.args)
            + [kw.value for kw in call.keywords]
        ):
            fin = summaries.call_finalizes_arg(fn, call, spec, var) \
                if summaries is not None else None
            if fin:
                out.discard(OPEN)
                out.discard(UNARMED)
                out.add(CLOSED)
            elif fin is None and spec.allow_escape and OPEN in out:
                out.discard(OPEN)
                out.add(ESCAPED)
            # resolved callee that does NOT finalize: state unchanged
    if var is not None and stmt is not None:
        # ownership transfers: return/yield, alias, store into a field
        if isinstance(stmt, ast.Return) and _name_in(stmt.value, var):
            out.discard(OPEN)
            out.add(ESCAPED)
        elif isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, (ast.Yield, ast.YieldFrom)
        ) and _name_in(stmt.value, var):
            out.discard(OPEN)
            out.add(ESCAPED)
        elif isinstance(stmt, ast.Assign) and _name_in(stmt.value, var) \
                and not isinstance(stmt.value, ast.Call):
            out.discard(OPEN)
            out.add(ESCAPED)
        elif isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == var for t in stmt.targets
        ) and _ctor_call_of(stmt.value, spec) is None:
            # rebound to something else: the old binding is gone
            out.discard(OPEN)
            out.add(ESCAPED)
    return frozenset(out)


def _flow(graph: ProjectGraph, fn: FuncInfo, cfg: CFG, spec: ResourceSpec,
          var: Optional[str], start_nodes: Sequence[int],
          summaries: Optional["Summaries"],
          collect: Optional[List[Violation]],
          init_state: FrozenSet[str] = frozenset({OPEN}),
          ) -> Dict[int, FrozenSet[str]]:
    """Worklist union-dataflow of one resource's states over the CFG.
    Returns the IN-state per node (EXIT's in-state is the verdict)."""
    in_states: Dict[int, FrozenSet[str]] = {}
    out_states: Dict[int, FrozenSet[str]] = {}
    work: List[int] = []
    for s in start_nodes:
        out_states[s] = init_state
        work.extend(cfg.succ[s])
    seen_pairs: Set[Tuple[int, FrozenSet[str]]] = set()
    while work:
        n = work.pop()
        preds_in = frozenset().union(*(
            out_states.get(p, frozenset()) for p in _preds_of(cfg, n)
        )) if _preds_of(cfg, n) else frozenset()
        if not preds_in:
            continue
        if in_states.get(n) == preds_in and n in out_states:
            continue
        in_states[n] = preds_in
        key = (n, preds_in)
        if key in seen_pairs:
            continue
        seen_pairs.add(key)
        out = _transfer(graph, fn, cfg, n, spec, var, preds_in,
                        summaries, None)
        if out_states.get(n) != out:
            out_states[n] = out
            work.extend(cfg.succ[n])
    if collect is not None:
        # one reporting pass with the converged states, deduped
        for n, state in sorted(in_states.items()):
            _transfer(graph, fn, cfg, n, spec, var, state, summaries,
                      collect)
    return in_states


_PRED_CACHE: Dict[int, List[Set[int]]] = {}


def _preds_of(cfg: CFG, n: int) -> Set[int]:
    preds = _PRED_CACHE.get(id(cfg))
    if preds is None or len(preds) != len(cfg.stmt_of):
        preds = cfg.preds()
        _PRED_CACHE[id(cfg)] = preds
    return preds[n]


def check_function(graph: ProjectGraph, fn: FuncInfo, spec: ResourceSpec,
                   summaries: Summaries) -> List[Violation]:
    """Every typestate violation for ``spec`` resources ``fn`` owns."""
    out: List[Violation] = []
    resources = local_resources(graph, fn, spec)
    if not resources:
        return out
    cfg = build_cfg(fn.node)
    for var, stmt, ctor_call, armed in resources:
        creation_nodes = cfg.nodes_of.get(id(stmt), [])
        if not creation_nodes:
            continue
        init = frozenset({OPEN if armed else UNARMED})
        seen: Set[Tuple[str, str, int]] = set()
        for cn in creation_nodes:
            viol: List[Violation] = []
            states = _flow(graph, fn, cfg, spec, var, (cn,), summaries,
                           viol, init_state=init)
            exit_states = states.get(CFG.EXIT, frozenset())
            if OPEN in exit_states:
                viol.append(Violation(
                    "leak", spec, var, stmt.lineno,
                    f"{spec.rtype} {var!r} is created here but some "
                    f"exit path never calls "
                    f"{'/'.join(spec.finalizers)}"
                    + (f" (or {'/'.join(spec.region_finalizers)})"
                       if spec.region_finalizers else ""),
                ))
            for v in viol:
                key = (v.kind, v.var, v.line)
                if key not in seen:
                    seen.add(key)
                    out.append(v)
    return out


# ---------------------------------------------------------------------------
# Path queries (R11)
# ---------------------------------------------------------------------------

def must_pass(cfg: CFG, target: int, blockers: Set[int]) -> bool:
    """True iff every ENTRY->``target`` path crosses some blocker node
    (collective dominance — any-of, which a plain dominator tree can't
    answer)."""
    if target in blockers:
        return True
    seen = {CFG.ENTRY}
    stack = [CFG.ENTRY]
    while stack:
        n = stack.pop()
        if n == target:
            return False
        for s in cfg.succ[n]:
            if s not in seen and s not in blockers:
                seen.add(s)
                stack.append(s)
    return True


def may_pending(cfg: CFG, gen: Set[int], kill: Set[int],
                queries: Set[int]) -> Set[int]:
    """Query nodes reachable with the gen/kill bit still set — e.g.
    a submit (gen) not yet drained (kill) when a save (query) runs.
    The bit is evaluated on the state ENTERING the query node, so a
    node that both drains and saves is clean."""
    pending_in: Set[int] = set()
    work: List[int] = []
    for g in gen:
        for s in cfg.succ[g]:
            if s not in kill and s not in pending_in:
                pending_in.add(s)
                work.append(s)
    while work:
        n = work.pop()
        if n in kill:
            continue
        for s in cfg.succ[n]:
            if s not in pending_in:
                pending_in.add(s)
                work.append(s)
    return queries & (pending_in | set(gen))
