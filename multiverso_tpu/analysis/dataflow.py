"""Interprocedural dataflow engine for mvlint (rules R6-R9 + R1 v2).

PR 8's rules resolved calls by *name* within one module — enough for the
lexical rules, but blind to exactly the bug classes this repo has paid
for interprocedurally (the PR 6 cross-thread dispatch deadlock crossed a
``self._pipe = TaskPipe(...)`` binding; the PR 5 donated-snapshot alias
crossed a ``self._step = jax.jit(..., donate_argnums=...)`` binding).
This module builds the repo-wide facts those rules need:

* a **module graph**: every scanned file keyed by its dotted module
  name, with per-module import tables (``import x.y as z`` /
  ``from x import y as z``) resolved against the scanned set;
* a **class index**: methods (through scanned base classes), plus
  **attribute type bindings** inferred from ``self._x = ClassName(...)``
  and ``self._x = jax.jit(...)``-style assignments anywhere in the
  class — the ``self._x = Thread(...)`` idiom the issue names;
* **local variable bindings** per function (``t = KVTable(...)`` makes
  ``t.get`` resolve to ``KVTable.get``);
* a **call graph** over all of it, with a documented resolution order
  (local scope, ``self``, typed receivers, imports, then a
  *unique-name* fallback: an unqualified method name resolves globally
  only when exactly one scanned definition carries it — which is what
  retires R1's hand-kept ambiguous-name exclusion list: ``get``/``add``
  now propagate through **typed** receivers and nothing else);
* **fixpoint reachability** queries with memoisation and cycle
  tolerance (``reaches``, ``reachable_set``);
* **thread entry discovery**: ``Thread(target=...)`` targets,
  ``ASyncBuffer`` fill actions, and closures submitted to ``TaskPipe``
  (``.submit``/``.submit_nowait``) — the inputs R1 v2 and R9 share.

Everything is pure-``ast``; nothing here imports the code under
analysis.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from multiverso_tpu.analysis.mvlint import Module

__all__ = [
    "FuncInfo",
    "ClassInfo",
    "ProjectGraph",
    "call_name",
    "receiver_of",
]

# constructor names that bind a *synchronization primitive* — R9 treats
# attributes holding these as safe to touch cross-thread (they carry
# their own locking), and R2's lock regex already covers the lock-ish
SYNC_PRIMITIVE_TYPES = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "OrderedLock", "TaskPipe", "ASyncBuffer", "local",
}

# thread-spawning constructors: (ctor name, how the entry is passed)
_THREAD_CTORS = {"Thread"}
_PIPE_SUBMIT_METHODS = {"submit", "submit_nowait"}

# Method names carried by builtin containers / files / sync primitives.
# The unique-name fallback must NEVER resolve an untyped ``x.items()``
# to a scanned def: ``state.items()`` on a plain dict would link to
# ``KVTable.items`` the moment the repo holds exactly one ``items``
# def. Typed receivers are unaffected — ``self._t.get(...)`` with
# ``self._t = KVTable(...)`` still resolves — which is precisely the
# improvement over the retired AMBIGUOUS_DISPATCH_NAMES hand-list: the
# generic names propagate through *evidence*, never through luck.
BUILTIN_METHOD_NAMES: Set[str] = set()
for _t in (dict, list, set, tuple, str, bytes, frozenset):
    BUILTIN_METHOD_NAMES.update(
        n for n in dir(_t) if not n.startswith("__")
    )
BUILTIN_METHOD_NAMES |= {
    "close", "flush", "read", "write", "readline", "readlines", "seek",
    "tell", "open", "start", "run", "is_alive", "put", "get_nowait",
    "put_nowait", "qsize", "empty", "full", "task_done",
    "acquire", "release", "locked", "wait", "notify", "notify_all",
    "set", "clear", "is_set", "submit", "result", "cancel", "done",
    "send", "recv", "connect", "bind", "listen", "accept", "shutdown",
}


def call_name(func: ast.AST) -> str:
    """Rightmost name of a call target (``a.b.c()`` -> ``c``)."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def receiver_of(func: ast.AST) -> Optional[ast.AST]:
    return func.value if isinstance(func, ast.Attribute) else None


def _dotted(node: ast.AST) -> str:
    """``a.b.c`` attribute chains as text; "" when not a pure chain."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


class FuncInfo:
    """One function/method/lambda in the scanned universe."""

    __slots__ = ("module", "cls", "name", "node", "uid")

    def __init__(self, module: Module, cls: str, name: str, node: ast.AST):
        self.module = module
        self.cls = cls  # "" for module-level
        self.name = name
        self.node = node
        self.uid = id(node)

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name

    def __repr__(self) -> str:  # debugging/messages only
        return f"<{self.module.relpath}::{self.qualname}>"


class ClassInfo:
    __slots__ = ("name", "module", "node", "bases", "methods",
                 "attr_types")

    def __init__(self, name: str, module: Module, node: ast.ClassDef):
        self.name = name
        self.module = module
        self.node = node
        self.bases: List[str] = []  # textual base refs, resolved lazily
        self.methods: Dict[str, FuncInfo] = {}
        # attr -> set of bound constructor/type names observed anywhere
        # in the class body ("Thread", "TaskPipe", "jit", ...)
        self.attr_types: Dict[str, Set[str]] = {}


def _module_dotted_name(relpath: str) -> str:
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    p = p.replace("/", ".")
    if p.endswith(".__init__"):
        p = p[: -len(".__init__")]
    return p


class ProjectGraph:
    """Repo-wide call graph + binding facts over a set of ``Module``s."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self.by_name: Dict[str, Module] = {
            _module_dotted_name(m.relpath): m for m in self.modules
        }
        # (module relpath, class name) -> ClassInfo; plus name -> [infos]
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        # per-module import table: local alias -> dotted target
        self.imports: Dict[str, Dict[str, str]] = {}
        # module-level functions: (module relpath, name) -> FuncInfo
        self.mod_funcs: Dict[Tuple[str, str], FuncInfo] = {}
        # global name -> all defs carrying it (unique-name fallback)
        self._defs_by_name: Dict[str, List[FuncInfo]] = {}
        # every FuncInfo by node id (incl. nested + lambdas-on-demand)
        self.funcs: Dict[int, FuncInfo] = {}
        # function uid -> enclosing FuncInfo uid (closure scope chain)
        self._parent: Dict[int, int] = {}
        self._callee_cache: Dict[int, Tuple[FuncInfo, ...]] = {}
        self._local_cache: Dict[int, Dict[str, Set[str]]] = {}
        for m in self.modules:
            self._index_module(m)
        self._link_bases()

    # --------------------------------------------------------- indexing

    def _index_module(self, m: Module) -> None:
        imp: Dict[str, str] = {}
        self.imports[m.relpath] = imp
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imp[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    imp[a.asname or a.name] = f"{node.module}.{a.name}"

        def visit(node: ast.AST, cls: Optional[ClassInfo],
                  parent_fn: Optional[FuncInfo]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    ci = ClassInfo(child.name, m, child)
                    for b in child.bases:
                        ref = _dotted(b)
                        if ref:
                            ci.bases.append(ref)
                    self.classes[(m.relpath, child.name)] = ci
                    self.classes_by_name.setdefault(
                        child.name, []
                    ).append(ci)
                    visit(child, ci, parent_fn)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    fi = FuncInfo(
                        m, cls.name if cls else "", child.name, child
                    )
                    self.funcs[fi.uid] = fi
                    if parent_fn is not None:
                        self._parent[fi.uid] = parent_fn.uid
                    if cls is not None and parent_fn is None:
                        cls.methods.setdefault(child.name, fi)
                    elif cls is None and parent_fn is None:
                        self.mod_funcs[(m.relpath, child.name)] = fi
                    self._defs_by_name.setdefault(
                        child.name, []
                    ).append(fi)
                    visit(child, cls, fi)
                else:
                    visit(child, cls, parent_fn)

        visit(m.tree, None, None)

        # attribute type bindings: self.X = Ctor(...) anywhere in a class
        for (relpath, _cname), ci in list(self.classes.items()):
            if relpath != m.relpath:
                continue
            for node in ast.walk(ci.node):
                tgt = None
                val = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    tgt, val = node.target, node.value
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                for t in self._value_type_names(val):
                    ci.attr_types.setdefault(tgt.attr, set()).add(t)

    @staticmethod
    def _value_type_names(val: Optional[ast.AST]) -> List[str]:
        """Constructor-ish names an assigned value binds (``Thread(...)``
        -> Thread; ``jax.jit(...)`` -> jit; ``Foo(...).start()`` -> Foo
        — the ``.start()`` fluent idiom must not hide the type)."""
        out: List[str] = []
        if isinstance(val, ast.Call):
            n = call_name(val.func)
            if n in ("start", "result"):  # fluent: Foo(...).start()
                recv = receiver_of(val.func)
                if isinstance(recv, ast.Call):
                    n = call_name(recv.func)
            if n:
                out.append(n)
        return out

    def _link_bases(self) -> None:
        """Resolve each class's textual base refs to ClassInfos once."""
        self._base_infos: Dict[Tuple[str, str], List[ClassInfo]] = {}
        for key, ci in self.classes.items():
            resolved: List[ClassInfo] = []
            for ref in ci.bases:
                leaf = ref.split(".")[-1]
                target = self._resolve_class(ci.module, leaf) or \
                    (self.classes_by_name.get(leaf) or [None])[0]
                if target is not None:
                    resolved.append(target)
            self._base_infos[key] = resolved

    # ------------------------------------------------------- resolution

    def _resolve_class(self, m: Module, name: str) -> Optional[ClassInfo]:
        ci = self.classes.get((m.relpath, name))
        if ci is not None:
            return ci
        dotted = self.imports.get(m.relpath, {}).get(name)
        if dotted:
            modname, _, leaf = dotted.rpartition(".")
            target = self.by_name.get(modname)
            if target is not None:
                return self.classes.get((target.relpath, leaf))
            # ``from multiverso_tpu.tables import KVTable`` re-export:
            # fall through to the global registry by leaf name
            hits = self.classes_by_name.get(leaf, [])
            if len(hits) == 1:
                return hits[0]
        return None

    def lookup_method(self, ci: ClassInfo, name: str,
                      _seen: Optional[Set[int]] = None
                      ) -> Optional[FuncInfo]:
        """Method resolution through scanned bases (C3-ish, depth-first
        in declaration order — enough for this repo's single-inheritance
        trees)."""
        seen = _seen if _seen is not None else set()
        if id(ci) in seen:
            return None
        seen.add(id(ci))
        fi = ci.methods.get(name)
        if fi is not None:
            return fi
        for base in self._base_infos.get((ci.module.relpath, ci.name), ()):
            fi = self.lookup_method(base, name, seen)
            if fi is not None:
                return fi
        return None

    def class_of_func(self, fn: FuncInfo) -> Optional[ClassInfo]:
        if not fn.cls:
            return None
        return self.classes.get((fn.module.relpath, fn.cls))

    def _local_bindings(self, fn: FuncInfo) -> Dict[str, Set[str]]:
        """var name -> constructor names bound inside this function."""
        cached = self._local_cache.get(fn.uid)
        if cached is not None:
            return cached
        out: Dict[str, Set[str]] = {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                for t in self._value_type_names(node.value):
                    out.setdefault(node.targets[0].id, set()).add(t)
        self._local_cache[fn.uid] = out
        return out

    def receiver_types(self, fn: FuncInfo, recv: ast.AST) -> List[ClassInfo]:
        """Scanned classes a call receiver may be an instance of."""
        names: Set[str] = set()
        if isinstance(recv, ast.Attribute) and isinstance(
            recv.value, ast.Name
        ) and recv.value.id == "self" and fn.cls:
            ci = self.class_of_func(fn)
            search: List[ClassInfo] = []
            if ci is not None:
                search = [ci] + self._base_infos.get(
                    (ci.module.relpath, ci.name), []
                )
            for c in search:
                names |= c.attr_types.get(recv.attr, set())
        elif isinstance(recv, ast.Name):
            names |= self._local_bindings(fn).get(recv.id, set())
        out: List[ClassInfo] = []
        for n in sorted(names):
            ci = self._resolve_class(fn.module, n)
            if ci is None:
                hits = self.classes_by_name.get(n, [])
                ci = hits[0] if len(hits) == 1 else None
            if ci is not None:
                out.append(ci)
        return out

    def resolve_callable_ref(self, fn: FuncInfo,
                             target: ast.AST) -> List[FuncInfo]:
        """Resolve a *reference* to a callable (a ``target=`` kwarg, a
        submitted closure) — not a call."""
        if isinstance(target, ast.Lambda):
            fi = self.funcs.get(id(target))
            if fi is None:
                fi = FuncInfo(fn.module, fn.cls, "<lambda>", target)
                self.funcs[fi.uid] = fi
                self._parent[fi.uid] = fn.uid
            return [fi]
        if isinstance(target, ast.Call):
            # functools.partial(f, ...) / wraps: resolve the first arg
            if call_name(target.func) == "partial" and target.args:
                return self.resolve_callable_ref(fn, target.args[0])
            return []
        return self._resolve_name_or_attr(fn, target)

    def _resolve_name_or_attr(self, fn: FuncInfo,
                              target: ast.AST) -> List[FuncInfo]:
        if isinstance(target, ast.Name):
            name = target.id
            # closure scope chain: nested def in this or enclosing fns
            cur: Optional[FuncInfo] = fn
            while cur is not None:
                for child in ast.walk(cur.node):
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and child.name == name and id(child) in self.funcs:
                        return [self.funcs[id(child)]]
                cur = self.funcs.get(self._parent.get(cur.uid, -1))
            mf = self.mod_funcs.get((fn.module.relpath, name))
            if mf is not None:
                return [mf]
            dotted = self.imports.get(fn.module.relpath, {}).get(name)
            if dotted:
                hit = self._resolve_dotted(dotted)
                if hit is not None:
                    return [hit]
            ci = self._resolve_class(fn.module, name)
            if ci is not None:  # constructor call -> __init__
                init = self.lookup_method(ci, "__init__")
                return [init] if init is not None else []
            # unique-name fallback (see module docstring)
            hits = self._defs_by_name.get(name, [])
            return [hits[0]] if len(hits) == 1 else []
        if isinstance(target, ast.Attribute):
            recv = target.value
            meth = target.attr
            if isinstance(recv, ast.Name) and recv.id == "self" and fn.cls:
                ci = self.class_of_func(fn)
                if ci is not None:
                    hit = self.lookup_method(ci, meth)
                    if hit is not None:
                        return [hit]
                return []
            if isinstance(recv, ast.Call) and call_name(recv.func) == \
                    "super" and fn.cls:
                ci = self.class_of_func(fn)
                if ci is not None:
                    for base in self._base_infos.get(
                        (ci.module.relpath, ci.name), ()
                    ):
                        hit = self.lookup_method(base, meth)
                        if hit is not None:
                            return [hit]
                return []
            for ci in self.receiver_types(fn, recv):
                hit = self.lookup_method(ci, meth)
                if hit is not None:
                    return [hit]
            # module-qualified: mod.func()
            ref = _dotted(recv)
            if ref:
                dotted = self.imports.get(fn.module.relpath, {}).get(
                    ref.split(".")[0]
                )
                if dotted:
                    full = dotted + ref[len(ref.split(".")[0]):] + \
                        "." + meth
                    hit = self._resolve_dotted(full)
                    if hit is not None:
                        return [hit]
                cls = self._resolve_class(fn.module, ref)
                if cls is not None:  # ClassName.meth
                    hit = self.lookup_method(cls, meth)
                    if hit is not None:
                        return [hit]
            # unique-name fallback for unknown receivers: propagate only
            # when the name is unambiguous repo-wide AND is not a
            # builtin-container method (an untyped ``x.items()`` is a
            # dict far more often than the one scanned ``items`` def;
            # typed receivers above already handled the real one)
            if meth in BUILTIN_METHOD_NAMES:
                return []
            hits = self._defs_by_name.get(meth, [])
            return [hits[0]] if len(hits) == 1 else []
        return []

    def _resolve_dotted(self, dotted: str) -> Optional[FuncInfo]:
        modname, _, leaf = dotted.rpartition(".")
        m = self.by_name.get(modname)
        if m is not None:
            fi = self.mod_funcs.get((m.relpath, leaf))
            if fi is not None:
                return fi
            ci = self.classes.get((m.relpath, leaf))
            if ci is not None:
                return self.lookup_method(ci, "__init__")
        return None

    # ------------------------------------------------------- call graph

    def own_nodes(self, fn: FuncInfo,
                  root: Optional[ast.AST] = None) -> Iterable[ast.AST]:
        """Nodes lexically inside ``fn`` (or ``root``), NOT descending
        into nested defs that carry their own FuncInfo — defining a
        closure is not executing it (the thread boundary R1/R6/R9 all
        hinge on). Lambdas have no indexed FuncInfo, so their bodies
        stay attributed to the enclosing function."""
        start = root if root is not None else fn.node
        stack: List[ast.AST] = [start]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and child is not start and id(child) in self.funcs:
                    continue
                stack.append(child)

    def callees(self, fn: FuncInfo) -> Tuple[FuncInfo, ...]:
        """Functions this one may CALL on its own thread of execution:
        resolved calls in its own nodes, plus nested defs it invokes by
        name (already covered — a called nested def resolves through the
        closure scope chain)."""
        cached = self._callee_cache.get(fn.uid)
        if cached is not None:
            return cached
        out: List[FuncInfo] = []
        seen: Set[int] = set()
        for node in self.own_nodes(fn):
            if isinstance(node, ast.Call):
                for hit in self._resolve_name_or_attr(fn, node.func):
                    if hit.uid not in seen:
                        seen.add(hit.uid)
                        out.append(hit)
        result = tuple(out)
        self._callee_cache[fn.uid] = result
        return result

    def reachable_set(self, roots: Iterable[FuncInfo]) -> Set[int]:
        """uids of every function reachable from ``roots`` (inclusive)."""
        out: Set[int] = set()
        stack = list(roots)
        while stack:
            fn = stack.pop()
            if fn.uid in out:
                continue
            out.add(fn.uid)
            stack.extend(self.callees(fn))
        return out

    def reachers_of(self, sink_uids: Set[int]) -> Set[int]:
        """uids of every function from which some sink is reachable
        (sinks included) — one reverse-BFS over the whole graph, so
        rules can test membership instead of re-walking per call site."""
        rev: Dict[int, List[int]] = {}
        for fn in list(self.funcs.values()):
            for callee in self.callees(fn):
                rev.setdefault(callee.uid, []).append(fn.uid)
        out: Set[int] = set()
        stack = [u for u in sink_uids]
        while stack:
            uid = stack.pop()
            if uid in out:
                continue
            out.add(uid)
            stack.extend(rev.get(uid, ()))
        return out

    def calls_in(self, fn: FuncInfo, node: Optional[ast.AST] = None
                 ) -> List[Tuple[ast.Call, List[FuncInfo]]]:
        """(call node, resolved callees) for every call lexically inside
        ``node`` (default: the whole function), own nodes only."""
        out = []
        for n in self.own_nodes(fn, node):
            if isinstance(n, ast.Call):
                out.append((n, self._resolve_name_or_attr(fn, n.func)))
        return out

    # ---------------------------------------------------- thread entries

    def thread_entries(self) -> List[Tuple[FuncInfo, ast.Call, str, FuncInfo]]:
        """Every (spawning fn, spawn call, kind, entry fn) in the scan:
        ``Thread(target=...)``, ``ASyncBuffer(fill)``, and closures
        handed to ``TaskPipe.submit``/``submit_nowait``. The TaskPipe
        worker is the *sanctioned* collective channel (R1 allows it) but
        R9 still needs to know its closures run off-thread."""
        out: List[Tuple[FuncInfo, ast.Call, str, FuncInfo]] = []
        for fn in list(self.funcs.values()):
            if isinstance(fn.node, ast.Lambda):
                continue
            for node in self.own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node.func)
                target: Optional[ast.AST] = None
                kind = ""
                if cname in _THREAD_CTORS:
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                            kind = "thread_target"
                elif cname == "ASyncBuffer":
                    if node.args:
                        target = node.args[0]
                    for kw in node.keywords:
                        if kw.arg == "fill_buffer_action":
                            target = kw.value
                    kind = "fill_action"
                elif cname in _PIPE_SUBMIT_METHODS and isinstance(
                    node.func, ast.Attribute
                ):
                    recv = receiver_of(node.func)
                    types = {c.name for c in self.receiver_types(
                        fn, recv
                    )} if recv is not None else set()
                    recv_text = _dotted(recv) if recv is not None else ""
                    if "TaskPipe" in types or "pipe" in recv_text.lower():
                        if node.args:
                            target = node.args[0]
                            kind = "pipe_submit"
                if target is None or not kind:
                    continue
                for entry in self.resolve_callable_ref(fn, target):
                    out.append((fn, node, kind, entry))
        return out
