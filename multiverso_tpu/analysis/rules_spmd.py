"""mvlint rules R6-R9 — the flow-sensitive SPMD/JAX rule pack.

Each rule here is ``(modules, config, graph) -> [Finding]`` and carries
``needs_graph = True``: the driver builds one
:class:`~multiverso_tpu.analysis.dataflow.ProjectGraph` per run and
hands it to every rule in this module. The four rules are the static
halves of bugs this repo has already paid for at runtime:

* **R6 rank-divergent-collective** — a call that can reach a collective
  (an ``@collective_dispatch`` entry point, a ``parallel/collectives``
  op, or a raw ``multihost_utils`` barrier) *inside a branch conditioned
  on the process rank*. Every rank must execute the identical collective
  sequence; ``if rank == 0: table.store(...)`` deadlocks ranks 1..n-1
  (the PR 6 incident class, generalized across calls).
* **R7 donation-aliasing** — a value handed to a ``donate_argnums``
  jitted callable (or ``device_put(..., donate=True)``) whose prior
  binding is read afterwards. Donated buffers are invalidated in place;
  the PR 5 zero-copy snapshot served garbage exactly this way.
* **R8 retrace-churn** — ``jax.jit`` constructed inside a loop, a
  per-round loop variable reaching a *static* jit argument, or argument
  shapes derived from the loop variable: each one recompiles every
  iteration (the PR 7 compile-cache churn class). A varying Python
  scalar at a *dynamic* position is fine — jax caches on
  shape/dtype/weak_type, not value — and is deliberately not flagged.
* **R9 unguarded-cross-thread-state** — ``self.X`` state with a
  read-modify-write on a thread path (``Thread`` target, ``ASyncBuffer``
  fill action, ``TaskPipe``-submitted closure) and any access from
  training-thread code, with no common lock on both sides. Single-store
  publication (``self._ready = True``) is GIL-atomic and stays legal;
  what fires is the lost-update shape the four hand-named runtime-
  guarded locks exist to prevent.

Approximations are documented per-rule in analysis/RULES.md; each errs
toward the runtime guards (:mod:`multiverso_tpu.analysis.guards`)
catching what static analysis cannot.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from multiverso_tpu.analysis.mvlint import Finding, LintConfig, Module
from multiverso_tpu.analysis.dataflow import (
    SYNC_PRIMITIVE_TYPES,
    ClassInfo,
    FuncInfo,
    ProjectGraph,
    call_name,
    receiver_of,
)

__all__ = [
    "rule_r6_rank_divergent_collective",
    "rule_r7_donation_aliasing",
    "rule_r8_retrace_churn",
    "rule_r9_cross_thread_state",
    "allow_region_node_ids",
    "SpmdFacts",
]

# ------------------------------------------------------- shared helpers

import re as _re

_LOCK_ATTR_RE = _re.compile(r"lock|mutex|_mu$|_cv$")

# jax collective/barrier entry points that live OUTSIDE the scanned tree
# but still block until every process arrives
EXTERNAL_COLLECTIVE_NAMES = {
    "sync_global_devices", "broadcast_one_to_all", "process_allgather",
    "assert_equal", "psum", "pmean", "pmax", "pmin", "all_gather",
    "all_to_all", "ppermute", "host_local_array_to_global_array",
    "global_array_to_host_local_array",
}

# rank-valued call/attribute spellings (jax.process_index(), runtime
# helpers, coordinator predicates)
_RANK_CALL_NAMES = {"process_index", "is_coordinator"}
_RANK_ATTR_NAMES = {"rank", "_rank", "process_index"}
_RANK_BARE_NAMES = {"rank", "is_coordinator"}

_JIT_NAMES = {"jit", "pjit"}


def _dotted_text(node: ast.AST) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def _has_dispatch_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if call_name(target) == "collective_dispatch":
            return True
    return False


def allow_region_node_ids(graph: ProjectGraph, fn: FuncInfo) -> Set[int]:
    """ids of every node lexically under a
    ``with allow_collective_dispatch(...)`` block in ``fn`` — the
    sanctioned sync-point escape hatch R1 and R6 both honor."""
    out: Set[int] = set()
    for node in graph.own_nodes(fn):
        if not isinstance(node, ast.With):
            continue
        if not any(
            isinstance(item.context_expr, ast.Call)
            and call_name(item.context_expr.func)
            == "allow_collective_dispatch"
            for item in node.items
        ):
            continue
        for stmt in node.body:
            for sub in ast.walk(stmt):
                out.add(id(sub))
    return out


class SpmdFacts:
    """Derived whole-program facts shared by R6-R9, computed lazily and
    cached on the graph (one graph per lint run)."""

    def __init__(self, graph: ProjectGraph):
        self.graph = graph
        self._collective_reachers: Optional[Set[int]] = None
        self._thread_uids: Optional[Set[int]] = None
        self._main_uids: Optional[Set[int]] = None
        self._entries: Optional[List[Tuple[FuncInfo, ast.Call, str, FuncInfo]]] = None

    # -- collectives ---------------------------------------------------

    def collective_sink_uids(self) -> Set[int]:
        g = self.graph
        sinks: Set[int] = set()
        for fn in list(g.funcs.values()):
            node = fn.node
            if _has_dispatch_decorator(node):
                sinks.add(fn.uid)
                continue
            if fn.module.relpath.endswith(
                "multiverso_tpu/parallel/collectives.py"
            ) and not fn.name.startswith("_"):
                sinks.add(fn.uid)
                continue
            for n in g.own_nodes(fn):
                if isinstance(n, ast.Call) and call_name(n.func) in \
                        EXTERNAL_COLLECTIVE_NAMES:
                    sinks.add(fn.uid)
                    break
        return sinks

    def collective_reachers(self) -> Set[int]:
        """uids of every function from which a collective is reachable."""
        if self._collective_reachers is None:
            self._collective_reachers = self.graph.reachers_of(
                self.collective_sink_uids()
            )
        return self._collective_reachers

    # -- thread sides --------------------------------------------------

    def thread_entries(self):
        if self._entries is None:
            self._entries = self.graph.thread_entries()
        return self._entries

    def thread_uids(self) -> Set[int]:
        """Everything reachable from a thread entry (the entry's code
        runs OFF the spawning thread)."""
        if self._thread_uids is None:
            self._thread_uids = self.graph.reachable_set(
                entry for _fn, _call, _kind, entry in self.thread_entries()
            )
        return self._thread_uids

    def main_uids(self) -> Set[int]:
        """Everything reachable without crossing a thread spawn: roots
        are all functions that are not already thread-side. A helper
        called from BOTH (``poll_once`` from the fleet watch thread and
        from ``wait_ready`` on main) lands in both sets — that is the
        dual-use shape R9 exists for."""
        if self._main_uids is None:
            tuids = self.thread_uids()
            roots = [
                fn for fn in self.graph.funcs.values()
                if fn.uid not in tuids
            ]
            self._main_uids = self.graph.reachable_set(roots)
        return self._main_uids


def spmd_facts(graph: ProjectGraph) -> SpmdFacts:
    facts = getattr(graph, "_spmd_facts", None)
    if facts is None:
        facts = SpmdFacts(graph)
        graph._spmd_facts = facts
    return facts


def _iter_funcs(graph: ProjectGraph,
                modules: Sequence[Module]) -> List[FuncInfo]:
    """FuncInfos belonging to the linted module set, def-ordered."""
    rels = {m.relpath for m in modules}
    return [
        fn for fn in graph.funcs.values()
        if fn.module.relpath in rels
        and not isinstance(fn.node, ast.Lambda)
    ]


# ------------------------------------------------------------------- R6

def _rank_tainted_names(graph: ProjectGraph, fn: FuncInfo) -> Set[str]:
    """Local names bound (directly) to a rank value: ``rank =
    jax.process_index()``, tuple-aligned where possible."""
    tainted: Set[str] = set()

    def value_is_rank(val: ast.AST) -> bool:
        for n in ast.walk(val):
            if isinstance(n, ast.Call) and call_name(n.func) in \
                    _RANK_CALL_NAMES:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _RANK_ATTR_NAMES:
                return True
        return False

    for node in graph.own_nodes(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt, val = node.targets[0], node.value
        if isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) \
                and len(tgt.elts) == len(val.elts):
            # rank, world = process_index(), process_count(): taint only
            # the aligned element — ``world`` must NOT become rank-ish
            for t, v in zip(tgt.elts, val.elts):
                if isinstance(t, ast.Name) and value_is_rank(v):
                    tainted.add(t.id)
        elif isinstance(tgt, ast.Name) and value_is_rank(val):
            tainted.add(tgt.id)
    return tainted


def _test_is_rank_conditioned(test: ast.AST, tainted: Set[str]) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Call) and call_name(n.func) in \
                _RANK_CALL_NAMES:
            return True
        if isinstance(n, ast.Name) and (
            n.id in tainted or n.id in _RANK_BARE_NAMES
        ):
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANK_ATTR_NAMES:
            return True
    return False


def _own_blocks(graph: ProjectGraph,
                fn: FuncInfo) -> Iterable[List[ast.stmt]]:
    """Every statement list lexically owned by ``fn`` (not descending
    into nested indexed defs)."""

    def rec(stmts: List[ast.stmt]) -> Iterable[List[ast.stmt]]:
        yield stmts
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and id(s) in graph.funcs:
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(s, field, None)
                if sub:
                    yield from rec(sub)
            for h in getattr(s, "handlers", ()):
                yield from rec(h.body)

    body = getattr(fn.node, "body", None)
    if isinstance(body, list):
        yield from rec(body)


def _terminates(stmts: List[ast.stmt]) -> bool:
    return any(
        isinstance(s, (ast.Return, ast.Raise, ast.Break, ast.Continue))
        for s in stmts
    )


def rule_r6_rank_divergent_collective(
    modules: Sequence[Module], cfg: LintConfig, graph: ProjectGraph
) -> List[Finding]:
    facts = spmd_facts(graph)
    reach = facts.collective_reachers()
    findings: List[Finding] = []
    for fn in _iter_funcs(graph, modules):
        tainted = _rank_tainted_names(graph, fn)
        allowed = allow_region_node_ids(graph, fn)
        regions: List[Tuple[int, List[ast.stmt]]] = []
        for block in _own_blocks(graph, fn):
            for i, stmt in enumerate(block):
                if not isinstance(stmt, ast.If):
                    continue
                if not _test_is_rank_conditioned(stmt.test, tainted):
                    continue
                regions.append((stmt.lineno, stmt.body))
                if stmt.orelse:
                    regions.append((stmt.lineno, stmt.orelse))
                elif _terminates(stmt.body):
                    # ``if rank != 0: return`` — everything after the
                    # guard runs on one side of the rank split too
                    rest = block[i + 1:]
                    if rest:
                        regions.append((stmt.lineno, rest))
        if not regions:
            continue
        seen: Set[int] = set()
        for guard_line, stmts in regions:
            for stmt in stmts:
                for call, hits in graph.calls_in(fn, stmt):
                    if id(call) in seen or id(call) in allowed:
                        continue
                    target = ""
                    if any(h.uid in reach for h in hits):
                        target = " / ".join(sorted(
                            h.qualname for h in hits if h.uid in reach
                        ))
                    elif call_name(call.func) in EXTERNAL_COLLECTIVE_NAMES:
                        target = call_name(call.func)
                    if not target:
                        continue
                    seen.add(id(call))
                    findings.append(Finding(
                        "R6", fn.module.relpath, call.lineno,
                        f"collective {target} is reachable inside a "
                        f"rank-conditioned branch (guard at line "
                        f"{guard_line}) — ranks that skip the branch "
                        "never post the matching collective "
                        "(SPMD desync/deadlock)",
                        "hoist the collective above the rank gate (the "
                        "store()/quorum idiom: every rank gathers, only "
                        "rank 0 touches the filesystem), or wrap a "
                        "documented sync point in "
                        "allow_collective_dispatch(reason)",
                    ))
    return findings


rule_r6_rank_divergent_collective.needs_graph = True


# ------------------------------------------------------------------- R7

def _donate_spec(call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
    """(positions, argnames) donated by a jit/pjit construction call."""
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums.extend(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                )
        elif kw.arg == "donate_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names.extend(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                )
    return tuple(nums), tuple(names)


def _static_spec(call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums.extend(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                )
        elif kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names.extend(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                )
    return tuple(nums), tuple(names)


class _JitRegistry:
    """Where jitted callables live: ``self.X = jax.jit(...)`` class
    attributes, ``fn = jax.jit(...)`` locals, decorated defs, and
    helpers that *return* a jitted callable. Each entry carries its
    donate and static specs."""

    def __init__(self, graph: ProjectGraph, modules: Sequence[Module]):
        self.graph = graph
        # (module relpath, class, attr) -> spec
        self.attr: Dict[Tuple[str, str, str], Tuple] = {}
        # (fn uid, local name) -> spec
        self.local: Dict[Tuple[int, str], Tuple] = {}
        # def uid -> spec (decorated with @partial(jit, ...))
        self.direct: Dict[int, Tuple] = {}
        # helper uid -> spec (returns a jitted callable)
        self.returns: Dict[int, Tuple] = {}
        self._build(modules)

    def _build(self, modules: Sequence[Module]) -> None:
        g = self.graph
        rels = {m.relpath for m in modules}
        for fn in g.funcs.values():
            if fn.module.relpath not in rels:
                continue
            # decorators: @partial(jax.jit, ...) / @jax.jit
            for dec in getattr(fn.node, "decorator_list", ()):
                if isinstance(dec, ast.Call):
                    if call_name(dec.func) == "partial" and dec.args and \
                            call_name(dec.args[0]) in _JIT_NAMES:
                        self.direct[fn.uid] = self._spec_of(dec)
                    elif call_name(dec.func) in _JIT_NAMES:
                        self.direct[fn.uid] = self._spec_of(dec)
            if isinstance(fn.node, ast.Lambda):
                continue
            jit_locals: Dict[str, Tuple] = {}
            for node in g.own_nodes(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                    spec = self._jit_value_spec(val)
                    if spec is None:
                        continue
                    if isinstance(tgt, ast.Name):
                        jit_locals[tgt.id] = spec
                        self.local[(fn.uid, tgt.id)] = spec
                    elif isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self" and fn.cls:
                        self.attr[
                            (fn.module.relpath, fn.cls, tgt.attr)
                        ] = spec
                elif isinstance(node, ast.Return) and node.value is not None:
                    spec = self._jit_value_spec(node.value)
                    if spec is None and isinstance(node.value, ast.Name):
                        spec = jit_locals.get(node.value.id)
                    if spec is not None:
                        self.returns[fn.uid] = spec

    @staticmethod
    def _spec_of(call: ast.Call) -> Tuple:
        return _donate_spec(call) + _static_spec(call)

    def _jit_value_spec(self, val: ast.AST) -> Optional[Tuple]:
        if isinstance(val, ast.Call) and call_name(val.func) in _JIT_NAMES:
            return self._spec_of(val)
        return None

    def spec_for_call(self, fn: FuncInfo,
                      call: ast.Call) -> Optional[Tuple]:
        """Donate/static spec when ``call`` invokes a known jitted
        callable; None otherwise."""
        func = call.func
        if isinstance(func, ast.Call):
            # helper()(args): helper returns a jitted callable
            for hit in self.graph._resolve_name_or_attr(fn, func.func):
                spec = self.returns.get(hit.uid)
                if spec is not None:
                    return spec
            if call_name(func.func) in _JIT_NAMES:
                return self._spec_of(func)  # jax.jit(f)(args) inline
            return None
        if isinstance(func, ast.Name):
            # walk the closure chain for the binding
            cur: Optional[FuncInfo] = fn
            while cur is not None:
                spec = self.local.get((cur.uid, func.id))
                if spec is not None:
                    return spec
                cur = self.graph.funcs.get(
                    self.graph._parent.get(cur.uid, -1)
                )
        elif isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ) and func.value.id == "self" and fn.cls:
            ci = self.graph.class_of_func(fn)
            if ci is not None:
                search = [ci] + self.graph._base_infos.get(
                    (ci.module.relpath, ci.name), []
                )
                for c in search:
                    spec = self.attr.get(
                        (c.module.relpath, c.name, func.attr)
                    )
                    if spec is not None:
                        return spec
        for hit in self.graph._resolve_name_or_attr(fn, func):
            spec = self.direct.get(hit.uid)
            if spec is not None:
                return spec
        return None


def _r7_donated_exprs(reg: _JitRegistry, fn: FuncInfo,
                      call: ast.Call) -> List[str]:
    """Texts of the value bindings this call donates."""
    out: List[str] = []
    spec = reg.spec_for_call(fn, call)
    if spec is not None:
        dnums, dnames = spec[0], spec[1]
        for p in dnums:
            if p < len(call.args) and not isinstance(
                call.args[p], ast.Starred
            ):
                t = _dotted_text(call.args[p])
                if t:
                    out.append(t)
        for kw in call.keywords:
            if kw.arg in dnames:
                t = _dotted_text(kw.value)
                if t:
                    out.append(t)
    if call_name(call.func) == "device_put":
        donate = any(
            kw.arg == "donate" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in call.keywords
        )
        if donate and call.args:
            t = _dotted_text(call.args[0])
            if t:
                out.append(t)
    return out


def rule_r7_donation_aliasing(
    modules: Sequence[Module], cfg: LintConfig, graph: ProjectGraph
) -> List[Finding]:
    reg = _JitRegistry(graph, modules)
    findings: List[Finding] = []
    for fn in _iter_funcs(graph, modules):
        # donating calls + every load/store of interesting texts. A call
        # nested under an If shows up while walking both the If and its
        # inner statement — keep the INNERMOST statement (blocks iterate
        # outer-first, so later matches are deeper) and dedup the call.
        don_stmt: Dict[int, ast.stmt] = {}
        don_call: Dict[int, Tuple[ast.Call, List[str]]] = {}
        for block in _own_blocks(graph, fn):
            for stmt in block:
                for node in graph.own_nodes(fn, stmt):
                    if isinstance(node, ast.Call):
                        texts = _r7_donated_exprs(reg, fn, node)
                        if texts:
                            don_stmt[id(node)] = stmt
                            don_call[id(node)] = (node, texts)
        donations = [
            (call, text, don_stmt[cid])
            for cid, (call, texts) in don_call.items()
            for text in dict.fromkeys(texts)
        ]
        if not donations:
            continue
        loads: List[Tuple[str, int, ast.AST]] = []
        stores: List[Tuple[str, int]] = []
        texts = {t for _c, t, _s in donations}
        for node in graph.own_nodes(fn):
            if isinstance(node, (ast.Name, ast.Attribute)):
                t = _dotted_text(node)
                if t not in texts:
                    continue
                ctx = getattr(node, "ctx", None)
                if isinstance(ctx, ast.Store):
                    stores.append((t, node.lineno))
                elif isinstance(ctx, ast.Load):
                    loads.append((t, node.lineno, node))
        for call, text, stmt in donations:
            # rebinding at the donation statement itself
            # (``self.storage = fn(self.storage, ...)`` — also through a
            # tuple target like ``self.W, loss = step(self.W, ...)``) is
            # the sanctioned idiom: post-donation reads get the new value
            flat_targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        flat_targets.extend(t.elts)
                    else:
                        flat_targets.append(t)
            rebound_here = any(
                _dotted_text(t) == text for t in flat_targets
            )
            inside_call = {id(n) for n in ast.walk(call)}
            first_kill = min(
                (ln for t, ln in stores
                 if t == text and ln > call.lineno
                 and not (rebound_here and ln == stmt.lineno)),
                default=None,
            )
            if rebound_here:
                # safe unless another read sneaks in before a later use
                continue
            # loop back-edge: donation inside a loop with no rebinding
            # anywhere in the loop — iteration 2 feeds the call a
            # buffer iteration 1 already invalidated (the call's own
            # argument load is excluded from the forward scan, so this
            # case needs its own check)
            loop = _enclosing_loop(fn, graph, call)
            if loop is not None and not any(
                t == text and _contains(loop, ln)
                for t, ln in stores
            ):
                findings.append(Finding(
                    "R7", fn.module.relpath, call.lineno,
                    f"{text!r} is donated here and re-read on the next "
                    "loop iteration without being rebound — the buffer "
                    "is invalidated after the first pass",
                    "rebind the donated value from the call's result "
                    f"({text} = fn({text}, ...)), the zero-copy "
                    "snapshot idiom from the PR 5 fix",
                ))
                continue
            offenders = [
                (t, ln) for t, ln, node in loads
                if t == text and ln > call.lineno
                and (first_kill is None or ln <= first_kill)
                and id(node) not in inside_call
            ]
            if offenders:
                ln = min(ln for _t, ln in offenders)
                findings.append(Finding(
                    "R7", fn.module.relpath, call.lineno,
                    f"{text!r} is donated to a jitted call here but "
                    f"read again at line {ln} — donated buffers are "
                    "invalidated in place (the PR 5 snapshot-aliasing "
                    "class)",
                    "rebind the name from the call's result before any "
                    "further read, or drop it from donate_argnums",
                ))
    return findings


rule_r7_donation_aliasing.needs_graph = True


def _enclosing_loop(fn: FuncInfo, graph: ProjectGraph,
                    target: ast.AST) -> Optional[ast.AST]:
    """Innermost For/While in ``fn`` lexically containing ``target``."""
    best: Optional[ast.AST] = None
    for node in graph.own_nodes(fn):
        if isinstance(node, (ast.For, ast.While)):
            if any(sub is target for sub in ast.walk(node)):
                if best is None or any(
                    s is node for s in ast.walk(best)
                ):
                    best = node
    return best


def _contains(root: ast.AST, line: int) -> bool:
    end = getattr(root, "end_lineno", None)
    return root.lineno <= line <= (end if end is not None else line)


# ------------------------------------------------------------------- R8

def _loop_tainted_names(graph: ProjectGraph, fn: FuncInfo) -> Set[str]:
    """Loop variables plus one step of derived assignments."""
    tainted: Set[str] = set()
    for node in graph.own_nodes(fn):
        if isinstance(node, ast.For):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    tainted.add(t.id)
    for node in graph.own_nodes(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            if any(
                isinstance(n, ast.Name) and n.id in tainted
                for n in ast.walk(node.value)
            ):
                tainted.add(node.targets[0].id)
    return tainted


_SHAPE_CTORS = {"arange", "zeros", "ones", "empty", "full", "linspace"}


def _expr_mentions(expr: ast.AST, names: Set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in names
        for n in ast.walk(expr)
    )


def _shape_churn(expr: ast.AST, tainted: Set[str]) -> bool:
    """Does this argument's SHAPE vary with a loop variable? (slices
    with tainted bounds, arange/zeros-style ctors with tainted sizes)"""
    for n in ast.walk(expr):
        if isinstance(n, ast.Subscript) and isinstance(n.slice, ast.Slice):
            for bound in (n.slice.lower, n.slice.upper, n.slice.step):
                if bound is not None and _expr_mentions(bound, tainted):
                    return True
        elif isinstance(n, ast.Call) and call_name(n.func) in \
                _SHAPE_CTORS:
            if any(_expr_mentions(a, tainted) for a in n.args):
                return True
    return False


def rule_r8_retrace_churn(
    modules: Sequence[Module], cfg: LintConfig, graph: ProjectGraph
) -> List[Finding]:
    reg = _JitRegistry(graph, modules)
    findings: List[Finding] = []
    for fn in _iter_funcs(graph, modules):
        tainted = _loop_tainted_names(graph, fn)
        for node in graph.own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            # (a) jit constructed inside a loop (fresh callable = fresh
            # trace every iteration) — a Subscript store is a deliberate
            # per-key compile cache and stays legal
            if call_name(node.func) in _JIT_NAMES and \
                    _enclosing_loop(fn, graph, node) is not None:
                stmt = _stmt_of(fn, graph, node)
                cached = isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Subscript) for t in stmt.targets
                )
                if not cached:
                    findings.append(Finding(
                        "R8", fn.module.relpath, node.lineno,
                        "jax.jit constructed inside a loop — every "
                        "iteration builds a fresh callable and "
                        "retraces from scratch",
                        "hoist the jit out of the loop, or store it in "
                        "a keyed compile cache (self._compiled[key] = "
                        "jax.jit(...)) like the tables do",
                    ))
                continue
            spec = reg.spec_for_call(fn, node)
            if spec is None:
                continue
            if _enclosing_loop(fn, graph, node) is None:
                continue
            _dn, _dm, snums, snames = spec
            # (b) per-round loop variable at a STATIC position: every
            # new value is a new cache key -> retrace per iteration
            for p in snums:
                if p < len(node.args) and _expr_mentions(
                    node.args[p], tainted
                ):
                    findings.append(Finding(
                        "R8", fn.module.relpath, node.lineno,
                        f"loop-varying value at static_argnums position "
                        f"{p} of a jitted call — each iteration is a "
                        "new cache key and retraces (the PR 7 "
                        "compile-churn class)",
                        "pass round-varying values as dynamic (traced) "
                        "arguments; keep static_argnums for genuinely "
                        "fixed topology/config",
                    ))
            for kw in node.keywords:
                if kw.arg in snames and _expr_mentions(kw.value, tainted):
                    findings.append(Finding(
                        "R8", fn.module.relpath, node.lineno,
                        f"loop-varying value at static_argnames "
                        f"{kw.arg!r} of a jitted call — each iteration "
                        "is a new cache key and retraces",
                        "pass round-varying values as dynamic (traced) "
                        "arguments; keep static_argnames for genuinely "
                        "fixed topology/config",
                    ))
            # (c) loop-varying argument SHAPES retrace at any position
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if _shape_churn(arg, tainted):
                    findings.append(Finding(
                        "R8", fn.module.relpath, node.lineno,
                        "argument shape varies with the loop variable "
                        "at a jitted call — every distinct shape "
                        "retraces",
                        "pad/bucket to a fixed shape before the jitted "
                        "boundary (the round_bucket idiom), or mask "
                        "inside the kernel",
                    ))
                    break
    return findings


rule_r8_retrace_churn.needs_graph = True


def _stmt_of(fn: FuncInfo, graph: ProjectGraph,
             target: ast.AST) -> Optional[ast.stmt]:
    """INNERMOST statement owning ``target`` — blocks iterate
    outer-first, so the last match is the deepest. Returning the first
    match would hand R8 the enclosing ``For`` instead of the
    ``cache[key] = jax.jit(...)`` assign and break the keyed-cache
    exemption."""
    best: Optional[ast.stmt] = None
    for block in _own_blocks(graph, fn):
        for stmt in block:
            if any(n is target for n in graph.own_nodes(fn, stmt)):
                best = stmt
    return best


# ------------------------------------------------------------------- R9

class _Access:
    __slots__ = ("attr", "kind", "line", "fn", "held")

    def __init__(self, attr: str, kind: str, line: int, fn: FuncInfo,
                 held: FrozenSet[str]):
        self.attr = attr
        self.kind = kind  # "read" | "write" | "aug"
        self.line = line
        self.fn = fn
        self.held = held


def _is_lock_attr(ci: Optional[ClassInfo], attr: str) -> bool:
    if _LOCK_ATTR_RE.search(attr):
        return True
    if ci is not None and ci.attr_types.get(attr, set()) & \
            SYNC_PRIMITIVE_TYPES:
        return True
    return False


def _fn_accesses(graph: ProjectGraph, fn: FuncInfo,
                 entry_held: FrozenSet[str]) -> Tuple[
                     List[_Access], List[Tuple[int, FrozenSet[str]]]]:
    """Self-attribute accesses in ``fn`` with the lock set lexically
    held at each, plus (callee uid, held) pairs for one level of
    caller-holds-the-lock propagation."""
    ci = graph.class_of_func(fn)
    accesses: List[_Access] = []
    callsites: List[Tuple[int, FrozenSet[str]]] = []

    def locks_of(with_node: ast.With) -> Set[str]:
        out: Set[str] = set()
        for item in with_node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func  # with self._lock.acquire_timeout(...)
            if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name
            ) and expr.value.id == "self" and _is_lock_attr(ci, expr.attr):
                out.add(expr.attr)
        return out

    def rec(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn.node and id(node) in graph.funcs:
            return
        if isinstance(node, ast.With):
            nh = held | frozenset(locks_of(node))
            for item in node.items:
                rec(item.context_expr, held)
            for child in node.body:
                rec(child, nh)
            return
        if isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Attribute
        ) and isinstance(node.target.value, ast.Name) and \
                node.target.value.id == "self":
            accesses.append(_Access(
                node.target.attr, "aug", node.lineno, fn, held
            ))
            rec(node.value, held)
            return
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ) and node.value.id == "self":
            ctx = node.ctx
            if isinstance(ctx, ast.Store):
                accesses.append(_Access(
                    node.attr, "write", node.lineno, fn, held
                ))
            elif isinstance(ctx, ast.Load):
                accesses.append(_Access(
                    node.attr, "read", node.lineno, fn, held
                ))
        if isinstance(node, ast.Call):
            for hit in graph._resolve_name_or_attr(fn, node.func):
                callsites.append((hit.uid, held))
        for child in ast.iter_child_nodes(node):
            rec(child, held)

    rec(fn.node, entry_held)
    return accesses, callsites


# @collective_dispatch is a *virtual lock*: the runtime guard pins
# every decorated entry point to one thread (GuardViolation on any
# other), so table state touched under it is serialized by
# construction — the decorator, not a Lock, is the synchronization.
# mvtsan mirrors it at runtime (analysis/mvtsan.py pushes the same
# name into the dynamic lockset inside the decorator), so static and
# dynamic verdicts agree on dispatch-serialized state.
DISPATCH_LOCK = "<collective_dispatch>"


def class_access_buckets(
    modules: Sequence[Module], graph: ProjectGraph
) -> Dict[Tuple[str, str], Dict[str, List[_Access]]]:
    """Per-class, per-attribute ``self.X`` access lists with the lock
    set held at each access — the shared substrate of the static R9
    verdict AND the mvtsan instrumentation plan
    (:mod:`multiverso_tpu.analysis.instrument`). ``__init__`` accesses
    and lock-typed attributes are excluded; entry-held locks from the
    caller-holds-the-lock fixpoint are folded into each access."""
    fns = [
        fn for fn in _iter_funcs(graph, modules)
        if fn.cls and fn.name != "__del__"  # finalizers cannot race
    ]
    # "caller holds the lock" propagation: a helper ALWAYS called with
    # some lock held inherits it at entry. Must-analysis iterated to a
    # fixpoint — entry_held[f] = ∩ over call sites of (locks lexically
    # held at the site ∪ locks the caller itself entered with) — so the
    # flush -> _ensure_resident -> _fill_slots chain resolves through
    # any call depth. Starting from ∅ this converges from below, which
    # is the conservative direction: a call cycle with an unlocked
    # entry inherits nothing. __init__ call sites are excluded
    # (happens-before any thread the object spawns).
    per_fn: Dict[int, Tuple[List[_Access], List[Tuple[int, FrozenSet[str]]]]] = {}
    sites: Dict[int, List[Tuple[int, FrozenSet[str]]]] = {}
    for fn in fns:
        base = frozenset({DISPATCH_LOCK}) if \
            _has_dispatch_decorator(fn.node) else frozenset()
        per_fn[fn.uid] = _fn_accesses(graph, fn, base)
        if fn.name == "__init__":
            continue
        for uid, held in per_fn[fn.uid][1]:
            sites.setdefault(uid, []).append((fn.uid, held))
    entry_held: Dict[int, Optional[FrozenSet[str]]] = {
        uid: None for uid in sites  # None = TOP (no caller seen yet)
    }
    for _ in range(len(sites) + 1):
        changed = False
        for uid, callers in sites.items():
            acc: Optional[FrozenSet[str]] = None
            for caller_uid, lex_held in callers:
                inherited = entry_held.get(caller_uid)
                term = lex_held | (
                    inherited if inherited is not None else frozenset()
                )
                acc = term if acc is None else (acc & term)
            if acc != entry_held[uid]:
                entry_held[uid] = acc
                changed = True
        if not changed:
            break
    # group accesses per class
    by_class: Dict[Tuple[str, str], Dict[str, List[_Access]]] = {}
    for fn in fns:
        eh = entry_held.get(fn.uid) or frozenset()
        accesses, _calls = per_fn[fn.uid]
        if eh:
            accesses = [
                _Access(a.attr, a.kind, a.line, a.fn, a.held | eh)
                for a in accesses
            ]
        ci = graph.class_of_func(fn)
        if ci is None:
            continue
        bucket = by_class.setdefault(
            (ci.module.relpath, ci.name), {}
        )
        for a in accesses:
            # __init__ runs happens-before any thread this object spawns
            if a.fn.name == "__init__" or _is_lock_attr(ci, a.attr):
                continue
            bucket.setdefault(a.attr, []).append(a)
    return by_class


class AttrVerdict:
    """The static R9 verdict on one (class, attr) bucket — also the
    instrumentation plan's classification record."""

    __slots__ = ("classification", "locks", "rmw", "cross_thread",
                 "anchor", "others", "why")

    def __init__(self, classification: str, locks: FrozenSet[str],
                 rmw: bool, cross_thread: bool,
                 anchor: Optional[_Access] = None,
                 others: Optional[List[_Access]] = None, why: str = ""):
        self.classification = classification
        self.locks = locks
        self.rmw = rmw
        self.cross_thread = cross_thread
        self.anchor = anchor
        self.others = others or []
        self.why = why


def classify_attr(accs: List[_Access], tuids: Set[int],
                  muids: Set[int]) -> AttrVerdict:
    """One attribute's cross-thread verdict. Classifications:
    ``reads-only`` (no writes outside ``__init__``),
    ``writer-serialized`` (every write and every check-then-act read
    holds one common lock — lock-free pure reads are GIL-atomic loads
    of a published value), ``one-side`` (never touched from both
    sides), ``publication`` (cross-thread but only plain stores race
    plain loads — single-assignment publication), ``lock-guarded``
    (the conflicting accesses share a lock), ``race`` (the R9
    finding). mvtsan's dynamic exemption set mirrors exactly these —
    static and dynamic verdicts must agree on the same field."""
    # a read AT OR BEFORE a write in the same function is a
    # read-modify-write even without an AugAssign
    # (``if self._n > k: self._n = 0``). Write-then-read-later
    # is NOT (publication + use, e.g. setup building a cache
    # it then consults).
    rmw_fns: Set[int] = set()
    first_read: Dict[int, int] = {}
    for a in accs:
        if a.kind == "aug":
            rmw_fns.add(a.fn.uid)
        elif a.kind == "read":
            first_read[a.fn.uid] = min(
                first_read.get(a.fn.uid, a.line), a.line
            )
    for a in accs:
        if a.kind == "write" and \
                first_read.get(a.fn.uid, a.line + 1) <= a.line:
            rmw_fns.add(a.fn.uid)

    def side(a: _Access) -> Tuple[bool, bool]:
        return a.fn.uid in tuids, a.fn.uid in muids

    t_acc = [a for a in accs if side(a)[0]]
    m_acc = [a for a in accs if side(a)[1]]
    cross = bool(t_acc) and bool(m_acc)
    writes = [
        a for a in accs
        if a.kind in ("write", "aug") and a.fn.name != "__init__"
    ]
    has_rmw = any(
        a.kind == "aug" or a.fn.uid in rmw_fns for a in writes
    )
    if not writes:
        return AttrVerdict("reads-only", frozenset(), False, cross)
    # Writer-serialized publication: every write — and every
    # read inside a fn that also writes the attr (the reads
    # that make a check-then-act) — holds one common lock.
    # Whatever accesses remain lock-free are pure reads in
    # reader-only fns: single reference loads of a published
    # value, atomic under the GIL (the TableServer._snapshot
    # swap pattern). A broken double-checked lazy-init does
    # NOT qualify — its lock-free check read lives in a
    # writer fn and empties the intersection.
    writer_uids = {a.fn.uid for a in writes}
    guard_accs = writes + [
        a for a in accs
        if a.kind == "read" and a.fn.uid in writer_uids
    ]
    serial = frozenset.intersection(*(a.held for a in guard_accs))
    if serial:
        return AttrVerdict(
            "writer-serialized", serial, has_rmw, cross
        )
    t_rmw = [
        a for a in writes
        if side(a)[0] and (a.kind == "aug" or a.fn.uid in rmw_fns)
    ]
    m_rmw = [
        a for a in writes
        if side(a)[1] and (a.kind == "aug" or a.fn.uid in rmw_fns)
    ]
    t_w = [a for a in writes if side(a)[0]]
    m_w = [a for a in writes if side(a)[1]]

    conflict: Optional[Tuple[_Access, List[_Access], str]] = None
    if t_rmw and m_acc:
        conflict = (t_rmw[0], m_acc,
                    "read-modify-write on a thread path")
    elif m_rmw and t_acc:
        conflict = (m_rmw[0], t_acc,
                    "read-modify-write racing a thread-path "
                    "access")
    elif any(
        w1.line != w2.line for w1 in t_w for w2 in m_w
    ):
        conflict = (t_w[0], m_w,
                    "written from both a thread path and "
                    "training-thread code")
    if conflict is None:
        kind = "publication" if cross else "one-side"
        return AttrVerdict(kind, frozenset(), has_rmw, cross)
    anchor, others, why = conflict
    involved = [anchor] + [a for a in others if a is not anchor]
    common = frozenset.intersection(
        *(a.held for a in involved)
    ) if involved else frozenset()
    if common:
        # a shared lock guards every involved access
        return AttrVerdict(
            "lock-guarded", common, has_rmw, cross, anchor, others, why
        )
    return AttrVerdict(
        "race", frozenset(), has_rmw, cross, anchor, others, why
    )


def rule_r9_cross_thread_state(
    modules: Sequence[Module], cfg: LintConfig, graph: ProjectGraph
) -> List[Finding]:
    facts = spmd_facts(graph)
    tuids = facts.thread_uids()
    muids = facts.main_uids()
    by_class = class_access_buckets(modules, graph)
    findings: List[Finding] = []
    for (relpath, clsname), attrs in sorted(by_class.items()):
        for attr, accs in sorted(attrs.items()):
            v = classify_attr(accs, tuids, muids)
            if v.classification != "race":
                continue
            anchor, others, why = v.anchor, v.others, v.why
            other_fns = sorted({
                a.fn.qualname for a in others if a.fn is not anchor.fn
            }) or [anchor.fn.qualname]
            findings.append(Finding(
                "R9", relpath, anchor.line,
                f"{clsname}.{attr}: {why} "
                f"({anchor.fn.qualname}, line {anchor.line}) with "
                f"unsynchronized access from {', '.join(other_fns)} — "
                "no common lock covers both sides",
                "guard every access with one OrderedLock attribute "
                "held on both paths (single-assignment publication "
                "needs none; counters and check-then-set do)",
            ))
    return findings


rule_r9_cross_thread_state.needs_graph = True
