"""mvlint rules R10-R12 — the lifecycle/protocol families (v3).

R1-R9 reason about reachability and races; the bugs this repo paid for
in PRs 6, 8, 9 and 12 were *protocol* violations: resources whose
state machine was driven out of order or never driven to its final
state on some exit path, checkpoints committed out of protocol order,
readiness flipped before restore landed, and flag implications
re-implemented by hand until code and docs drifted apart.  These three
families close that class on top of :mod:`analysis.typestate`:

* **R10** — resource typestate: TaskPipe / ASyncBuffer / HealthServer /
  TableServer / non-daemon Thread / ``MV_CreateTable`` bindings must
  reach their final state on EVERY exit path (path-sensitive, with
  ``with``/``finally`` recognition and interprocedural must-call
  summaries), plus class-attribute and dashboard attach↔detach pairing;
* **R11** — checkpoint/publish protocol order: ``commit_atomic`` must
  be dominated by a verify in staging functions, ``publish`` must pass
  the validation gate before installing a snapshot, ``drain()`` must
  dominate any pipelined-depth save, and readiness may only flip to
  True *after* restore/publish work, never before;
* **R12** — flag-constraint conformance: ``config/constraints.py`` is
  the single source of flag implications; a hand-rolled implication or
  requirement CHECK elsewhere, or drift between the model and the
  generated DEPLOY.md block, is a finding.

Approximations err toward the runtime guards (``analysis/guards.py``,
``config.constraints.check_options``) catching what static analysis
cannot; suppression contracts live in ``analysis/RULES.md``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from multiverso_tpu.analysis.mvlint import Finding, LintConfig, Module
from multiverso_tpu.analysis.dataflow import (
    ClassInfo, FuncInfo, ProjectGraph, call_name,
)
from multiverso_tpu.analysis import typestate as ts

# ------------------------------------------------------------------- R10

_PIPE_SPEC = ts.ResourceSpec(
    rtype="TaskPipe",
    ctors=("TaskPipe",),
    finalizers=("close", "break_pipe"),
    uses=("submit", "submit_nowait"),
    leak_hint=(
        "close it in a finally (the worker thread and its queue outlive "
        "the function otherwise — the bench drain-drill bug class)"
    ),
)
_BUFFER_SPEC = ts.ResourceSpec(
    rtype="ASyncBuffer",
    ctors=("ASyncBuffer",),
    finalizers=("Stop", "stop"),
    leak_hint=(
        "Stop() it on every exit path — the PR 8 reader bug left its "
        "fill thread producing into an abandoned queue"
    ),
)
_THREAD_SPEC = ts.ResourceSpec(
    rtype="Thread",
    ctors=("Thread",),
    finalizers=("join",),
    arm_methods=("start",),
    daemon_exempt=True,
    leak_hint=(
        "join it on every exit path (R4 checks that a join EXISTS; this "
        "is the path R4's lexical check cannot see)"
    ),
)
_HEALTH_SPEC = ts.ResourceSpec(
    rtype="HealthServer",
    ctors=("HealthServer",),
    finalizers=("stop",),
    leak_hint="stop() it in a finally — it binds a TCP port and a thread",
)
_SERVER_SPEC = ts.ResourceSpec(
    rtype="TableServer",
    ctors=("TableServer",),
    finalizers=("stop",),
    arm_methods=("start",),
    leak_hint="stop() every start()ed TableServer on every exit path",
)
_TABLE_SPEC = ts.ResourceSpec(
    rtype="table handle",
    ctors=("MV_CreateTable",),
    finalizers=("release_tables",),
    region_finalizers=("release_tables",),
    allow_escape=False,
    leak_hint=(
        "pass it to release_tables() before returning — the PR 6 "
        "registry leak pinned ~8 GB of host shards per bench sweep"
    ),
)

_R10_SPECS = (
    _PIPE_SPEC, _BUFFER_SPEC, _THREAD_SPEC, _HEALTH_SPEC, _SERVER_SPEC,
    _TABLE_SPEC,
)


def _leak_finding(fn: FuncInfo, spec: ts.ResourceSpec,
                  v: ts.Violation) -> Finding:
    fins = "/".join(spec.finalizers)
    return Finding(
        "R10", fn.module.relpath, v.line,
        f"{spec.rtype} {v.var!r} is created here but some exit path "
        f"(return, raise, or a failing assert) never calls {fins}",
        spec.leak_hint or f"call {fins} on every exit path",
    )


def _use_after_finding(fn: FuncInfo, spec: ts.ResourceSpec,
                       v: ts.Violation) -> Finding:
    return Finding(
        "R10", fn.module.relpath, v.line,
        f"use after finalize: {v.detail}",
        "finalize exactly once, on the exit paths only",
    )


def rule_r10_resource_typestate(
    modules: Sequence[Module], cfg: LintConfig, graph: ProjectGraph
) -> List[Finding]:
    # function-scope import: rules.py imports this module to build
    # ALL_RULES, so a module-level import back would be a cycle
    from multiverso_tpu.analysis.rules import _binding_joined

    findings: List[Finding] = []
    summaries = ts.Summaries(graph, _R10_SPECS)
    mod_ids = {id(m) for m in modules}
    for fn in graph.funcs.values():
        if isinstance(fn.node, ast.Lambda) or id(fn.module) not in mod_ids:
            continue
        for spec in _R10_SPECS:
            for v in ts.check_function(graph, fn, spec, summaries):
                if spec is _THREAD_SPEC and v.kind == "leak":
                    # R4 owns threads with NO join anywhere in scope; R10
                    # only upgrades the check when a join exists lexically
                    # but some path misses it — firing both would double-
                    # report one bug.
                    ci = graph.class_of_func(fn)
                    scope = ci.node if ci is not None else fn.module.tree
                    if not _binding_joined(v.var, scope):
                        continue
                if v.kind == "leak":
                    findings.append(_leak_finding(fn, spec, v))
                else:
                    findings.append(_use_after_finding(fn, spec, v))
    findings.extend(_attr_pairing(modules, graph))
    findings.extend(_dashboard_pairing(modules))
    return findings


rule_r10_resource_typestate.needs_graph = True  # type: ignore[attr-defined]


# class attribute -> the finalizer names that discharge it.  Threads are
# deliberately absent: R4's lexical join check already owns attr-held
# threads.
_ATTR_FINALIZERS: Dict[str, Tuple[str, ...]] = {
    "TaskPipe": ("close", "break_pipe"),
    "ASyncBuffer": ("Stop", "stop"),
    "HealthServer": ("stop", "close"),
    "TableServer": ("stop",),
}


def _class_own_walk(cls: ast.ClassDef) -> Iterable[ast.AST]:
    """Walk a class body without descending into nested classes (their
    resources are their own problem)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(cls))
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                continue
            stack.append(child)


def _attr_finalized(ci: ClassInfo, attr: str,
                    fins: Tuple[str, ...]) -> bool:
    """Loose pairing: SOME method both mentions ``self.<attr>`` and
    calls a finalizer name.  Deliberately receiver-insensitive — the
    repo's teardown idiom swaps the attribute into a local first
    (``pipe, self._pipe = self._pipe, None; pipe.close()``)."""
    for meth in _class_own_walk(ci.node):
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mentions = False
        finalizes = False
        for n in ast.walk(meth):
            if isinstance(n, ast.Attribute) and n.attr == attr \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                mentions = True
            if isinstance(n, ast.Call) and call_name(n.func) in fins:
                finalizes = True
        if mentions and finalizes:
            return True
    return False


def _attr_armed(ci: ClassInfo, attr: str) -> bool:
    """Is ``self.<attr>.start()`` ever driven (directly or fluently at
    the assignment)?  An armless TableServer needs no stop."""
    for n in _class_own_walk(ci.node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "start":
            recv = n.func.value
            if isinstance(recv, ast.Attribute) and recv.attr == attr:
                return True
        if isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Attribute) and t.attr == attr
            for t in n.targets
        ):
            v = n.value
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                    and v.func.attr == "start":
                return True
    return False


def _attr_daemon(ci: ClassInfo, attr: str, rtype: str) -> bool:
    for n in _class_own_walk(ci.node):
        if isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Attribute) and t.attr == attr
            for t in n.targets
        ):
            for c in ast.walk(n.value):
                if isinstance(c, ast.Call) and call_name(c.func) == rtype:
                    for kw in c.keywords:
                        if kw.arg == "daemon" and isinstance(
                            kw.value, ast.Constant
                        ) and kw.value.value is True:
                            return True
    return False


def _attr_assign_line(ci: ClassInfo, attr: str, rtype: str) -> int:
    for n in _class_own_walk(ci.node):
        if isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Attribute) and t.attr == attr
            for t in n.targets
        ) and any(
            isinstance(c, ast.Call) and call_name(c.func) == rtype
            for c in ast.walk(n.value)
        ):
            return n.lineno
    return ci.node.lineno


def _attr_pairing(modules: Sequence[Module],
                  graph: ProjectGraph) -> List[Finding]:
    findings: List[Finding] = []
    mod_ids = {id(m) for m in modules}
    for ci in graph.classes.values():
        if id(ci.module) not in mod_ids:
            continue
        for attr in sorted(ci.attr_types):
            for rtype in sorted(
                ci.attr_types[attr] & set(_ATTR_FINALIZERS)
            ):
                fins = _ATTR_FINALIZERS[rtype]
                if rtype == "TableServer" and not _attr_armed(ci, attr):
                    continue
                if _attr_daemon(ci, attr, rtype):
                    continue
                if _attr_finalized(ci, attr, fins):
                    continue
                findings.append(Finding(
                    "R10", ci.module.relpath,
                    _attr_assign_line(ci, attr, rtype),
                    f"{ci.name}.{attr} holds a {rtype} but no method of "
                    f"the class finalizes it ({'/'.join(fins)}) — the "
                    "worker outlives its owner",
                    f"call self.{attr}.{fins[0]}() from the owner's "
                    "close()/stop()",
                ))
    return findings


_TEARDOWN_NAMES = {
    "close", "stop", "shutdown", "detach", "__exit__", "release",
    "unregister",
}


def _section_key_is_per_instance(call: ast.Call) -> bool:
    exprs = list(call.args[:1]) + [
        kw.value for kw in call.keywords if kw.arg in ("key", "name")
    ]
    for e in exprs:
        for n in ast.walk(e):
            if isinstance(n, ast.Call) and call_name(n.func) == "id":
                return True
    return False


def _dashboard_pairing(modules: Sequence[Module]) -> List[Finding]:
    """``Dashboard.add_section`` without a ``remove_section`` anywhere in
    the same class leaks a section per instance — the PR 9 serving leak.
    Process-lifetime singletons (no teardown method, constant key) are
    exempt: their one section dies with the process by design."""
    findings: List[Finding] = []
    for m in modules:
        for cls in ast.walk(m.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            adds = [
                n for n in _class_own_walk(cls)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "add_section"
            ]
            if not adds:
                continue
            if any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "remove_section"
                for n in _class_own_walk(cls)
            ):
                continue
            has_teardown = any(
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name in _TEARDOWN_NAMES
                for n in cls.body
            )
            for add in adds:
                per_instance = _section_key_is_per_instance(add)
                if not (has_teardown or per_instance):
                    continue
                why = (
                    "per-instance key: every construction leaks a section"
                    if per_instance else
                    "the class has a teardown method that never detaches it"
                )
                findings.append(Finding(
                    "R10", m.relpath, add.lineno,
                    f"{cls.name} attaches a dashboard section with no "
                    f"matching remove_section ({why}) — the PR 9 serving "
                    "dashboard leak class",
                    "call Dashboard.remove_section(key) from the owner's "
                    "close()/stop()",
                ))
    return findings


# ------------------------------------------------------------------- R11

_READY_NAMES = ("set_ready", "_set_ready")
_GATE_SUBSTRINGS = ("resume", "restore", "publish", "validate", "rollback")
_SAVE_NAMES = (
    "_ps_save_checkpoint", "save_checkpoint", "save_tables", "maybe_save",
)


def _stmt_line(cfg: ts.CFG, n: int) -> int:
    stmt = cfg.stmt_of[n]
    return stmt.lineno if stmt is not None else 0


def _receiver_leaf(call: ast.Call) -> str:
    func = call.func
    if isinstance(func, ast.Attribute):
        recv = func.value
        if isinstance(recv, ast.Name):
            return recv.id
        if isinstance(recv, ast.Attribute):
            return recv.attr
    return ""


def _is_ready_flip(call: ast.Call) -> bool:
    cn = call_name(call.func)
    if cn == "set_serving_ready":
        return True
    if cn in _READY_NAMES:
        return bool(call.args) and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is True
    return False


def _is_gate_call(call: ast.Call) -> bool:
    cn = call_name(call.func).lower()
    return any(s in cn for s in _GATE_SUBSTRINGS)


def _reachable_from(cfg: ts.CFG, start: int) -> Set[int]:
    seen: Set[int] = set()
    stack = list(cfg.succ[start])
    while stack:
        n = stack.pop()
        if n in seen:
            continue
        seen.add(n)
        stack.extend(cfg.succ[n])
    return seen


def _assigns_snapshot(stmt: Optional[ast.stmt]) -> bool:
    if not isinstance(stmt, ast.Assign):
        return False
    return any(
        isinstance(t, ast.Attribute) and "snapshot" in t.attr.lower()
        for t in stmt.targets
    )


def rule_r11_protocol_order(
    modules: Sequence[Module], cfg: LintConfig, graph: ProjectGraph
) -> List[Finding]:
    findings: List[Finding] = []
    mod_ids = {id(m) for m in modules}
    for fn in graph.funcs.values():
        if isinstance(fn.node, ast.Lambda) or id(fn.module) not in mod_ids:
            continue
        called = {
            call_name(n.func) for n in graph.own_nodes(fn)
            if isinstance(n, ast.Call)
        }
        commits = "commit_atomic" in called
        stages = any("stage" in c.lower() for c in called)
        submits = bool(called & {"submit", "submit_nowait"})
        readies = bool(called & (set(_READY_NAMES)
                                 | {"set_serving_ready"}))
        publishes = fn.name.startswith("publish")
        if not (commits or submits or readies or publishes):
            continue
        fcfg = ts.build_cfg(fn.node)

        # (a) stage -> verify -> commit: in a function that stages a
        # checkpoint record, the atomic commit must be dominated by a
        # verify of what was staged (quorum-commit protocol).
        if commits and stages:
            verify_nodes = ts.nodes_where(
                fcfg, lambda c: "verify" in call_name(c.func).lower()
            )
            for n in sorted(ts.nodes_where(
                fcfg, lambda c: call_name(c.func) == "commit_atomic"
            )):
                if not ts.must_pass(fcfg, n, verify_nodes):
                    findings.append(Finding(
                        "R11", fn.module.relpath, _stmt_line(fcfg, n),
                        "commit_atomic is reachable without passing a "
                        "verify of the staged checkpoint (stage -> "
                        "verify -> commit is the quorum protocol)",
                        "verify the staged payload on every path into "
                        "the commit",
                    ))

        # (b) publish installs a snapshot only past the validation gate.
        if publishes:
            gate_nodes = ts.nodes_where(
                fcfg, lambda c: any(
                    s in call_name(c.func).lower()
                    for s in ("validate", "verify")
                )
            )
            for n in range(len(fcfg.stmt_of)):
                if not _assigns_snapshot(fcfg.stmt_of[n]):
                    continue
                if not ts.must_pass(fcfg, n, gate_nodes):
                    findings.append(Finding(
                        "R11", fn.module.relpath, _stmt_line(fcfg, n),
                        f"{fn.name}() installs a serving snapshot on a "
                        "path that skips the validation gate (a bad "
                        "snapshot must be rejected, not served)",
                        "route every install through _validate_host() "
                        "(raise PublishRejected on problems)",
                    ))

        # (c) drain() dominates any pipelined-depth save: a checkpoint
        # taken with submitted work still in flight captures a torn
        # round boundary.
        if submits:
            gen = ts.nodes_where(fcfg, lambda c: (
                call_name(c.func) in ("submit", "submit_nowait")
                and "pipe" in _receiver_leaf(c).lower()
            ))
            kill = ts.nodes_where(fcfg, lambda c: (
                call_name(c.func) in ("drain", "close", "break_pipe")
                and "pipe" in _receiver_leaf(c).lower()
            ))
            saves = ts.nodes_where(
                fcfg, lambda c: call_name(c.func) in _SAVE_NAMES
            )
            if gen and saves:
                for n in sorted(ts.may_pending(fcfg, gen, kill, saves)):
                    findings.append(Finding(
                        "R11", fn.module.relpath, _stmt_line(fcfg, n),
                        "checkpoint save is reachable with submitted "
                        "pipe work still in flight — drain() must "
                        "dominate every pipelined-depth save",
                        "pipe.drain() on every path into the save (the "
                        "planned-checkpoint boundary idiom)",
                    ))

        # (d) readiness may only flip to True AFTER restore/publish
        # work: a True flip from which a gate call is still reachable
        # serves traffic from a rank that is still restoring.
        if readies and not publishes \
                and fn.name not in ("set_ready", "_set_ready",
                                    "set_serving_ready"):
            gate_nodes = ts.nodes_where(fcfg, _is_gate_call)
            for n in sorted(ts.nodes_where(fcfg, _is_ready_flip)):
                hit = _reachable_from(fcfg, n) & gate_nodes
                if not hit:
                    continue
                gname = next((
                    call_name(c.func)
                    for c in ts.node_calls(fcfg, sorted(hit)[0])
                    if _is_gate_call(c)
                ), "restore")
                findings.append(Finding(
                    "R11", fn.module.relpath, _stmt_line(fcfg, n),
                    "readiness flips to True while "
                    f"{gname}() work is still ahead — probes can route "
                    "traffic to a rank that has not finished restoring",
                    "flip readiness after the restore/publish path "
                    "completes (alive-vs-ready wiring, ISSUE 7)",
                ))
    return findings


rule_r11_protocol_order.needs_graph = True  # type: ignore[attr-defined]


# ------------------------------------------------------------------- R12

class _FlagModel:
    __slots__ = ("module", "line", "implications", "requirements",
                 "all_flags")

    def __init__(self, module: Module, line: int,
                 implications: List[Tuple[str, str, str]],
                 requirements: List[Tuple[str, Tuple[str, ...]]]) -> None:
        self.module = module
        self.line = line
        self.implications = implications  # (name, trigger, flag)
        self.requirements = requirements  # (name, sorted flags)
        self.all_flags: Set[str] = set()
        for _n, trig, flag in implications:
            self.all_flags |= {trig, flag}
        for _n, flags in requirements:
            self.all_flags |= set(flags)


def _const_kw(call: ast.Call, name: str) -> Optional[object]:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _extract_flag_model(modules: Sequence[Module]) -> Optional[_FlagModel]:
    """AST-read the first IMPLICATIONS/REQUIREMENTS declarations in the
    scan — no import, so fixture models work standalone."""
    for m in modules:
        imps: List[Tuple[str, str, str]] = []
        reqs: List[Tuple[str, Tuple[str, ...]]] = []
        line = 0
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tname = node.targets[0].id
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                tname = node.target.id
            else:
                continue
            if tname not in ("IMPLICATIONS", "REQUIREMENTS"):
                continue
            line = line or node.lineno
            for call in ast.walk(node.value):
                if not isinstance(call, ast.Call):
                    continue
                cn = call_name(call.func)
                if cn == "Implication":
                    name = _const_kw(call, "name")
                    trig = _const_kw(call, "trigger")
                    flag = _const_kw(call, "flag")
                    if isinstance(trig, str) and isinstance(flag, str):
                        imps.append((str(name or flag), trig, flag))
                elif cn == "Requirement":
                    name = _const_kw(call, "name")
                    flags: Tuple[str, ...] = ()
                    for kw in call.keywords:
                        if kw.arg == "flags" and isinstance(
                            kw.value, (ast.Tuple, ast.List)
                        ):
                            flags = tuple(sorted(
                                e.value for e in kw.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)
                            ))
                    if flags:
                        reqs.append((str(name or "/".join(flags)), flags))
        if imps or reqs:
            return _FlagModel(m, line or 1, imps, reqs)
    return None


def _attrs_in(node: ast.AST) -> Set[str]:
    return {
        n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)
    }


def _r12_reimplemented_implications(
    m: Module, model: _FlagModel
) -> List[Finding]:
    """An assignment to an implied flag, inside an ``if`` over its
    trigger flag, re-implements the model by hand (the exact shape the
    old app.py tier block had).  Unconditional writes — bench sweeps
    configuring an option set — are legitimate."""
    findings: List[Finding] = []
    forced_by: Dict[str, Set[str]] = {}
    for _name, trig, flag in model.implications:
        forced_by.setdefault(flag, set()).add(trig)
    triggers = {t for _n, t, _f in model.implications}

    def visit(node: ast.AST, active: Set[str]) -> None:
        if isinstance(node, ast.If):
            tested = _attrs_in(node.test) & triggers
            for child in node.body + node.orelse:
                visit(child, active | tested)
            return
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for t in targets:
            if not isinstance(t, ast.Attribute):
                continue
            trigs = forced_by.get(t.attr, set()) & active
            if trigs:
                findings.append(Finding(
                    "R12", m.relpath, node.lineno,
                    f"hand-written implication: {t.attr} is forced "
                    f"under a test of -{sorted(trigs)[0]}, which "
                    "config/constraints.py already owns",
                    "delete the inline rewrite; "
                    "constraints.apply_implications() is the single "
                    "source",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, active)

    visit(m.tree, set())
    return findings


def _r12_reimplemented_requirements(
    m: Module, model: _FlagModel
) -> List[Finding]:
    findings: List[Finding] = []
    multi = [(n, set(f)) for n, f in model.requirements if len(f) > 1]
    if not multi:
        return findings
    for node in ast.walk(m.tree):
        expr: Optional[ast.AST] = None
        if isinstance(node, ast.Call) and call_name(node.func) == "CHECK":
            expr = node
        elif isinstance(node, ast.Assert):
            expr = node.test
        if expr is None:
            continue
        mentioned = _attrs_in(expr)
        for name, flags in multi:
            if flags <= mentioned:
                findings.append(Finding(
                    "R12", m.relpath, node.lineno,
                    f"hand-written CHECK couples {'+'.join(sorted(flags))}"
                    f" — requirement '{name}' in config/constraints.py "
                    "already owns that pair",
                    "delete the inline CHECK; "
                    "constraints.check_options() enforces the model",
                ))
                break
    return findings


_REAL_MODEL_RELPATH = "multiverso_tpu/config/constraints.py"


def _r12_doc_drift(model: _FlagModel, cfg: LintConfig) -> List[Finding]:
    """The DEPLOY.md block between the mvlint markers must be byte-equal
    to ``render_markdown()`` — regenerated, never hand-edited.  Only the
    real repo model is importable; fixture models skip the doc check."""
    if model.module.relpath != _REAL_MODEL_RELPATH:
        return []
    try:
        from multiverso_tpu.config import constraints as live
    except ImportError:  # pragma: no cover - the real model always imports
        return []
    findings: List[Finding] = []
    rendered = live.render_markdown()
    for doc in cfg.doc_files:
        if os.path.basename(doc) != "DEPLOY.md" or not os.path.exists(doc):
            continue
        with open(doc, encoding="utf-8") as fh:
            text = fh.read()
        if live.MARKER_BEGIN not in text or live.MARKER_END not in text:
            findings.append(Finding(
                "R12", model.module.relpath, model.line,
                "DEPLOY.md has no generated flag-constraints block — "
                "the implications/requirements in the model are "
                "undocumented",
                "insert the output of `python -m multiverso_tpu.analysis "
                "--constraint-table` into DEPLOY.md",
            ))
            continue
        start = text.index(live.MARKER_BEGIN)
        end = text.index(live.MARKER_END) + len(live.MARKER_END)
        if text[start:end] != rendered:
            findings.append(Finding(
                "R12", model.module.relpath, model.line,
                "DEPLOY.md flag-constraints block drifted from "
                "config/constraints.py",
                "regenerate it: `python -m multiverso_tpu.analysis "
                "--constraint-table` (edit the model, not the block)",
            ))
    return findings


def _r12_registry_drift(modules: Sequence[Module],
                        model: _FlagModel) -> List[Finding]:
    defined: Set[str] = set()
    for m in modules:
        for n in ast.walk(m.tree):
            if isinstance(n, ast.Call) \
                    and call_name(n.func).startswith("MV_DEFINE") \
                    and n.args and isinstance(n.args[0], ast.Constant):
                defined.add(n.args[0].value)
    if not defined:
        return []
    return [
        Finding(
            "R12", model.module.relpath, model.line,
            f"constraint model references flag -{flag}, which no "
            "MV_DEFINE_* in the scan registers",
            "fix the flag name in the model (or register the flag)",
        )
        for flag in sorted(model.all_flags - defined)
    ]


def rule_r12_flag_constraints(
    modules: Sequence[Module], cfg: LintConfig
) -> List[Finding]:
    model = _extract_flag_model(modules)
    if model is None:
        return []
    findings: List[Finding] = []
    for m in modules:
        if m is model.module:
            continue
        findings.extend(_r12_reimplemented_implications(m, model))
        findings.extend(_r12_reimplemented_requirements(m, model))
    findings.extend(_r12_doc_drift(model, cfg))
    findings.extend(_r12_registry_drift(modules, model))
    return findings
