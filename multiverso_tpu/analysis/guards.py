"""Runtime concurrency guards — the dynamic half of ``mvlint``.

The static rules in :mod:`multiverso_tpu.analysis.rules` prove properties
about the code that *can* be proven without running it; this module holds
the runtime assertions they pair with, all gated behind the
``-debug_thread_guards`` flag (default: off, or the value of the
``MV_DEBUG_THREAD_GUARDS`` env var — the tier-1 test suite exports it so
every threaded test runs with the guards armed):

* ``@collective_dispatch`` (pairs with rule **R1**) tags the table
  get/add/allgather entry points. Multi-device collective programs
  dispatched concurrently from two threads can invert per-device launch
  order and deadlock XLA's rendezvous (the PR 6 prefetch deadlock), so
  with the flag on every tagged call asserts it runs on an allowed
  thread: the ``TaskPipe`` comms worker, the registered training thread,
  the main thread, or inside an explicit ``allow_collective_dispatch``
  sync point. A violation raises a structured :class:`GuardViolation`
  *immediately* — a one-line error instead of a pod-scale hang.

* ``OrderedLock`` (pairs with rule **R2**) wraps the repo's cross-thread
  locks (tiered-table tier lock, batcher mutex, snapshot swap, heartbeat
  store). With the flag on, every acquisition records the held->acquired
  edge in a process-wide order graph; an acquisition that inverts a
  previously recorded order raises :class:`GuardViolation` at the exact
  second acquisition — deterministic detection of a deadlock that would
  otherwise need the losing interleaving to strike.

Both guards are no-ops (one flag read) when the flag is off, so the
production hot path pays nothing measurable.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from functools import wraps
from typing import Dict, Optional, Set, Tuple

import multiverso_tpu.analysis.mvtsan as _mvtsan
from multiverso_tpu.utils.configure import (
    GetFlag,
    MV_DEFINE_bool,
    mutation_count,
)

__all__ = [
    "GuardViolation",
    "collective_dispatch",
    "allow_collective_dispatch",
    "register_comms_thread",
    "unregister_comms_thread",
    "register_training_thread",
    "OrderedLock",
    "guards_enabled",
    "reset_lock_order_graph",
]

# env-derived default (not a plain False): tests call
# ResetFlagsToDefault() liberally, and the tier-1 contract is "guards ON
# for the whole suite" — the default must survive a reset.
MV_DEFINE_bool(
    "debug_thread_guards",
    os.environ.get("MV_DEBUG_THREAD_GUARDS", "") == "1",
    "arm the runtime concurrency guards: @collective_dispatch thread "
    "identity asserts + OrderedLock lock-order inversion detection "
    "(GuardViolation instead of a deadlock; see analysis/RULES.md)",
)


_enabled_cache: Optional[bool] = None
_enabled_gen = -1


def guards_enabled() -> bool:
    """Lock-free on the hot path: every tagged table op and every
    OrderedLock acquire/release calls this, so it must NOT funnel the
    whole process through the flag registry's global mutex. The value is
    cached against the registry's mutation counter and re-read only when
    a flag actually changed (SetCMDFlag/ParseCMDFlags/Reset)."""
    global _enabled_cache, _enabled_gen
    gen = mutation_count()
    if _enabled_cache is None or _enabled_gen != gen:
        _enabled_cache = bool(GetFlag("debug_thread_guards"))
        _enabled_gen = gen
    return _enabled_cache


class GuardViolation(RuntimeError):
    """Structured runtime-guard failure.

    ``kind``: ``collective_dispatch`` (R1 — tagged entry point invoked
    from a rogue thread) or ``lock_order`` (R2 — lock acquisition that
    inverts a recorded order). Raised at the violating call, on the
    violating thread — never a hang."""

    def __init__(self, kind: str, message: str, *, thread: str = "",
                 entry: str = ""):
        super().__init__(f"[{kind}] {message}")
        self.kind = kind
        self.thread = thread
        self.entry = entry
        try:
            # flight-recorder breadcrumb (obs): a guard trip is exactly
            # the kind of event a post-mortem needs on its timeline.
            # Guarded import: guards loads very early and must survive a
            # broken/absent obs package.
            from multiverso_tpu.obs.flight import recorder

            recorder.record(
                "guard_violation", violation_kind=kind, entry=entry,
                thread=thread,
            )
        except Exception:  # noqa: BLE001 — never mask the violation
            pass


# --------------------------------------------------- dispatch-thread guard

_comms_threads: Set[int] = set()
_comms_lock = threading.Lock()
_training_thread: Optional[int] = None
_tls = threading.local()


def register_comms_thread() -> None:
    """Called by the ``TaskPipe`` worker at thread start: tasks executed
    on the pipe ARE the documented collective-dispatch channel."""
    with _comms_lock:
        _comms_threads.add(threading.get_ident())


def unregister_comms_thread() -> None:
    with _comms_lock:
        _comms_threads.discard(threading.get_ident())


def register_training_thread() -> None:
    """Declare the calling thread as THE training thread (the depth-0 PS
    sync points and the host-batch loops dispatch collectives from it).
    Training entry points (``WordEmbedding.train``, ``LogReg.Train``)
    call this, so a demo/test that runs training off the main thread
    stays within the guard's contract. Last registration wins — there is
    one training loop per process."""
    global _training_thread
    _training_thread = threading.get_ident()


@contextmanager
def allow_collective_dispatch(reason: str):
    """Explicit, documented sync point: allow tagged entry points on the
    current thread for the duration of the block. ``reason`` is required
    — it is the justification string a reviewer greps for."""
    if not reason:
        raise ValueError("allow_collective_dispatch requires a reason")
    depth = getattr(_tls, "allow_depth", 0)
    _tls.allow_depth = depth + 1
    try:
        yield
    finally:
        _tls.allow_depth = depth


def _check_dispatch_thread(entry: str) -> None:
    ident = threading.get_ident()
    with _comms_lock:
        if ident in _comms_threads:
            return
    if getattr(_tls, "allow_depth", 0) > 0:
        return
    if _training_thread is not None and ident == _training_thread:
        return
    cur = threading.current_thread()
    if cur is threading.main_thread():
        return
    raise GuardViolation(
        "collective_dispatch",
        f"{entry} dispatched from thread {cur.name!r} — collective table "
        "ops may only run on the TaskPipe comms worker or the training "
        "thread (concurrent multi-device dispatch can invert per-device "
        "launch order and deadlock XLA's rendezvous). Route the call "
        "through the comms TaskPipe, or wrap a documented sync point in "
        "allow_collective_dispatch(reason).",
        thread=cur.name,
        entry=entry,
    )


def collective_dispatch(fn):
    """Tag a table collective entry point (R1's ground truth). With
    ``-debug_thread_guards`` on, asserts the dispatching thread identity;
    otherwise the only cost is one flag read."""

    @wraps(fn)
    def wrapper(*args, **kwargs):
        if guards_enabled():
            _check_dispatch_thread(fn.__qualname__)
        if _mvtsan._ACTIVE:
            # mvtsan mirrors mvlint R9's credit: the thread-identity
            # guard serializes tagged entries, so table state touched
            # here holds the same VIRTUAL lock the static rule assumes
            with _mvtsan.virtual_lock("<collective_dispatch>"):
                return fn(*args, **kwargs)
        return fn(*args, **kwargs)

    wrapper.__mv_collective_dispatch__ = True
    return wrapper


# --------------------------------------------------------- lock-order guard

# process-wide acquisition-order graphs, both (held, acquired) -> first
# thread name: one over lock CLASS names, one over instance uids (two
# locks of the same class — e.g. every table's tier lock shares
# "tiered_table._tier_lock" — still need a consistent relative order)
_order_edges: Dict[Tuple[str, str], str] = {}
_order_edges_inst: Dict[Tuple[int, int], str] = {}
_order_mutex = threading.Lock()
_uid_counter = 0


def reset_lock_order_graph() -> None:
    """Test isolation: forget every recorded edge."""
    with _order_mutex:
        _order_edges.clear()
        _order_edges_inst.clear()


def _held_stack() -> list:
    stack = getattr(_tls, "lock_stack", None)
    if stack is None:
        stack = []
        _tls.lock_stack = stack
    return stack


class OrderedLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper that records the
    lock-acquisition order per thread and raises :class:`GuardViolation`
    on an inversion (A held while taking B after B-held-while-taking-A
    was ever recorded, in any thread) — across lock classes by NAME and
    across same-named instances by a process-unique uid, so two tables'
    tier locks nested in opposite orders are caught too. Flag off: pure
    delegation (the stack pop itself is unconditional, so toggling the
    flag while a lock is held cannot corrupt the held-stack)."""

    def __init__(self, name: str, recursive: bool = False):
        global _uid_counter
        self.name = name
        self._recursive = recursive
        self._lock = threading.RLock() if recursive else threading.Lock()
        # mvtsan happens-before cell: release publishes the holder's
        # vector clock here, acquire joins it (armed runs only)
        self._mv_sync = _mvtsan.SyncClock()
        with _order_mutex:
            _uid_counter += 1
            # never-reused (unlike id()): a GC'd lock's slot in the
            # instance-order graph must not be inherited by a new lock
            self._uid = _uid_counter

    def _raise_inversion(self, held_name: str, thread: str) -> None:
        raise GuardViolation(
            "lock_order",
            f"lock order inversion: acquiring {self.name!r} while "
            f"holding {held_name!r} on thread {thread!r}, but the "
            "opposite order was recorded earlier — a deadlock waiting "
            "for the losing interleaving. Pick one order (see "
            "analysis/RULES.md R2).",
            thread=thread,
            entry=self.name,
        )

    def _record(self) -> None:
        stack = _held_stack()  # entries: (name, uid)
        if any(uid == self._uid for _n, uid in stack):
            # true re-entry of THIS instance (recursive locks)
            stack.append((self.name, self._uid))
            return
        thread = threading.current_thread().name
        with _order_mutex:
            for held_name, held_uid in stack:
                if held_name != self.name:
                    if (self.name, held_name) in _order_edges:
                        self._raise_inversion(held_name, thread)
                    _order_edges.setdefault(
                        (held_name, self.name), thread
                    )
                else:
                    # same class, different instance: order by uid
                    if (self._uid, held_uid) in _order_edges_inst:
                        self._raise_inversion(
                            f"{held_name}#{held_uid}", thread
                        )
                    _order_edges_inst.setdefault(
                        (held_uid, self._uid), thread
                    )
        stack.append((self.name, self._uid))

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok and guards_enabled():
            try:
                self._record()
            except GuardViolation:
                self._lock.release()
                raise
        if ok and _mvtsan._ACTIVE:
            _mvtsan.lock_acquired(self._mv_sync, self.name, self._uid)
        return ok

    def release(self) -> None:
        # pop unconditionally: if the flag was disarmed while this lock
        # was held, the acquire-time stack entry must still come off, or
        # it would poison every later order check on this thread
        stack = getattr(_tls, "lock_stack", None)
        if stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][1] == self._uid:
                    del stack[i]
                    break
        if _mvtsan._ACTIVE:
            # publish while still holding: the next acquirer must see
            # every write made inside this critical section
            _mvtsan.lock_released(self._mv_sync, self.name, self._uid)
        self._lock.release()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()
