"""Double-buffered prefetcher + ordered comms executor.

TPU-native equivalent of the reference ASyncBuffer
(ref: include/multiverso/util/async_buffer.h:10-116): a background thread
fills the idle buffer via ``fill_buffer_action`` while the caller consumes
the ready one; ``Get()`` swaps. Used for pipelined model pulls
(sync_frequency / pipeline mode — ref:
Applications/LogisticRegression/src/model/ps_model.cpp:232-271) and block
prefetch in WordEmbedding. A fill-thread exception is STICKY: it re-raises
on the consumer's next ``Get()`` (and every one after), and ``Get()``
after ``Stop()`` raises cleanly — the consumer can never deadlock on (or
silently re-consume) a buffer whose producer died.

``TaskPipe`` is the pipelined-PS communicator thread (the reference's
Communicator + MtQueueMove handoff, communicator.cpp:117-249 running on its
own thread): a single background thread executing submitted thunks in
STRICT submission order. That ordering is the whole contract — every rank
submits the identical sequence of collective table ops (meta allgather,
pull, push), so the SPMD programs stay lockstep across processes while the
training thread overlaps device compute with them.

Failure domains (resilience subsystem): a ticket wait can be bounded
(``wait_result(deadline_s=...)``) and watchdog-aware — a collective that
exceeds its deadline, or a peer the heartbeat monitor declared dead,
raises a structured ``RankFailure`` on the waiting (training) thread
instead of blocking forever. The first such failure marks the pipe
*broken*: subsequent ``submit``/waits fail fast with ``PipelineBroken``
(poisoned-pipe containment), and ``drain()`` waits for every already-
submitted task to land so surviving ranks stop at a well-defined round
boundary.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Generic, Optional, TypeVar

import multiverso_tpu.analysis.mvtsan as _mvtsan
from multiverso_tpu.obs import tracer as _tracer

T = TypeVar("T")

__all__ = ["ASyncBuffer", "TaskPipe"]


class ASyncBuffer(Generic[T]):
    """``fill_buffer_action()`` produces the next value; ``Get()`` returns the
    ready value and kicks off the next fill in the background."""

    def __init__(self, fill_buffer_action: Callable[[], T],
                 name: str = "asyncbuffer"):
        self._fill = fill_buffer_action
        self._span_name = f"fill.{name}"
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._value: Optional[T] = None
        self._error: Optional[BaseException] = None
        self._stopped = False
        self._start_fill()

    def _start_fill(self) -> None:
        self._ready.clear()
        # mvtsan consumer→fill edge (armed runs): the fill closure
        # inherits everything the consumer did before kicking it off
        hb_to_fill = _mvtsan.publish() if _mvtsan._ACTIVE else None

        def run():
            _mvtsan.join(hb_to_fill)
            try:
                # obs: the fill thread's block-prep/prefetch work lands
                # on its own track in the span trace
                with _tracer.span(self._span_name):
                    value = self._fill()
                with self._lock:
                    self._value = value
            except BaseException as e:  # surfaced (sticky) on next Get()
                with self._lock:
                    self._error = e
            finally:
                if _mvtsan._ACTIVE:
                    # fill→Get edge: publish BEFORE releasing the
                    # consumer through _ready
                    self._mv_hb_from_fill = _mvtsan.publish()
                self._ready.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def Get(self) -> T:
        """Block until the in-flight fill completes, return it, and start
        prefetching the next one. A failed fill re-raises here — and on
        every later ``Get()`` (sticky): no stale value is ever served and
        no new fill is started after an error."""
        if self._stopped:
            raise RuntimeError("ASyncBuffer already stopped")
        self._ready.wait()
        if _mvtsan._ACTIVE:
            _mvtsan.join(getattr(self, "_mv_hb_from_fill", None))
        with self._lock:
            if self._error is not None:
                raise self._error
            value, self._value = self._value, None
        self._start_fill()
        return value

    def Stop(self) -> None:
        self._stopped = True
        self._thread.join(timeout=5)

    get = Get
    stop = Stop


class _Ticket:
    """Result handle for one ``TaskPipe`` submission."""

    __slots__ = ("_done", "_value", "_error", "_pipe", "tag",
                 "_mv_hb_submit", "_mv_hb_done")

    def __init__(self, pipe: Optional["TaskPipe"] = None, tag: str = ""):
        self._done = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._pipe = pipe
        self.tag = tag
        # mvtsan submit→run and run→wait_result edge payloads (clock
        # snapshots; None disarmed)
        self._mv_hb_submit = None
        self._mv_hb_done = None

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block until the task ran on the pipe thread; re-raise its
        exception there if it failed. Idempotent — a resolved ticket can
        be read any number of times."""
        if not self._done.wait(timeout):
            raise TimeoutError("TaskPipe task did not complete in time")
        if _mvtsan._ACTIVE:
            _mvtsan.join(self._mv_hb_done)
        if self._error is not None:
            raise self._error
        return self._value

    def wait_result(
        self,
        deadline_s: Optional[float] = None,
        watchdog=None,
        *,
        round_idx: int = -1,
        poll_s: float = 0.05,
        clock: Callable[[], float] = time.monotonic,
    ) -> Any:
        """Failure-domain-aware ``result()``: bounded by the per-ticket
        ``deadline_s`` and by the heartbeat ``watchdog`` — either firing
        marks the pipe broken and raises a structured ``RankFailure``
        here (the training thread) instead of blocking forever. A pipe
        already broken by an earlier failure fails fast with
        ``PipelineBroken``."""
        from multiverso_tpu.resilience.watchdog import (
            PipelineBroken,
            RankFailure,
            fd_stats,
        )

        start = clock()
        while True:
            if self._done.wait(poll_s):
                break
            pipe = self._pipe
            if pipe is not None and pipe.broken is not None:
                raise PipelineBroken(pipe.broken)
            if watchdog is not None:
                hb = watchdog.failed()
                if hb is not None:
                    rf = RankFailure(
                        hb.kind, f"peer lost while waiting on {self.tag!r}",
                        rank=hb.rank, round_idx=round_idx, cause=hb,
                    )
                    if pipe is not None:
                        pipe.break_pipe(rf)
                    raise rf
            if deadline_s is not None and clock() - start > deadline_s:
                rf = RankFailure(
                    "collective_timeout",
                    f"{self.tag or 'task'} exceeded its "
                    f"{deadline_s:.1f}s deadline",
                    round_idx=round_idx,
                )
                fd_stats.note_rank_failure("collective_timeout")
                if pipe is not None:
                    pipe.break_pipe(rf)
                raise rf
        fd_stats.note_ticket_wait(clock() - start)
        if _mvtsan._ACTIVE:
            _mvtsan.join(self._mv_hb_done)
        if self._error is not None:
            raise self._error
        return self._value

    def done(self) -> bool:
        return self._done.is_set()


class TaskPipe:
    """Single worker thread running submitted thunks strictly in
    submission order; ``submit`` returns a ticket whose ``result()``
    blocks and re-raises. Handoff rides the native ``MtQueue`` ticket
    ring (runtime.cpp — the reference's MtQueueMove; the queue's Python
    fallback engages when the native lib is absent). ``capacity`` bounds
    in-flight tasks: a full ring blocks ``submit`` (natural backpressure
    for a runaway producer)."""

    def __init__(self, capacity: int = 64, name: str = "mv-taskpipe"):
        from multiverso_tpu.native.host_runtime import MtQueue

        assert capacity >= 1
        self._ready: MtQueue = MtQueue()
        self._free: MtQueue = MtQueue()
        self._slots: list = [None] * capacity
        for i in range(capacity):
            self._free.push(i)
        self._closed = False
        self._broken: Optional[BaseException] = None
        self._inflight = 0
        self._state_lock = threading.Lock()
        self._idle = threading.Condition(self._state_lock)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name
        )
        self._thread.start()

    @property
    def broken(self) -> Optional[BaseException]:
        return self._broken

    def break_pipe(self, cause: BaseException) -> None:
        """Poisoned-pipe containment: mark the pipe broken (first cause
        wins, idempotent). Subsequent ``submit``/``wait_result`` calls
        fail fast with ``PipelineBroken`` instead of queueing work behind
        (or blocking on) a collective that will never resolve. The worker
        thread is NOT joined — it may be stuck inside a hung collective;
        already-queued tasks still run/fail and park on their tickets."""
        with self._state_lock:
            if self._broken is not None:
                return
            self._broken = cause
        from multiverso_tpu.resilience.watchdog import fd_stats

        fd_stats.note_broken_pipe()

    def _run(self) -> None:
        # the pipe worker IS the sanctioned collective-dispatch channel:
        # register with the runtime thread-identity guard (R1) so tagged
        # table entry points accept tasks executed here
        from multiverso_tpu.analysis.guards import (
            register_comms_thread,
            unregister_comms_thread,
        )

        register_comms_thread()
        try:
            self._run_loop()
        finally:
            unregister_comms_thread()

    def _run_loop(self) -> None:
        while True:
            slot = self._ready.pop()
            if slot is None:  # exit() drained — no more tasks can arrive
                return
            fn, ticket = self._slots[slot]
            self._slots[slot] = None
            self._free.push(slot)
            if _mvtsan._ACTIVE:
                # submit→run: the task sees everything its submitter did
                _mvtsan.join(ticket._mv_hb_submit)
            try:
                if _tracer.tracing_enabled():
                    # ticket execution on the comms worker: the span name
                    # is the tag's kind prefix ("pull:17" -> "pipe.pull")
                    # so the track stays low-cardinality; the full tag
                    # rides in args
                    kind = ticket.tag.split(":", 1)[0] if ticket.tag else ""
                    with _tracer.span(
                        f"pipe.{kind or 'task'}", tag=ticket.tag
                    ):
                        ticket._value = fn()
                else:
                    ticket._value = fn()
            except BaseException as e:  # surfaced at ticket.result()
                ticket._error = e
            finally:
                if _mvtsan._ACTIVE:
                    # run→wait_result: publish BEFORE releasing waiters
                    ticket._mv_hb_done = _mvtsan.publish()
                ticket._done.set()
                with self._idle:
                    self._inflight -= 1
                    if self._inflight == 0:
                        self._idle.notify_all()

    def submit(self, fn: Callable[[], Any], tag: str = "") -> _Ticket:
        if self._closed:
            raise RuntimeError("TaskPipe already closed")
        if self._broken is not None:
            from multiverso_tpu.resilience.watchdog import PipelineBroken

            raise PipelineBroken(self._broken)
        slot = self._free.pop()
        if slot is None:
            raise RuntimeError("TaskPipe torn down while submitting")
        return self._enqueue(slot, fn, tag)

    def submit_nowait(self, fn: Callable[[], Any], tag: str = "") -> Optional[_Ticket]:
        """Non-blocking ``submit`` for ADVISORY work — the tiered table's
        look-ahead prefetch tickets. A full ring, a broken pipe or a
        closed pipe returns ``None`` instead of blocking or raising:
        dropping a prefetch is always safe (the access path faults the
        rows in itself), and the prep thread must never stall behind a
        slow fault-in."""
        if self._closed or self._broken is not None:
            return None
        slot = self._free.try_pop()
        if slot is None:
            return None
        try:
            return self._enqueue(slot, fn, tag)
        except RuntimeError:
            # close() raced between the _closed check and the ready push
            # (ring already torn down): advisory work just drops
            return None

    def _enqueue(self, slot: int, fn: Callable[[], Any], tag: str) -> _Ticket:
        ticket = _Ticket(self, tag)
        if _mvtsan._ACTIVE:
            ticket._mv_hb_submit = _mvtsan.publish()
        self._slots[slot] = (fn, ticket)
        with self._idle:
            self._inflight += 1
        if not self._ready.push(slot):
            with self._idle:
                self._inflight -= 1
            raise RuntimeError("TaskPipe torn down while submitting")
        return ticket

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Wait until every already-submitted task has completed (landed
        or failed onto its ticket) — the consistent-round-boundary
        primitive: after a True return, all in-flight pushes have been
        applied and the table state sits at a well-defined boundary.
        Returns False when ``timeout_s`` expires first (a hung collective
        is still in flight)."""
        from multiverso_tpu.resilience.watchdog import fd_stats

        t0 = time.monotonic()
        deadline = None if timeout_s is None else t0 + timeout_s
        with self._idle:
            while self._inflight > 0:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    fd_stats.note_drain(time.monotonic() - t0, ok=False)
                    return False
                self._idle.wait(remaining if remaining is not None else 1.0)
        fd_stats.note_drain(time.monotonic() - t0, ok=True)
        return True

    def close(self, timeout_s: float = 60.0) -> None:
        """Drain every queued task, then stop the thread (idempotent).
        Exceptions from drained tasks stay parked on their tickets. On a
        broken pipe the join is best-effort under ``timeout_s`` — the
        worker may be stuck inside a hung collective."""
        with self._state_lock:
            # check-then-set under the lock: two racing close() calls
            # must not both run the teardown below
            if self._closed:
                return
            self._closed = True
        self._ready.exit()  # pop() returns queued items, then None
        self._thread.join(timeout=timeout_s)
