"""Double-buffered prefetcher + ordered comms executor.

TPU-native equivalent of the reference ASyncBuffer
(ref: include/multiverso/util/async_buffer.h:10-116): a background thread
fills the idle buffer via ``fill_buffer_action`` while the caller consumes
the ready one; ``Get()`` swaps. Used for pipelined model pulls
(sync_frequency / pipeline mode — ref:
Applications/LogisticRegression/src/model/ps_model.cpp:232-271) and block
prefetch in WordEmbedding.

``TaskPipe`` is the pipelined-PS communicator thread (the reference's
Communicator + MtQueueMove handoff, communicator.cpp:117-249 running on its
own thread): a single background thread executing submitted thunks in
STRICT submission order. That ordering is the whole contract — every rank
submits the identical sequence of collective table ops (meta allgather,
pull, push), so the SPMD programs stay lockstep across processes while the
training thread overlaps device compute with them.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Generic, Optional, TypeVar

T = TypeVar("T")

__all__ = ["ASyncBuffer", "TaskPipe"]


class ASyncBuffer(Generic[T]):
    """``fill_buffer_action()`` produces the next value; ``Get()`` returns the
    ready value and kicks off the next fill in the background."""

    def __init__(self, fill_buffer_action: Callable[[], T]):
        self._fill = fill_buffer_action
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._value: Optional[T] = None
        self._error: Optional[BaseException] = None
        self._stopped = False
        self._start_fill()

    def _start_fill(self) -> None:
        self._ready.clear()

        def run():
            try:
                value = self._fill()
                with self._lock:
                    self._value = value
            except BaseException as e:  # surfaced on next Get()
                with self._lock:
                    self._error = e
            finally:
                self._ready.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def Get(self) -> T:
        """Block until the in-flight fill completes, return it, and start
        prefetching the next one."""
        if self._stopped:
            raise RuntimeError("ASyncBuffer already stopped")
        self._ready.wait()
        with self._lock:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            value = self._value
        self._start_fill()
        return value

    def Stop(self) -> None:
        self._stopped = True
        self._thread.join(timeout=5)

    get = Get
    stop = Stop


class _Ticket:
    """Result handle for one ``TaskPipe`` submission."""

    __slots__ = ("_done", "_value", "_error")

    def __init__(self):
        self._done = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block until the task ran on the pipe thread; re-raise its
        exception there if it failed."""
        if not self._done.wait(timeout):
            raise TimeoutError("TaskPipe task did not complete in time")
        if self._error is not None:
            raise self._error
        return self._value

    def done(self) -> bool:
        return self._done.is_set()


class TaskPipe:
    """Single worker thread running submitted thunks strictly in
    submission order; ``submit`` returns a ticket whose ``result()``
    blocks and re-raises. Handoff rides the native ``MtQueue`` ticket
    ring (runtime.cpp — the reference's MtQueueMove; the queue's Python
    fallback engages when the native lib is absent). ``capacity`` bounds
    in-flight tasks: a full ring blocks ``submit`` (natural backpressure
    for a runaway producer)."""

    def __init__(self, capacity: int = 64, name: str = "mv-taskpipe"):
        from multiverso_tpu.native.host_runtime import MtQueue

        assert capacity >= 1
        self._ready: MtQueue = MtQueue()
        self._free: MtQueue = MtQueue()
        self._slots: list = [None] * capacity
        for i in range(capacity):
            self._free.push(i)
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            slot = self._ready.pop()
            if slot is None:  # exit() drained — no more tasks can arrive
                return
            fn, ticket = self._slots[slot]
            self._slots[slot] = None
            self._free.push(slot)
            try:
                ticket._value = fn()
            except BaseException as e:  # surfaced at ticket.result()
                ticket._error = e
            finally:
                ticket._done.set()

    def submit(self, fn: Callable[[], Any]) -> _Ticket:
        if self._closed:
            raise RuntimeError("TaskPipe already closed")
        ticket = _Ticket()
        slot = self._free.pop()
        if slot is None:
            raise RuntimeError("TaskPipe torn down while submitting")
        self._slots[slot] = (fn, ticket)
        if not self._ready.push(slot):
            raise RuntimeError("TaskPipe torn down while submitting")
        return ticket

    def close(self) -> None:
        """Drain every queued task, then stop the thread (idempotent).
        Exceptions from drained tasks stay parked on their tickets."""
        if self._closed:
            return
        self._closed = True
        self._ready.exit()  # pop() returns queued items, then None
        self._thread.join(timeout=60)
