"""Double-buffered prefetcher.

TPU-native equivalent of the reference ASyncBuffer
(ref: include/multiverso/util/async_buffer.h:10-116): a background thread
fills the idle buffer via ``fill_buffer_action`` while the caller consumes
the ready one; ``Get()`` swaps. Used for pipelined model pulls
(sync_frequency / pipeline mode — ref:
Applications/LogisticRegression/src/model/ps_model.cpp:232-271) and block
prefetch in WordEmbedding.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")

__all__ = ["ASyncBuffer"]


class ASyncBuffer(Generic[T]):
    """``fill_buffer_action()`` produces the next value; ``Get()`` returns the
    ready value and kicks off the next fill in the background."""

    def __init__(self, fill_buffer_action: Callable[[], T]):
        self._fill = fill_buffer_action
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._value: Optional[T] = None
        self._error: Optional[BaseException] = None
        self._stopped = False
        self._start_fill()

    def _start_fill(self) -> None:
        self._ready.clear()

        def run():
            try:
                value = self._fill()
                with self._lock:
                    self._value = value
            except BaseException as e:  # surfaced on next Get()
                with self._lock:
                    self._error = e
            finally:
                self._ready.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def Get(self) -> T:
        """Block until the in-flight fill completes, return it, and start
        prefetching the next one."""
        if self._stopped:
            raise RuntimeError("ASyncBuffer already stopped")
        self._ready.wait()
        with self._lock:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            value = self._value
        self._start_fill()
        return value

    def Stop(self) -> None:
        self._stopped = True
        self._thread.join(timeout=5)

    get = Get
    stop = Stop
