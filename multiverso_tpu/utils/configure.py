"""Typed flag / configuration registry.

TPU-native equivalent of the reference's gflags-clone
(ref: include/multiverso/util/configure.h:13-114, src/util/configure.cpp:9-54).
Semantics preserved:

* typed flag declaration via ``MV_DEFINE_int/bool/string/double`` (one registry
  per type in the reference; a single typed registry here),
* ``ParseCMDFlags(argv)`` consumes ``-key=value`` entries and *compacts* the
  argv, returning only the entries it did not recognise
  (ref: src/util/configure.cpp:19-53),
* programmatic override via ``SetCMDFlag`` / ``MV_SetFlag``
  (ref: include/multiverso/multiverso.h:31-33).

Unlike the reference there is no static-initialisation-order dance: flags are
declared at import time of the defining module.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "MV_DEFINE_int",
    "MV_DEFINE_bool",
    "MV_DEFINE_string",
    "MV_DEFINE_double",
    "ParseCMDFlags",
    "GetFlag",
    "SetCMDFlag",
    "ResetFlagsToDefault",
    "AllFlags",
    "mutation_count",
]


class _Flag:
    __slots__ = ("name", "default", "value", "type", "help")

    def __init__(self, name: str, default: Any, type_: type, help_: str):
        self.name = name
        self.default = default
        self.value = default
        self.type = type_
        self.help = help_


_lock = threading.Lock()
_registry: Dict[str, _Flag] = {}
# bumped on every mutation (define/set/parse/reset); lets hot paths cache
# a flag value lock-free and re-read only when something actually changed
_generation = 0


def mutation_count() -> int:
    return _generation


def _bump() -> None:
    global _generation
    _generation += 1


def _define(name: str, default: Any, type_: type, help_: str) -> None:
    with _lock:
        existing = _registry.get(name)
        if existing is not None:
            if existing.type is not type_:
                raise ValueError(
                    f"flag {name!r} redefined with different type "
                    f"({existing.type.__name__} vs {type_.__name__})"
                )
            return  # idempotent re-definition (module reloads)
        _registry[name] = _Flag(name, default, type_, help_)
        _bump()


def MV_DEFINE_int(name: str, default: int = 0, help: str = "") -> None:
    _define(name, int(default), int, help)


def MV_DEFINE_bool(name: str, default: bool = False, help: str = "") -> None:
    _define(name, bool(default), bool, help)


def MV_DEFINE_string(name: str, default: str = "", help: str = "") -> None:
    _define(name, str(default), str, help)


def MV_DEFINE_double(name: str, default: float = 0.0, help: str = "") -> None:
    _define(name, float(default), float, help)


def _coerce(flag: _Flag, raw: Any) -> Any:
    if flag.type is bool:
        if isinstance(raw, str):
            low = raw.strip().lower()
            if low in ("true", "1", "yes", "on"):
                return True
            if low in ("false", "0", "no", "off"):
                return False
            raise ValueError(f"cannot parse {raw!r} as bool for flag {flag.name!r}")
        return bool(raw)
    return flag.type(raw)


def GetFlag(name: str, default: Optional[Any] = None) -> Any:
    with _lock:
        flag = _registry.get(name)
        if flag is None:
            if default is not None:
                return default
            raise KeyError(f"unknown flag {name!r}")
        return flag.value


def SetCMDFlag(name: str, value: Any) -> None:
    """Programmatic flag override (ref: configure.h:86-90, multiverso.h:31-33)."""
    with _lock:
        flag = _registry.get(name)
        if flag is None:
            raise KeyError(f"unknown flag {name!r}")
        flag.value = _coerce(flag, value)
        _bump()


def ParseCMDFlags(argv: Optional[Sequence[str]]) -> List[str]:
    """Consume ``-key=value`` entries; return the compacted remainder.

    Mirrors the reference's argv-compacting parse loop
    (ref: src/util/configure.cpp:19-53): entries that look like ``-key=value``
    (or ``--key=value``) for a *registered* key are consumed; everything else
    is passed through in order.
    """
    if argv is None:
        return []
    remaining: List[str] = []
    for arg in argv:
        consumed = False
        if isinstance(arg, str) and arg.startswith("-") and "=" in arg:
            body = arg.lstrip("-")
            key, _, val = body.partition("=")
            with _lock:
                flag = _registry.get(key)
                if flag is not None:
                    flag.value = _coerce(flag, val)
                    _bump()
                    consumed = True
        if not consumed:
            remaining.append(arg)
    return remaining


def ResetFlagsToDefault() -> None:
    """Restore every flag to its declared default (test isolation helper)."""
    with _lock:
        for flag in _registry.values():
            flag.value = flag.default
        _bump()


def AllFlags() -> Dict[str, Any]:
    with _lock:
        return {name: f.value for name, f in _registry.items()}
