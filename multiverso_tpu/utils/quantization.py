"""Sparse wire/storage compression.

TPU-native equivalent of the reference SparseFilter
(ref: include/multiverso/util/quantization_util.h:10-158): per-blob, if more
than half the entries are zero, rewrite as (index, value) pairs plus a size
header; ``FilterIn`` compresses, ``FilterOut`` restores. On TPU there is no
wire between workers and servers, so this is used for checkpoint/export
compaction and for the C-API/IPC boundary. (The reference's declared-but-empty
``OneBitsFilter`` — quantization_util.h:160-161 — is intentionally absent.)
"""

from __future__ import annotations

from typing import Tuple, Union

import numpy as np

__all__ = ["SparseFilter"]

Dense = np.ndarray
Compressed = Tuple[str, tuple, np.ndarray, np.ndarray]  # ("sparse", shape, idx, vals)


class SparseFilter:
    """Compress arrays that are >50% zeros into (idx, val) pairs."""

    @staticmethod
    def filter_in(arr: np.ndarray) -> Union[Dense, Compressed]:
        arr = np.asarray(arr)
        flat = arr.reshape(-1)
        nz = np.flatnonzero(flat)
        if nz.size * 2 >= flat.size:  # not sparse enough — pass through
            return arr
        return ("sparse", arr.shape, nz.astype(np.int64), flat[nz].copy())

    @staticmethod
    def filter_out(data: Union[Dense, Compressed]) -> np.ndarray:
        if isinstance(data, np.ndarray):
            return data
        tag, shape, idx, vals = data
        assert tag == "sparse"
        flat = np.zeros(int(np.prod(shape)), vals.dtype)
        flat[idx] = vals
        return flat.reshape(shape)

    # reference-style aliases
    FilterIn = filter_in
    FilterOut = filter_out
