"""Sparse / 1-bit wire and storage compression.

TPU-native equivalent of the reference SparseFilter
(ref: include/multiverso/util/quantization_util.h:10-158): per-blob, if more
than half the entries are zero, rewrite as (index, value) pairs plus a size
header; ``FilterIn`` compresses, ``FilterOut`` restores. On TPU there is no
wire between workers and servers, so this is used for checkpoint/export
compaction and for the C-API/IPC boundary.

``OneBitsFilter`` implements the filter the reference declares but leaves
empty (quantization_util.h:160-161): 1-bit SGD gradient compression — each
entry reduced to its sign, scaled by the mean absolute value of its sign
class, with the quantization error fed back into the next round (Seide et
al.'s error-feedback scheme, the standard completion of the reference's
stub). 32x smaller payloads for delta pushes over DCN/IPC.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

__all__ = ["SparseFilter", "OneBitsFilter"]

Dense = np.ndarray
Compressed = Tuple[str, tuple, np.ndarray, np.ndarray]  # ("sparse", shape, idx, vals)


class SparseFilter:
    """Compress arrays that are >50% zeros into (idx, val) pairs."""

    @staticmethod
    def filter_in(arr: np.ndarray) -> Union[Dense, Compressed]:
        arr = np.asarray(arr)
        flat = arr.reshape(-1)
        nz = np.flatnonzero(flat)
        if nz.size * 2 >= flat.size:  # not sparse enough — pass through
            return arr
        return ("sparse", arr.shape, nz.astype(np.int64), flat[nz].copy())

    @staticmethod
    def filter_out(data: Union[Dense, Compressed]) -> np.ndarray:
        if isinstance(data, np.ndarray):
            return data
        tag, shape, idx, vals = data
        assert tag == "sparse"
        flat = np.zeros(int(np.prod(shape)), vals.dtype)
        flat[idx] = vals
        return flat.reshape(shape)

    # reference-style aliases
    FilterIn = filter_in
    FilterOut = filter_out


OneBit = Tuple[str, tuple, np.ndarray, float, float]  # ("1bit", shape, bits, pos_scale, neg_scale)


class OneBitsFilter:
    """1-bit gradient compression with error feedback.

    Stateful per stream: construct one filter per delta stream (e.g. per
    table); ``filter_in`` adds the carried quantization residual before
    quantizing and retains the new residual, so the long-run updates are
    unbiased. ``filter_out`` is stateless decompression.
    """

    def __init__(self):
        self._residual: Optional[np.ndarray] = None

    def filter_in(self, arr: np.ndarray) -> OneBit:
        arr = np.asarray(arr, np.float32)
        if self._residual is None:
            self._residual = np.zeros_like(arr)
        if self._residual.shape != arr.shape:
            raise ValueError(
                f"OneBitsFilter stream shape changed: {self._residual.shape} "
                f"-> {arr.shape}; use one filter per delta stream"
            )
        x = arr + self._residual
        pos = x >= 0
        # per-sign-class mean magnitude minimizes L2 quantization error
        pos_scale = float(x[pos].mean()) if pos.any() else 0.0
        neg_scale = float(x[~pos].mean()) if (~pos).any() else 0.0
        deq = np.where(pos, pos_scale, neg_scale).astype(np.float32)
        self._residual = x - deq
        bits = np.packbits(pos.reshape(-1))
        return ("1bit", arr.shape, bits, pos_scale, neg_scale)

    @staticmethod
    def filter_out(data: OneBit) -> np.ndarray:
        tag, shape, bits, pos_scale, neg_scale = data
        assert tag == "1bit"
        n = int(np.prod(shape))
        pos = np.unpackbits(bits)[:n].astype(bool)
        return np.where(pos, np.float32(pos_scale), np.float32(neg_scale)).reshape(shape)

    # reference-style aliases
    FilterIn = filter_in
    FilterOut = filter_out
