"""Sparse / 1-bit wire and storage compression.

TPU-native equivalent of the reference SparseFilter
(ref: include/multiverso/util/quantization_util.h:10-158): per-blob, if more
than half the entries are zero, rewrite as (index, value) pairs plus a size
header; ``FilterIn`` compresses, ``FilterOut`` restores. On TPU there is no
server wire, but the host<->device PCIe link and the cross-process
collective transport are real wires — the PS push path
(``-ps_compress=sparse|1bit``) moves exactly these payloads.

``OneBitsFilter`` implements the filter the reference declares but leaves
empty (quantization_util.h:160-161): 1-bit SGD gradient compression — each
entry reduced to its sign, scaled by the mean absolute value of its sign
class, with the quantization error fed back into the next round (Seide et
al.'s error-feedback scheme, the standard completion of the reference's
stub). 32x smaller payloads for delta pushes over DCN/IPC.

Two layers:

* the original host-side numpy filters (``SparseFilter``/``OneBitsFilter``)
  — checkpoint/export compaction and the C-API/IPC boundary;
* jit-traceable device kernels (``onebit_pack_jnp``/``onebit_unpack_jnp``,
  ``sparse_pack_jnp``/``sparse_unpack_jnp``) sharing the numpy filters' bit
  and (idx, val) layouts, so either side can decode the other. These run
  INSIDE jitted programs — the pipelined PS push packs deltas on device
  (compression never stalls the host) and the table unpacks inside its
  scatter program, so only packed bytes cross the wire.
  ``DeltaCodec`` wraps them per delta stream with a device-resident
  per-row error-feedback residual for the 1-bit mode.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

__all__ = [
    "SparseFilter",
    "OneBitsFilter",
    "onebit_pack_jnp",
    "onebit_unpack_jnp",
    "sparse_pack_jnp",
    "sparse_unpack_jnp",
    "DeltaCodec",
]

Dense = np.ndarray
Compressed = Tuple[str, tuple, np.ndarray, np.ndarray]  # ("sparse", shape, idx, vals)


class SparseFilter:
    """Compress arrays that are >50% zeros into (idx, val) pairs."""

    @staticmethod
    def filter_in(arr: np.ndarray) -> Union[Dense, Compressed]:
        arr = np.asarray(arr)
        flat = arr.reshape(-1)
        nz = np.flatnonzero(flat)
        if nz.size * 2 >= flat.size:  # not sparse enough — pass through
            return arr
        return ("sparse", arr.shape, nz.astype(np.int64), flat[nz].copy())

    @staticmethod
    def filter_out(data: Union[Dense, Compressed]) -> np.ndarray:
        if isinstance(data, np.ndarray):
            return data
        tag, shape, idx, vals = data
        assert tag == "sparse"
        flat = np.zeros(int(np.prod(shape)), vals.dtype)
        flat[idx] = vals
        return flat.reshape(shape)

    # reference-style aliases
    FilterIn = filter_in
    FilterOut = filter_out


OneBit = Tuple[str, tuple, np.ndarray, float, float]  # ("1bit", shape, bits, pos_scale, neg_scale)


class OneBitsFilter:
    """1-bit gradient compression with error feedback.

    Stateful per stream: construct one filter per delta stream (e.g. per
    table); ``filter_in`` adds the carried quantization residual before
    quantizing and retains the new residual, so the long-run updates are
    unbiased. ``filter_out`` is stateless decompression.
    """

    def __init__(self):
        self._residual: Optional[np.ndarray] = None

    def filter_in(self, arr: np.ndarray) -> OneBit:
        arr = np.asarray(arr, np.float32)
        if self._residual is None:
            self._residual = np.zeros_like(arr)
        if self._residual.shape != arr.shape:
            raise ValueError(
                f"OneBitsFilter stream shape changed: {self._residual.shape} "
                f"-> {arr.shape}; use one filter per delta stream"
            )
        x = arr + self._residual
        pos = x >= 0
        # per-sign-class mean magnitude minimizes L2 quantization error
        pos_scale = float(x[pos].mean()) if pos.any() else 0.0
        neg_scale = float(x[~pos].mean()) if (~pos).any() else 0.0
        deq = np.where(pos, pos_scale, neg_scale).astype(np.float32)
        self._residual = x - deq
        bits = np.packbits(pos.reshape(-1))
        return ("1bit", arr.shape, bits, pos_scale, neg_scale)

    @staticmethod
    def filter_out(data: OneBit) -> np.ndarray:
        tag, shape, bits, pos_scale, neg_scale = data
        assert tag == "1bit"
        n = int(np.prod(shape))
        pos = np.unpackbits(bits)[:n].astype(bool)
        return np.where(pos, np.float32(pos_scale), np.float32(neg_scale)).reshape(shape)

    # reference-style aliases
    FilterIn = filter_in
    FilterOut = filter_out


# --------------------------------------------------------------------------
# Device-side (jit-traceable) kernels.
#
# Bit/value layouts match the numpy filters above exactly (packbits is
# MSB-first; sparse is ascending (idx, val) pairs), so a device-packed
# payload decodes with the host filters and vice versa. All of these are
# pure jnp and safe to call INSIDE other jitted programs — the PS tables
# unpack inside their scatter programs so only packed bytes cross the
# host<->device / cross-process wire.
# --------------------------------------------------------------------------

_BIT_WEIGHTS = np.array([128, 64, 32, 16, 8, 4, 2, 1], np.uint8)  # MSB-first


def onebit_pack_jnp(x, valid=None):
    """Trace-safe 1-bit pack of ``x`` (any shape): returns
    ``(bits u8[ceil(n/8)], pos_scale f32, neg_scale f32)``. ``valid`` —
    optional flat-broadcastable 0/1 mask; masked-out elements are excluded
    from the scale means and packed as sign-positive (callers re-mask after
    decode — ``onebit_unpack_jnp`` cannot know the mask)."""
    import jax.numpy as jnp

    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    if valid is None:
        v = jnp.ones((n,), jnp.float32)
    else:
        v = valid.reshape(-1).astype(jnp.float32)
    pos = (flat >= 0).astype(jnp.float32) * v
    neg = (1.0 - (flat >= 0)) * v
    # per-sign-class mean magnitude minimizes L2 quantization error
    pos_scale = jnp.sum(flat * pos) / jnp.maximum(jnp.sum(pos), 1.0)
    neg_scale = jnp.sum(flat * neg) / jnp.maximum(jnp.sum(neg), 1.0)
    npad = -(-n // 8) * 8
    bitsrc = jnp.pad((flat >= 0).astype(jnp.uint8), (0, npad - n))
    bits = jnp.sum(
        bitsrc.reshape(-1, 8) * jnp.asarray(_BIT_WEIGHTS), axis=1
    ).astype(jnp.uint8)
    return bits, pos_scale, neg_scale


def onebit_unpack_jnp(bits, pos_scale, neg_scale, n):
    """Trace-safe 1-bit decode: flat (n,) f32 of the two scale values
    (``n`` static). Inverse of ``onebit_pack_jnp`` / ``OneBitsFilter``'s
    bit layout."""
    import jax.numpy as jnp

    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    expanded = (bits[:, None] >> shifts) & jnp.uint8(1)
    posmask = expanded.reshape(-1)[:n].astype(jnp.bool_)
    return jnp.where(
        posmask,
        jnp.asarray(pos_scale, jnp.float32),
        jnp.asarray(neg_scale, jnp.float32),
    )


def sparse_pack_jnp(x, cap):
    """Trace-safe sparse pack: ``(count i32, idx i32[cap], vals f32[cap])``
    of the nonzero entries of flat ``x`` (ascending idx, the SparseFilter
    pair layout; padding slots carry idx 0 / val 0). ``cap`` is static —
    callers size it from a counted readback; entries past ``cap`` are
    DROPPED, so cap must be >= the nonzero count for a lossless
    round-trip."""
    import jax.numpy as jnp

    flat = x.reshape(-1).astype(jnp.float32)
    count = jnp.count_nonzero(flat).astype(jnp.int32)
    (idx,) = jnp.nonzero(flat, size=cap, fill_value=0)
    idx = idx.astype(jnp.int32)
    live = jnp.arange(cap, dtype=jnp.int32) < jnp.minimum(count, cap)
    vals = flat[idx] * live.astype(jnp.float32)
    return count, idx, vals


def sparse_unpack_jnp(idx, vals, n):
    """Trace-safe sparse decode to a flat (n,) f32 (``n`` static).
    Padding pairs are (0, 0.0) so a scatter-ADD restores exactly."""
    import jax.numpy as jnp

    return jnp.zeros((n,), jnp.float32).at[idx].add(vals)


def payload_nbytes(payload) -> int:
    """Wire footprint of an encoded payload (array bytes + 8 per scalar
    field) — the byte counters the ps_comms dashboard reports."""
    if isinstance(payload, np.ndarray):
        return payload.nbytes
    tag = payload[0]
    if tag == "dense":
        return payload[1].nbytes
    if tag == "sparse":
        _, _shape, idx, vals, _count = payload
        return int(idx.nbytes + vals.nbytes + 8)
    if tag == "1bit":
        _, _shape, bits, _pos, _neg, _nrows = payload
        return int(bits.nbytes + 3 * 8)
    raise ValueError(f"unknown payload tag {tag!r}")


def decode_payload(payload) -> np.ndarray:
    """Host-side decode of any push payload to a dense np.float32 array —
    what the PS client applies to its local row cache (the values match
    what the table's in-program unpack scatters, bit for bit)."""
    if isinstance(payload, np.ndarray):
        return payload
    tag = payload[0]
    if tag == "dense":
        return payload[1]
    if tag == "sparse":
        _, shape, idx, vals, count = payload
        flat = np.zeros(int(np.prod(shape)), np.float32)
        flat[idx[:count]] = vals[:count]
        return flat.reshape(shape)
    if tag == "1bit":
        _, shape, bits, pos, neg, nrows = payload
        dense = OneBitsFilter.filter_out(
            ("1bit", shape, bits, float(pos), float(neg))
        )
        dense[nrows:] = 0.0  # bucket padding rows carry no delta
        return dense
    raise ValueError(f"unknown payload tag {tag!r}")


class DeltaCodec:
    """Per-stream device-side delta encoder for PS push blocks.

    One codec per (table, direction) stream. ``encode`` runs the whole
    subtract / error-feedback / quantize pipeline in jitted device
    programs (cached per bucket shape) and returns a HOST payload tuple —
    the only device->host bytes moved are the packed ones:

    * ``mode='none'``   — passthrough ``("dense", (new-old)/denom)``;
    * ``mode='sparse'`` — SparseFilter layout when >50% of entries are
      zero, dense passthrough otherwise (one counted-scalar readback
      decides; lossless either way);
    * ``mode='1bit'``   — OneBitsFilter layout with a PERSISTENT
      device-resident per-row error-feedback residual (``(num_row, dim)``
      f32, Seide et al. 2014): each encode quantizes
      ``delta + residual[ids]`` and retains the new per-row error, so a
      row's long-run pushed sum stays unbiased even across rounds that
      touch it intermittently.

    Payload tuples are understood by ``MatrixTable.add_rows_local_packed``
    (in-program unpack before the scatter) and by ``decode_payload``
    (host cache update).
    """

    def __init__(self, mode: str, num_row: int = 0, dim: int = 0):
        assert mode in ("none", "sparse", "1bit"), mode
        self.mode = mode
        self._jits: dict = {}
        self._residual = None
        if mode == "1bit":
            assert num_row > 0 and dim > 0, "1bit codec needs (num_row, dim)"
            self._num_row, self._dim = int(num_row), int(dim)

    def _jit(self, key, build):
        fn = self._jits.get(key)
        if fn is None:
            fn = build()
            self._jits[key] = fn
        return fn

    # ------------------------------------------------------------- encode

    def encode(self, new_dev, old_dev, ids: np.ndarray, nrows: int,
               denom: float):
        """Encode ``(new - old) / denom`` for a padded row bucket.
        ``ids``/``nrows`` — the bucket's global row ids and its real
        (unpadded) row count; padding rows carry zero delta by
        construction and are masked out of 1-bit scales/residuals."""
        import jax
        import jax.numpy as jnp

        shape = tuple(new_dev.shape)
        if self.mode == "none":
            delta = (np.asarray(new_dev) - np.asarray(old_dev)) / denom
            return ("dense", delta.astype(np.float32))
        if self.mode == "sparse":
            count_fn = self._jit(("count", shape), lambda: jax.jit(
                lambda a, b: jnp.count_nonzero(
                    (a - b).astype(jnp.float32)
                ).astype(jnp.int32)
            ))
            nnz = int(count_fn(new_dev, old_dev))
            size = int(np.prod(shape))
            if nnz * 2 >= size:  # not sparse enough — dense passthrough
                diff_fn = self._jit(("diff", shape), lambda: jax.jit(
                    lambda a, b, d: (a - b).astype(jnp.float32) / d
                ))
                return (
                    "dense",
                    np.asarray(diff_fn(new_dev, old_dev, jnp.float32(denom))),
                )
            from multiverso_tpu.utils import next_pow2

            cap = max(8, next_pow2(max(nnz, 1)))
            pack_fn = self._jit(("pack", shape, cap), lambda: jax.jit(
                lambda a, b, d: sparse_pack_jnp(
                    (a - b).astype(jnp.float32) / d, cap
                )
            ))
            count, idx, vals = pack_fn(new_dev, old_dev, jnp.float32(denom))
            return (
                "sparse", shape, np.asarray(idx), np.asarray(vals), int(count)
            )
        # 1bit: error-feedback quantization against the persistent residual
        if self._residual is None:
            self._residual = jnp.zeros(
                (self._num_row, self._dim), jnp.float32
            )

        def build():
            nr = self._num_row

            def run(new, old, residual, ids_d, n, d):
                delta = (new - old).astype(jnp.float32) / d
                valid = (
                    jnp.arange(new.shape[0], dtype=jnp.int32) < n
                ).astype(jnp.float32)
                x = (delta + residual[ids_d]) * valid[:, None]
                vmask = jnp.broadcast_to(valid[:, None], x.shape)
                bits, pos_s, neg_s = onebit_pack_jnp(x, valid=vmask)
                deq = onebit_unpack_jnp(
                    bits, pos_s, neg_s, x.size
                ).reshape(x.shape) * vmask
                # padding slots scatter out of bounds -> dropped (id-0
                # duplicates would otherwise race on residual row 0)
                ids_clean = jnp.where(
                    jnp.arange(new.shape[0], dtype=jnp.int32) < n,
                    ids_d, nr,
                )
                residual = residual.at[ids_clean].set(x - deq, mode="drop")
                return bits, pos_s, neg_s, residual

            return jax.jit(run, donate_argnums=(2,))

        fn = self._jit(("1bit", shape), build)
        bits, pos_s, neg_s, self._residual = fn(
            new_dev, old_dev, self._residual,
            jnp.asarray(np.asarray(ids, np.int32)), jnp.int32(nrows),
            jnp.float32(denom),
        )
        return (
            "1bit", shape, np.asarray(bits), float(pos_s), float(neg_s),
            int(nrows),
        )
