"""Stopwatch timer (ref: include/multiverso/util/timer.h, src/timer.cpp)."""

from __future__ import annotations

import time

__all__ = ["Timer"]


class Timer:
    """Monotonic stopwatch; elapsed() in milliseconds like the reference."""

    def __init__(self):
        self._start = time.monotonic()

    def Start(self) -> None:
        self._start = time.monotonic()

    def elapse(self) -> float:
        """Elapsed milliseconds since Start()/construction."""
        return (time.monotonic() - self._start) * 1000.0

    # pythonic aliases
    start = Start
    elapsed_ms = elapse

    def elapsed_s(self) -> float:
        return time.monotonic() - self._start
