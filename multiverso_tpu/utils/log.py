"""Leveled logger + CHECK asserts.

TPU-native equivalent of the reference logger
(ref: include/multiverso/util/log.h:9-142, src/util/log.cpp).
Semantics preserved: Debug/Info/Error/Fatal levels with timestamped prefix,
optional file sink, kill-on-fatal toggle (here: raise ``FatalError`` instead of
``exit()`` so tests can assert on it), ``-logtostderr``-style control, and the
``CHECK`` / ``CHECK_NOTNULL`` macros (ref: util/log.h:10-18).
"""

from __future__ import annotations

import datetime
import enum
import io
import sys
import threading
from typing import Any, Optional

from multiverso_tpu.utils.configure import MV_DEFINE_bool, GetFlag

__all__ = ["LogLevel", "Log", "Logger", "FatalError", "CHECK", "CHECK_NOTNULL"]

MV_DEFINE_bool("logtostderr", False, "send log output to stderr instead of stdout")


class LogLevel(enum.IntEnum):
    Debug = 0
    Info = 1
    Error = 2
    Fatal = 3


class FatalError(RuntimeError):
    """Raised by Log.Fatal / failed CHECK (the reference calls exit(1))."""


class Logger:
    """Instance logger; the module-level ``Log`` wraps a process singleton."""

    def __init__(self, level: LogLevel = LogLevel.Info, file: Optional[str] = None):
        self._level = level
        self._lock = threading.Lock()
        self._file: Optional[io.TextIOBase] = None
        if file:
            self.ResetLogFile(file)

    def ResetLogLevel(self, level: LogLevel) -> None:
        self._level = level

    def ResetLogFile(self, filename: Optional[str]) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            if filename:
                self._file = open(filename, "a")

    def _write(self, level: LogLevel, fmt: str, *args: Any) -> None:
        if level < self._level:
            return
        msg = (fmt % args) if args else fmt
        stamp = datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S")
        line = f"[{level.name.upper()}] [{stamp}] {msg}"
        with self._lock:
            stream = sys.stderr if GetFlag("logtostderr") else sys.stdout
            print(line, file=stream, flush=True)
            if self._file is not None:
                self._file.write(line + "\n")
                self._file.flush()

    def Debug(self, fmt: str, *args: Any) -> None:
        self._write(LogLevel.Debug, fmt, *args)

    def Info(self, fmt: str, *args: Any) -> None:
        self._write(LogLevel.Info, fmt, *args)

    def Error(self, fmt: str, *args: Any) -> None:
        self._write(LogLevel.Error, fmt, *args)

    def Fatal(self, fmt: str, *args: Any) -> None:
        self._write(LogLevel.Fatal, fmt, *args)
        raise FatalError((fmt % args) if args else fmt)


class _LogSingleton:
    """Static-style facade, mirroring the reference's ``Log`` static class."""

    _logger = Logger()

    @classmethod
    def logger(cls) -> Logger:
        return cls._logger

    @classmethod
    def ResetLogLevel(cls, level: LogLevel) -> None:
        cls._logger.ResetLogLevel(level)

    @classmethod
    def ResetLogFile(cls, filename: Optional[str]) -> None:
        cls._logger.ResetLogFile(filename)

    @classmethod
    def Debug(cls, fmt: str, *args: Any) -> None:
        cls._logger.Debug(fmt, *args)

    @classmethod
    def Info(cls, fmt: str, *args: Any) -> None:
        cls._logger.Info(fmt, *args)

    @classmethod
    def Error(cls, fmt: str, *args: Any) -> None:
        cls._logger.Error(fmt, *args)

    @classmethod
    def Fatal(cls, fmt: str, *args: Any) -> None:
        cls._logger.Fatal(fmt, *args)


Log = _LogSingleton


def CHECK(condition: Any, message: str = "CHECK failed") -> None:
    """Fatal assert (ref: util/log.h:10-14)."""
    if not condition:
        Log.Fatal(message)


def CHECK_NOTNULL(pointer: Any, name: str = "value") -> Any:
    """Fatal assert on None (ref: util/log.h:15-18). Returns the value."""
    if pointer is None:
        Log.Fatal("%s must not be None", name)
    return pointer
