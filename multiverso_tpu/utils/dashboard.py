"""Monitor / Dashboard instrumentation.

TPU-native equivalent of the reference profiling dashboard
(ref: include/multiverso/dashboard.h:16-74, src/dashboard.cpp). Semantics
preserved: a process-wide name -> Monitor map where each Monitor accumulates
{count, total elapsed ms}; ``MONITOR_BEGIN/END(name)`` macro pairs become the
``monitor(name)`` context manager; ``Dashboard.Display()`` dumps everything.

Extension over the reference: ``monitor(name, trace=True)`` additionally opens
a ``jax.profiler.TraceAnnotation`` so the region shows up in TPU profiler
traces alongside the host-side timing.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

from multiverso_tpu.utils.timer import Timer

__all__ = ["Monitor", "Counter", "Dashboard", "monitor"]


class Counter:
    """Plain value accumulator (bytes moved, rows transferred, rounds run)
    — the Monitor's unit-less sibling for quantities that are not wall
    time. Process-global and cumulative, like Monitors: the pipelined PS
    loop mirrors its per-run wire-byte totals into the ``ps.*_bytes_wire``
    counters so ``Display()`` shows lifetime traffic next to the per-run
    ``ps_comms`` section."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.total += value

    @property
    def average(self) -> float:
        return self.total / self.count if self.count else 0.0

    def info_string(self) -> str:
        return (
            f"[Counter] {self.name}: count={self.count} "
            f"total={self.total:.0f} avg={self.average:.1f}"
        )


class Monitor:
    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.elapsed_ms = 0.0
        self._lock = threading.Lock()

    def add(self, elapsed_ms: float) -> None:
        with self._lock:
            self.count += 1
            self.elapsed_ms += elapsed_ms

    @property
    def average_ms(self) -> float:
        return self.elapsed_ms / self.count if self.count else 0.0

    def info_string(self) -> str:
        return (
            f"[Monitor] {self.name}: count={self.count} "
            f"total={self.elapsed_ms:.3f}ms avg={self.average_ms:.3f}ms"
        )


class Dashboard:
    """Static name -> Monitor registry (ref: dashboard.h:16-40).

    Extension: ``add_section(name, fn)`` registers a callable returning
    extra display lines — the serving subsystem plugs its histogram /
    QPS / shed report in through this, so ``Display()`` stays the one
    process-wide dump.

    Structured twin (obs subsystem): ``add_section(name, fn,
    snapshot=...)`` additionally registers a dict-valued snapshot
    callable; ``snapshots()`` collects them all, and
    ``obs.metrics`` renders that collection as Prometheus text at
    ``GET /metrics`` (and feeds the depth controller)."""

    _lock = threading.Lock()
    _monitors: Dict[str, Monitor] = {}
    _counters: Dict[str, Counter] = {}
    _sections: Dict[str, object] = {}  # name -> () -> List[str]
    _snapshots: Dict[str, object] = {}  # name -> () -> Dict

    @classmethod
    def get(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = Monitor(name)
                cls._monitors[name] = mon
            return mon

    @classmethod
    def counter(cls, name: str) -> Counter:
        with cls._lock:
            ctr = cls._counters.get(name)
            if ctr is None:
                ctr = Counter(name)
                cls._counters[name] = ctr
            return ctr

    @classmethod
    def add_section(cls, name: str, fn, snapshot=None) -> None:
        with cls._lock:
            cls._sections[name] = fn
            if snapshot is not None:
                cls._snapshots[name] = snapshot
            else:
                # re-registering without a snapshot drops any stale twin
                cls._snapshots.pop(name, None)

    @classmethod
    def remove_section(cls, name: str) -> None:
        with cls._lock:
            cls._sections.pop(name, None)
            cls._snapshots.pop(name, None)

    @classmethod
    def snapshots(cls) -> Dict[str, Dict]:
        """Every registered dict-valued section snapshot (the structured
        twin of ``Display()``). Snapshot callables run OUTSIDE the lock
        (they take their own); one failing section is skipped, never
        fatal — a broken stats provider must not take the scrape down."""
        with cls._lock:
            fns = list(cls._snapshots.items())
        out: Dict[str, Dict] = {}
        for name, fn in fns:
            try:
                snap = fn()
            except Exception:  # noqa: BLE001 — skip broken providers
                continue
            if isinstance(snap, dict):
                out[name] = snap
        return out

    @classmethod
    def core_metrics(cls) -> Dict[str, float]:
        """Monitors/Counters as one flat numeric dict (the ``core``
        metrics family): ``<name>_count`` / ``<name>_total_ms`` per
        Monitor, ``<name>_count`` / ``<name>_total`` per Counter."""
        with cls._lock:
            monitors = list(cls._monitors.values())
            counters = list(cls._counters.values())
        out: Dict[str, float] = {}
        for m in monitors:
            out[f"{m.name}_count"] = float(m.count)
            out[f"{m.name}_total_ms"] = float(m.elapsed_ms)
        for c in counters:
            out[f"{c.name}_count"] = float(c.count)
            out[f"{c.name}_total"] = float(c.total)
        return out

    @classmethod
    def Display(cls) -> str:
        with cls._lock:
            lines = [m.info_string() for m in cls._monitors.values()]
            lines.extend(c.info_string() for c in cls._counters.values())
            sections = list(cls._sections.values())
        for fn in sections:  # outside the lock: sections take their own
            lines.extend(fn())
        out = "\n".join(lines)
        if out:
            print(out, flush=True)
        return out

    @classmethod
    def Reset(cls) -> None:
        with cls._lock:
            cls._monitors.clear()
            cls._counters.clear()
            cls._sections.clear()
            cls._snapshots.clear()


@contextmanager
def monitor(name: str, trace: bool = False) -> Iterator[Monitor]:
    """MONITOR_BEGIN/END pair (ref: dashboard.h:61-74) as a context manager.

    With ``trace=True`` the region is also annotated in the JAX profiler
    timeline (device-side visibility; the host timing still lands in the
    Dashboard).
    """
    mon = Dashboard.get(name)
    timer = Timer()
    ann = None
    if trace:
        import jax.profiler  # deferred: keep dashboard importable without jax

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    try:
        yield mon
    finally:
        if ann is not None:
            ann.__exit__(None, None, None)
        mon.add(timer.elapse())
