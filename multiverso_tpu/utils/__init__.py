"""Utility layer: flags, logging, timing, instrumentation.

TPU-native rebuild of the reference utility layer
(ref: include/multiverso/util/, src/util/ — SURVEY.md §2.1/§2.5). The pieces
the TPU runtime makes obsolete are intentionally absent:

* ``MtQueue`` / ``Waiter`` / actor mailboxes — JAX's async dispatch already
  gives every table op a future-like handle (``jax.Array`` +
  ``block_until_ready``); there is no actor thread pool to feed. (The
  native ``MtQueue`` rebuild lives in ``native/host_runtime.py`` for the
  places that DO want a real blocking queue: the training prefetch
  pipeline and the serving batcher's ticket ring.)
* ``Allocator`` / ``Blob`` — buffers live in HBM and are managed by the XLA
  runtime allocator; host-side staging uses numpy.
* ``net_util`` — no sockets; the mesh fabric is ICI/DCN owned by XLA.
"""

from multiverso_tpu.utils.configure import (
    MV_DEFINE_bool,
    MV_DEFINE_double,
    MV_DEFINE_int,
    MV_DEFINE_string,
    GetFlag,
    ParseCMDFlags,
    SetCMDFlag,
)
from multiverso_tpu.utils.dashboard import Dashboard, Monitor, monitor
from multiverso_tpu.utils.log import CHECK, CHECK_NOTNULL, FatalError, Log, LogLevel, Logger
from multiverso_tpu.utils.timer import Timer


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1). ONE definition: KV-table
    growth and the serving padded-bucket rule both round with this."""
    p = 1
    while p < n:
        p <<= 1
    return p

__all__ = [
    "MV_DEFINE_bool",
    "MV_DEFINE_double",
    "MV_DEFINE_int",
    "MV_DEFINE_string",
    "GetFlag",
    "ParseCMDFlags",
    "SetCMDFlag",
    "Dashboard",
    "Monitor",
    "monitor",
    "CHECK",
    "CHECK_NOTNULL",
    "FatalError",
    "Log",
    "LogLevel",
    "Logger",
    "Timer",
    "next_pow2",
]
