"""Utility layer: flags, logging, timing, instrumentation.

TPU-native rebuild of the reference utility layer
(ref: include/multiverso/util/, src/util/ — SURVEY.md §2.1/§2.5). The pieces
the TPU runtime makes obsolete are intentionally absent:

* ``MtQueue`` / ``Waiter`` / actor mailboxes — JAX's async dispatch already
  gives every table op a future-like handle (``jax.Array`` +
  ``block_until_ready``); there is no actor thread pool to feed.
* ``Allocator`` / ``Blob`` — buffers live in HBM and are managed by the XLA
  runtime allocator; host-side staging uses numpy.
* ``net_util`` — no sockets; the mesh fabric is ICI/DCN owned by XLA.
"""

from multiverso_tpu.utils.configure import (
    MV_DEFINE_bool,
    MV_DEFINE_double,
    MV_DEFINE_int,
    MV_DEFINE_string,
    GetFlag,
    ParseCMDFlags,
    SetCMDFlag,
)
from multiverso_tpu.utils.dashboard import Dashboard, Monitor, monitor
from multiverso_tpu.utils.log import CHECK, CHECK_NOTNULL, FatalError, Log, LogLevel, Logger
from multiverso_tpu.utils.timer import Timer

__all__ = [
    "MV_DEFINE_bool",
    "MV_DEFINE_double",
    "MV_DEFINE_int",
    "MV_DEFINE_string",
    "GetFlag",
    "ParseCMDFlags",
    "SetCMDFlag",
    "Dashboard",
    "Monitor",
    "monitor",
    "CHECK",
    "CHECK_NOTNULL",
    "FatalError",
    "Log",
    "LogLevel",
    "Logger",
    "Timer",
]
