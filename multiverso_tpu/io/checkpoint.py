"""Sharded checkpoint / resume for the table store.

The reference defines per-table ``Serializable::Store/Load(Stream*)`` hooks
(ref: include/multiverso/table_interface.h:61-75) implemented as raw storage
dumps (ref: src/table/array_table.cpp:144-151, matrix_table.cpp:457-464), but
no core driver calls them (SURVEY.md §5) — apps roll their own. The TPU build
promotes checkpointing to a first-class subsystem:

* ``DenseTable.store/load`` (in tables/base.py) — single-file Stream-based
  dump/restore, Store/Load parity, including the reference LogReg's
  Load-as-Add mode (worker-0 delta injection — ref:
  Applications/LogisticRegression/src/model/ps_model.cpp:113-168);
* ``save_tables``/``restore_tables`` (here) — orbax-backed sharded
  checkpoint of every registered table's storage + optimizer slots: each
  device writes its own HBM shard, restore re-shards onto the live mesh.

**Crash consistency** (resilience subsystem): ``save_tables`` publishes
atomically — the whole payload (orbax tree, ``logical_shapes.json``
sidecar, KV npz dumps) lands in ``<dir>.tmp-<token>``, a fsynced
``MANIFEST.json`` seals it with per-file size+crc32 checksums, and one
rename makes it visible. A reader therefore never observes a torn
directory; ``load_arrays``/``restore_tables`` verify the manifest first
and die with ONE clear error naming the directory and the broken piece
instead of an orbax stack trace.
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from multiverso_tpu.resilience import checkpoint as rckpt
from multiverso_tpu.resilience.chaos import with_retries
from multiverso_tpu.runtime import runtime
from multiverso_tpu.utils.log import Log

__all__ = ["save_tables", "restore_tables", "load_arrays"]


def _dense_tables(tables: Optional[List[Any]]) -> List[Any]:
    from multiverso_tpu.tables.base import DenseTable

    if tables is None:
        tables = runtime().tables
    return [t for t in tables if isinstance(t, DenseTable)]


def _tree_of(tables: List[Any]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for t in tables:
        tree[f"table_{t.table_id}"] = {"storage": t.storage, "state": dict(t.state)}
    return tree


def _sync(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _shared_token() -> str:
    """One tmp-dir token every process agrees on (multi-process saves write
    shards into the SAME staging directory)."""
    if jax.process_count() == 1:
        return uuid.uuid4().hex[:8]
    from jax.experimental import multihost_utils

    tok = np.frombuffer(uuid.uuid4().bytes, np.uint8).copy()
    tok = np.asarray(multihost_utils.broadcast_one_to_all(tok))
    return bytes(tok.tolist()).hex()[:8]


def save_tables(
    directory: str,
    tables: Optional[List[Any]] = None,
    *,
    step: Optional[int] = None,
    meta: Optional[Dict] = None,
) -> str:
    """Write a crash-consistent sharded checkpoint of all (dense)
    registered tables; KV tables save alongside as npz (their index is
    host metadata). The directory appears atomically — write to
    ``<dir>.tmp-<token>``, seal with a checksummed ``MANIFEST.json``
    (carrying ``step``/``meta`` for elastic resume), rename. Returns the
    path."""
    import orbax.checkpoint as ocp

    from multiverso_tpu.tables.kv_table import KVTable

    directory = os.path.abspath(directory)
    parent = os.path.dirname(directory)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{directory}.tmp-{_shared_token()}"
    if jax.process_index() == 0 and os.path.exists(tmp):
        shutil.rmtree(tmp)  # corpse of a crashed save with our token (rare)
    _sync("mv_ckpt_stage")
    os.makedirs(tmp, exist_ok=True)
    dense = _dense_tables(tables)
    if dense:  # orbax rejects an empty pytree (KV-only checkpoints)
        ckptr = ocp.StandardCheckpointer()

        def _write():
            ckptr.save(os.path.join(tmp, "tables"), _tree_of(dense), force=True)
            ckptr.wait_until_finished()

        # transient-fs retry budget: a flaky NFS/gcsfuse write gets three
        # tries; a real failure still propagates (and leaves only a tmp
        # corpse — never a torn published checkpoint). SINGLE-process
        # only: the orbax save is a collective in multi-process runs, and
        # one rank retrying while its peers proceed to the sync points
        # would desync the pod's barrier sequence — there, one attempt,
        # fail loudly, relaunch the save collectively.
        attempts = 3 if jax.process_count() == 1 else 1
        with_retries(_write, attempts=attempts, base_delay_s=0.2,
                     max_delay_s=2.0, describe=f"checkpoint table write {tmp}")
        if jax.process_index() == 0:
            # logical shapes ride alongside: the orbax tree stores the
            # PHYSICAL shard-padded storage (what restore_tables maps
            # straight back onto live tables), but a serving consumer
            # must not see padding rows — load_arrays crops with this
            import json

            shapes = {f"table_{t.table_id}": list(t.shape) for t in dense}
            with open(os.path.join(tmp, "logical_shapes.json"), "w") as f:
                json.dump(shapes, f)
    all_tables = tables if tables is not None else runtime().tables
    for t in all_tables:
        if isinstance(t, KVTable):
            t.store(os.path.join(tmp, f"kv_{t.table_id}.npz"))
    _sync("mv_ckpt_written")
    if jax.process_index() == 0:
        rckpt.commit_atomic(tmp, directory, step=step, meta=meta)
    _sync("mv_ckpt_commit")
    Log.Info("checkpoint saved: %s (%d dense tables)", directory, len(dense))
    return directory


def _check_readable(directory: str) -> None:
    """Pre-flight: a manifest-sealed checkpoint must verify; a pre-manifest
    (legacy) directory must at least contain the orbax tree. Either way a
    bad directory dies HERE with one clear message, not inside orbax."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        Log.Fatal("checkpoint %s is incomplete or corrupt: not a directory",
                  directory)
    if os.path.exists(os.path.join(directory, rckpt.MANIFEST_NAME)):
        rckpt.require_valid(directory)


def _fatal_orbax(directory: str, what: str, exc: Exception) -> None:
    Log.Fatal(
        "checkpoint %s is incomplete or corrupt: %s (%s: %s)",
        directory, what, type(exc).__name__,
        str(exc).splitlines()[0] if str(exc) else "no detail",
    )


def load_arrays(directory: str) -> Dict[str, np.ndarray]:
    """Load-for-serving: restore the dense tables' raw storage arrays from
    a ``save_tables`` checkpoint WITHOUT live tables or a started runtime.

    ``restore_tables`` needs the creation-order table registry to exist
    (training resume); a serving process has no reason to rebuild
    updater state or register tables just to read weights. Returns
    ``{"table_<id>": storage}`` as host arrays, ready for
    ``TableServer.publish`` / ``restore`` (optimizer slots are restored
    by ``restore_tables`` only — serving reads weights, not momenta)."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    _check_readable(directory)
    path = os.path.join(directory, "tables")
    if not os.path.isdir(path):
        Log.Fatal(
            "checkpoint %s is incomplete or corrupt: missing the 'tables' "
            "orbax tree (dense-table payload)", directory,
        )
    ckptr = ocp.PyTreeCheckpointer()
    # no abstract target tree (no live arrays to mirror): read the stored
    # STRUCTURE, then restore only each table's 'storage' leaf as plain
    # numpy — serving never reads optimizer slots, and the g2/momentum
    # arrays are storage-sized, so a full-tree restore would move 2-3x
    # the bytes just to drop them; plain-numpy also keeps the load
    # topology-independent (the orbax sharding-file path is explicitly
    # unsafe across topologies)
    try:
        structure = ckptr.metadata(path)
        item = {k: {"storage": v["storage"]} for k, v in structure.items()}
        restore_args = {
            k: {"storage": ocp.RestoreArgs(restore_type=np.ndarray)}
            for k in structure
        }
        restored = ckptr.restore(
            path, item=item, restore_args=restore_args, transforms={}
        )
    except Exception as e:  # noqa: BLE001 — one clear error, not a stack dump
        _fatal_orbax(directory, "failed to read the 'tables' orbax tree", e)
    # crop shard padding: the stored storage is physical (dim 0 padded up
    # to a shard multiple); serving phantom zero rows would corrupt top-k
    # (padding ids outscore real rows at negative cosine) and let
    # out-of-range lookups pass the range check. Checkpoints written
    # before the sidecar existed load uncropped (physical == best known).
    import json

    meta_path = os.path.join(directory, "logical_shapes.json")
    logical = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            logical = json.load(f)
    out: Dict[str, np.ndarray] = {}
    for key, entry in restored.items():
        arr = np.asarray(entry["storage"])
        shape = logical.get(key)
        if shape is not None:
            arr = arr[tuple(slice(0, s) for s in shape)]
        out[key] = arr
    Log.Info("checkpoint arrays loaded for serving: %s (%d tables)",
             directory, len(out))
    return out


def restore_tables(directory: str, tables: Optional[List[Any]] = None) -> None:
    """Restore a checkpoint into the live (already-created) tables: creation
    order defines table ids, exactly like the reference's registration
    protocol, so shapes/updaters must match."""
    import orbax.checkpoint as ocp

    from multiverso_tpu.tables.kv_table import KVTable

    directory = os.path.abspath(directory)
    _check_readable(directory)
    dense = _dense_tables(tables)
    if dense:
        target = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            _tree_of(dense),
        )
        ckptr = ocp.StandardCheckpointer()
        try:
            restored = ckptr.restore(os.path.join(directory, "tables"), target)
        except Exception as e:  # noqa: BLE001 — one clear error
            _fatal_orbax(directory, "failed to restore the 'tables' orbax tree", e)
        for t in dense:
            entry = restored[f"table_{t.table_id}"]
            t.storage = entry["storage"]
            t.state = dict(entry["state"])
    all_tables = tables if tables is not None else runtime().tables
    for t in all_tables:
        if isinstance(t, KVTable):
            path = os.path.join(directory, f"kv_{t.table_id}.npz")
            if os.path.exists(path):
                t.load(path)
    Log.Info("checkpoint restored: %s (%d dense tables)", directory, len(dense))
