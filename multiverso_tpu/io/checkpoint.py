"""Sharded checkpoint / resume for the table store.

The reference defines per-table ``Serializable::Store/Load(Stream*)`` hooks
(ref: include/multiverso/table_interface.h:61-75) implemented as raw storage
dumps (ref: src/table/array_table.cpp:144-151, matrix_table.cpp:457-464), but
no core driver calls them (SURVEY.md §5) — apps roll their own. The TPU build
promotes checkpointing to a first-class subsystem:

* ``DenseTable.store/load`` (in tables/base.py) — single-file Stream-based
  dump/restore, Store/Load parity, including the reference LogReg's
  Load-as-Add mode (worker-0 delta injection — ref:
  Applications/LogisticRegression/src/model/ps_model.cpp:113-168);
* ``save_tables``/``restore_tables`` (here) — orbax-backed sharded
  checkpoint of every registered table's storage + optimizer slots: each
  device writes its own HBM shard, restore re-shards onto the live mesh.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from multiverso_tpu.runtime import runtime
from multiverso_tpu.utils.log import Log

__all__ = ["save_tables", "restore_tables", "load_arrays"]


def _dense_tables(tables: Optional[List[Any]]) -> List[Any]:
    from multiverso_tpu.tables.base import DenseTable

    if tables is None:
        tables = runtime().tables
    return [t for t in tables if isinstance(t, DenseTable)]


def _tree_of(tables: List[Any]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for t in tables:
        tree[f"table_{t.table_id}"] = {"storage": t.storage, "state": dict(t.state)}
    return tree


def save_tables(directory: str, tables: Optional[List[Any]] = None) -> str:
    """Write a sharded checkpoint of all (dense) registered tables. KV tables
    save alongside as npz (their index is host metadata). Returns the path."""
    import orbax.checkpoint as ocp

    from multiverso_tpu.tables.kv_table import KVTable

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    dense = _dense_tables(tables)
    if dense:  # orbax rejects an empty pytree (KV-only checkpoints)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(directory, "tables"), _tree_of(dense), force=True)
        ckptr.wait_until_finished()
        if jax.process_index() == 0:
            # logical shapes ride alongside: the orbax tree stores the
            # PHYSICAL shard-padded storage (what restore_tables maps
            # straight back onto live tables), but a serving consumer
            # must not see padding rows — load_arrays crops with this
            import json

            meta = {
                f"table_{t.table_id}": list(t.shape) for t in dense
            }
            with open(os.path.join(directory, "logical_shapes.json"), "w") as f:
                json.dump(meta, f)
    all_tables = tables if tables is not None else runtime().tables
    for t in all_tables:
        if isinstance(t, KVTable):
            t.store(os.path.join(directory, f"kv_{t.table_id}.npz"))
    Log.Info("checkpoint saved: %s (%d dense tables)", directory, len(dense))
    return directory


def load_arrays(directory: str) -> Dict[str, np.ndarray]:
    """Load-for-serving: restore the dense tables' raw storage arrays from
    a ``save_tables`` checkpoint WITHOUT live tables or a started runtime.

    ``restore_tables`` needs the creation-order table registry to exist
    (training resume); a serving process has no reason to rebuild
    updater state or register tables just to read weights. Returns
    ``{"table_<id>": storage}`` as host arrays, ready for
    ``TableServer.publish`` / ``restore`` (optimizer slots are restored
    by ``restore_tables`` only — serving reads weights, not momenta)."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    path = os.path.join(directory, "tables")
    ckptr = ocp.PyTreeCheckpointer()
    # no abstract target tree (no live arrays to mirror): read the stored
    # STRUCTURE, then restore only each table's 'storage' leaf as plain
    # numpy — serving never reads optimizer slots, and the g2/momentum
    # arrays are storage-sized, so a full-tree restore would move 2-3x
    # the bytes just to drop them; plain-numpy also keeps the load
    # topology-independent (the orbax sharding-file path is explicitly
    # unsafe across topologies)
    structure = ckptr.metadata(path)
    item = {k: {"storage": v["storage"]} for k, v in structure.items()}
    restore_args = {
        k: {"storage": ocp.RestoreArgs(restore_type=np.ndarray)}
        for k in structure
    }
    restored = ckptr.restore(
        path, item=item, restore_args=restore_args, transforms={}
    )
    # crop shard padding: the stored storage is physical (dim 0 padded up
    # to a shard multiple); serving phantom zero rows would corrupt top-k
    # (padding ids outscore real rows at negative cosine) and let
    # out-of-range lookups pass the range check. Checkpoints written
    # before the sidecar existed load uncropped (physical == best known).
    import json

    meta_path = os.path.join(directory, "logical_shapes.json")
    logical = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            logical = json.load(f)
    out: Dict[str, np.ndarray] = {}
    for key, entry in restored.items():
        arr = np.asarray(entry["storage"])
        shape = logical.get(key)
        if shape is not None:
            arr = arr[tuple(slice(0, s) for s in shape)]
        out[key] = arr
    Log.Info("checkpoint arrays loaded for serving: %s (%d tables)",
             directory, len(out))
    return out


def restore_tables(directory: str, tables: Optional[List[Any]] = None) -> None:
    """Restore a checkpoint into the live (already-created) tables: creation
    order defines table ids, exactly like the reference's registration
    protocol, so shapes/updaters must match."""
    import orbax.checkpoint as ocp

    from multiverso_tpu.tables.kv_table import KVTable

    directory = os.path.abspath(directory)
    dense = _dense_tables(tables)
    if dense:
        target = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            _tree_of(dense),
        )
        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.join(directory, "tables"), target)
        for t in dense:
            entry = restored[f"table_{t.table_id}"]
            t.storage = entry["storage"]
            t.state = dict(entry["state"])
    all_tables = tables if tables is not None else runtime().tables
    for t in all_tables:
        if isinstance(t, KVTable):
            path = os.path.join(directory, f"kv_{t.table_id}.npz")
            if os.path.exists(path):
                t.load(path)
    Log.Info("checkpoint restored: %s (%d dense tables)", directory, len(dense))
