"""Sharded checkpoint / resume for the table store.

The reference defines per-table ``Serializable::Store/Load(Stream*)`` hooks
(ref: include/multiverso/table_interface.h:61-75) implemented as raw storage
dumps (ref: src/table/array_table.cpp:144-151, matrix_table.cpp:457-464), but
no core driver calls them (SURVEY.md §5) — apps roll their own. The TPU build
promotes checkpointing to a first-class subsystem:

* ``DenseTable.store/load`` (in tables/base.py) — single-file Stream-based
  dump/restore, Store/Load parity, including the reference LogReg's
  Load-as-Add mode (worker-0 delta injection — ref:
  Applications/LogisticRegression/src/model/ps_model.cpp:113-168);
* ``save_tables``/``restore_tables`` (here) — orbax-backed sharded
  checkpoint of every registered table's storage + optimizer slots: each
  device writes its own HBM shard, restore re-shards onto the live mesh.

**Crash consistency** (resilience subsystem): ``save_tables`` publishes
atomically — the whole payload (orbax tree, ``logical_shapes.json``
sidecar, KV npz dumps) lands in ``<dir>.tmp-<token>``, a fsynced
``MANIFEST.json`` seals it with per-file size+crc32 checksums, and one
rename makes it visible. A reader therefore never observes a torn
directory; ``load_arrays``/``restore_tables`` verify the manifest first
and die with ONE clear error naming the directory and the broken piece
instead of an orbax stack trace.

**Quorum commit** (failure-domain hardening): multi-process saves are
TWO-PHASE. Phase 1 — every rank stages its payload (orbax shards, its
``rank<p>/`` extra files) and seals its own fsynced
``stage-rank<p>.json`` record. Phase 2 — rank 0 verifies every rank's
stage record is present and parseable *before* the single commit
rename; a missing/broken record aborts the commit (``QuorumAbort``) and
sweeps the staging dir. A rank dying mid-save can therefore never
publish a half checkpoint: the torn artifact is always an ignored
``.tmp-`` corpse. The cross-rank sync points are bounded by
``-collective_timeout_s`` (when armed) so a dead peer raises
``RankFailure`` instead of hanging the save forever.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from multiverso_tpu.resilience import checkpoint as rckpt
from multiverso_tpu.resilience import chaos
from multiverso_tpu.resilience.chaos import with_retries
from multiverso_tpu.resilience.watchdog import (
    QuorumAbort,
    RankFailure,
    collective_timeout_s,
    fd_stats,
)
from multiverso_tpu.runtime import runtime
from multiverso_tpu.utils.log import CHECK, FatalError, Log

__all__ = ["save_tables", "restore_tables", "load_arrays"]


def _dense_tables(tables: Optional[List[Any]]) -> List[Any]:
    from multiverso_tpu.tables.base import DenseTable

    if tables is None:
        tables = runtime().tables
    return [t for t in tables if isinstance(t, DenseTable)]


def _tree_of(tables: List[Any]) -> Dict[str, Any]:
    # checkpoint_tree is the per-table serialization hook: dense tables
    # hand over their raw sharded storage + slots; a TieredMatrixTable
    # flushes its HBM cache and hands over the full host-tier logical
    # table, so checkpoints are tier-transparent
    tree: Dict[str, Any] = {}
    for t in tables:
        tree[f"table_{t.table_id}"] = t.checkpoint_tree()
    return tree


def _sync(tag: str) -> None:
    """Cross-rank checkpoint sync point, bounded by
    ``-collective_timeout_s`` when armed: a peer that died mid-save makes
    this raise ``RankFailure`` (no commit happened yet — the staging dir
    is the only artifact) instead of hanging every survivor forever."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    timeout = collective_timeout_s()
    if timeout is None:
        multihost_utils.sync_global_devices(tag)
        return
    err: List[BaseException] = []

    def run():
        try:
            multihost_utils.sync_global_devices(tag)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            err.append(e)

    th = threading.Thread(target=run, daemon=True, name="mv-ckpt-sync")
    th.start()
    th.join(timeout)
    if th.is_alive():
        rf = RankFailure(
            "collective_timeout",
            f"checkpoint sync point {tag!r} exceeded {timeout:.1f}s "
            "(a peer likely died mid-save; no checkpoint was published)",
        )
        fd_stats.note_rank_failure("collective_timeout")
        raise rf
    if err:
        raise err[0]


_STAGE_PREFIX = "stage-rank"


def _stage_record_path(tmp: str, rank: int) -> str:
    return os.path.join(tmp, f"{_STAGE_PREFIX}{rank}.json")


def _write_stage_record(tmp: str, rank_meta: Optional[Dict]) -> None:
    """Phase-1 seal: this rank finished staging its payload. fsynced so a
    crash after the sync point cannot leave a record the verifier reads
    as complete while its bytes are still in flight."""
    path = _stage_record_path(tmp, jax.process_index())
    with open(path, "w") as f:
        json.dump(
            {"rank": jax.process_index(), "ok": True,
             "rank_meta": rank_meta or {}},
            f,
        )
        f.flush()
        os.fsync(f.fileno())


def _verify_quorum(tmp: str, attempts: int = 4,
                   grace_s: float = 0.2) -> Dict[str, Dict]:
    """Phase-2 gate (rank 0): every rank's stage record must be present
    and parseable, else ``QuorumAbort``. Returns the merged per-rank
    metadata for the manifest.

    A short bounded re-read grace covers shared filesystems whose
    attribute caches can hide a peer's just-written record for a moment
    after the barrier (NFS) — a healthy save must not flake into an
    abort; a genuinely dead rank still aborts within ~1s."""
    missing: List[str] = []
    for attempt in range(attempts):
        ranks: Dict[str, Dict] = {}
        missing = []
        for p in range(jax.process_count()):
            path = _stage_record_path(tmp, p)
            try:
                with open(path) as f:
                    rec = json.load(f)
                if not rec.get("ok"):
                    raise ValueError("stage record not ok")
                ranks[str(p)] = rec.get("rank_meta") or {}
            except (OSError, ValueError) as e:
                missing.append(f"rank {p} ({e})")
        if not missing:
            return ranks
        if attempt < attempts - 1:
            time.sleep(grace_s)
    fd_stats.note_quorum_abort()
    raise QuorumAbort(
        "checkpoint quorum commit ABORTED — stage record missing or "
        f"broken for {', '.join(missing)}; no version was published "
        f"(staging dir {tmp} swept)"
    )


def _shared_token() -> str:
    """One tmp-dir token every process agrees on (multi-process saves write
    shards into the SAME staging directory)."""
    if jax.process_count() == 1:
        return uuid.uuid4().hex[:8]
    from jax.experimental import multihost_utils

    tok = np.frombuffer(uuid.uuid4().bytes, np.uint8).copy()
    tok = np.asarray(multihost_utils.broadcast_one_to_all(tok))
    return bytes(tok.tolist()).hex()[:8]


def save_tables(
    directory: str,
    tables: Optional[List[Any]] = None,
    *,
    step: Optional[int] = None,
    meta: Optional[Dict] = None,
    rank_payload: Optional[Callable[[str], None]] = None,
    rank_meta: Optional[Dict] = None,
) -> str:
    """Write a crash-consistent sharded checkpoint of all (dense)
    registered tables; KV tables save alongside as npz (their index is
    host metadata). The directory appears atomically — write to
    ``<dir>.tmp-<token>``, seal with a checksummed ``MANIFEST.json``
    (carrying ``step``/``meta`` for elastic resume), rename. Returns the
    path.

    Two-phase quorum commit: every rank stages payload + its own
    ``stage-rank<p>.json`` record; rank 0 verifies ALL stage records
    before the single commit rename (``QuorumAbort`` and a swept staging
    dir otherwise — a rank dying mid-save can never publish a half
    checkpoint). ``rank_payload(tmp_dir)`` lets each rank stage extra
    files of its own (e.g. the pipelined PS in-flight pull buffers — by
    convention under ``rank<p>/``); ``rank_meta`` rides in that rank's
    stage record and lands merged in the manifest as
    ``meta["ranks"][str(p)]``."""
    from multiverso_tpu.obs import recorder, span

    with span("ckpt.save", dir=os.path.basename(directory)):
        path = _save_tables_impl(
            directory, tables, step=step, meta=meta,
            rank_payload=rank_payload, rank_meta=rank_meta,
        )
    recorder.record(
        "checkpoint_saved", path=path,
        step=-1 if step is None else int(step),
    )
    return path


def _save_tables_impl(
    directory: str,
    tables: Optional[List[Any]],
    *,
    step: Optional[int],
    meta: Optional[Dict],
    rank_payload: Optional[Callable[[str], None]],
    rank_meta: Optional[Dict],
) -> str:
    import orbax.checkpoint as ocp

    from multiverso_tpu.tables.kv_table import KVTable

    directory = os.path.abspath(directory)
    parent = os.path.dirname(directory)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{directory}.tmp-{_shared_token()}"
    if jax.process_index() == 0 and os.path.exists(tmp):
        shutil.rmtree(tmp)  # corpse of a crashed save with our token (rare)
    _sync("mv_ckpt_stage")
    os.makedirs(tmp, exist_ok=True)
    dense = _dense_tables(tables)
    if dense:  # orbax rejects an empty pytree (KV-only checkpoints)
        ckptr = ocp.StandardCheckpointer()

        def _write():
            ckptr.save(os.path.join(tmp, "tables"), _tree_of(dense), force=True)
            ckptr.wait_until_finished()

        # transient-fs retry budget: a flaky NFS/gcsfuse write gets three
        # tries; a real failure still propagates (and leaves only a tmp
        # corpse — never a torn published checkpoint). SINGLE-process
        # only: the orbax save is a collective in multi-process runs, and
        # one rank retrying while its peers proceed to the sync points
        # would desync the pod's barrier sequence — there, one attempt,
        # fail loudly, relaunch the save collectively.
        attempts = 3 if jax.process_count() == 1 else 1
        with_retries(_write, attempts=attempts, base_delay_s=0.2,
                     max_delay_s=2.0, describe=f"checkpoint table write {tmp}")
        if jax.process_index() == 0:
            # logical shapes ride alongside: the orbax tree stores the
            # PHYSICAL shard-padded storage (what restore_tables maps
            # straight back onto live tables), but a serving consumer
            # must not see padding rows — load_arrays crops with this
            shapes = {f"table_{t.table_id}": list(t.shape) for t in dense}
            with open(os.path.join(tmp, "logical_shapes.json"), "w") as f:
                json.dump(shapes, f)
    all_tables = tables if tables is not None else runtime().tables
    for t in all_tables:
        if isinstance(t, KVTable):
            t.store(os.path.join(tmp, f"kv_{t.table_id}.npz"))
    if rank_payload is not None:
        rank_payload(tmp)
    # phase 1 seal: this rank's staging is complete (chaos can drop it —
    # what a rank dying between payload and seal looks like to rank 0)
    if not chaos.quorum_stage_should_skip():
        _write_stage_record(tmp, rank_meta)
    _sync("mv_ckpt_written")
    commit_err: Optional[BaseException] = None
    if jax.process_index() == 0:
        try:
            ranks = _verify_quorum(tmp)
            full_meta = dict(meta or {})
            full_meta["ranks"] = ranks
            # the writing world's topology: the elastic (N -> N') resume
            # names it in its log line, and an operator reading a bare
            # MANIFEST.json can tell what world wrote it (len(ranks) is
            # the authoritative writer count the code branches on)
            full_meta["world"] = {
                "processes": jax.process_count(),
                "devices": jax.device_count(),
            }
            rckpt.commit_atomic(tmp, directory, step=step, meta=full_meta)
            fd_stats.note_quorum_commit()
        except BaseException as e:  # noqa: BLE001 — ANY commit failure
            # (QuorumAbort, a disk-full OSError in the manifest/rename,
            # chaos) must join the commit sync first, THEN raise: peers
            # must not hang on a barrier rank 0 never reaches
            commit_err = e
    _sync("mv_ckpt_commit")
    if commit_err is not None:
        if isinstance(commit_err, QuorumAbort):
            shutil.rmtree(tmp, ignore_errors=True)
        Log.Error("checkpoint commit failed: %s", commit_err)
        raise commit_err
    if jax.process_index() != 0:
        # rank 0 aborted (or died) before the rename: shared-fs truth is
        # the absence of the published directory. Bounded re-probe: an
        # NFS negative-dentry cache can hide a just-renamed directory
        for attempt in range(4):
            if os.path.isdir(directory):
                break
            time.sleep(0.2)
        else:
            raise QuorumAbort(
                f"checkpoint {directory} was not published by rank 0 "
                "(quorum commit aborted)"
            )
    Log.Info("checkpoint saved: %s (%d dense tables)", directory, len(dense))
    return directory


def _check_readable(directory: str) -> None:
    """Pre-flight: a manifest-sealed checkpoint must verify; a pre-manifest
    (legacy) directory must at least contain the orbax tree. Either way a
    bad directory dies HERE with one clear message, not inside orbax."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        Log.Fatal("checkpoint %s is incomplete or corrupt: not a directory",
                  directory)
    if os.path.exists(os.path.join(directory, rckpt.MANIFEST_NAME)):
        rckpt.require_valid(directory)


def _fatal_orbax(directory: str, what: str, exc: Exception) -> None:
    Log.Fatal(
        "checkpoint %s is incomplete or corrupt: %s (%s: %s)",
        directory, what, type(exc).__name__,
        str(exc).splitlines()[0] if str(exc) else "no detail",
    )


def load_arrays(directory: str) -> Dict[str, np.ndarray]:
    """Load-for-serving: restore the dense tables' raw storage arrays from
    a ``save_tables`` checkpoint WITHOUT live tables or a started runtime.

    ``restore_tables`` needs the creation-order table registry to exist
    (training resume); a serving process has no reason to rebuild
    updater state or register tables just to read weights. Returns
    ``{"table_<id>": storage}`` as host arrays, ready for
    ``TableServer.publish`` / ``restore`` (optimizer slots are restored
    by ``restore_tables`` only — serving reads weights, not momenta)."""
    from multiverso_tpu.obs import span

    with span("ckpt.load_arrays", dir=os.path.basename(directory)):
        return _load_arrays_impl(directory)


def _load_arrays_impl(directory: str) -> Dict[str, np.ndarray]:
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    _check_readable(directory)
    path = os.path.join(directory, "tables")
    if not os.path.isdir(path):
        Log.Fatal(
            "checkpoint %s is incomplete or corrupt: missing the 'tables' "
            "orbax tree (dense-table payload)", directory,
        )
    ckptr = ocp.PyTreeCheckpointer()
    # no abstract target tree (no live arrays to mirror): read the stored
    # STRUCTURE, then restore only each table's 'storage' leaf as plain
    # numpy — serving never reads optimizer slots, and the g2/momentum
    # arrays are storage-sized, so a full-tree restore would move 2-3x
    # the bytes just to drop them; plain-numpy also keeps the load
    # topology-independent (the orbax sharding-file path is explicitly
    # unsafe across topologies)
    try:
        structure = ckptr.metadata(path)
        item = {k: {"storage": v["storage"]} for k, v in structure.items()}
        restore_args = {
            k: {"storage": ocp.RestoreArgs(restore_type=np.ndarray)}
            for k in structure
        }
        restored = ckptr.restore(
            path, item=item, restore_args=restore_args, transforms={}
        )
    except Exception as e:  # noqa: BLE001 — one clear error, not a stack dump
        _fatal_orbax(directory, "failed to read the 'tables' orbax tree", e)
    # crop shard padding: the stored storage is physical (dim 0 padded up
    # to a shard multiple); serving phantom zero rows would corrupt top-k
    # (padding ids outscore real rows at negative cosine) and let
    # out-of-range lookups pass the range check. Checkpoints written
    # before the sidecar existed load uncropped (physical == best known).
    import json

    meta_path = os.path.join(directory, "logical_shapes.json")
    logical = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            logical = json.load(f)
    out: Dict[str, np.ndarray] = {}
    for key, entry in restored.items():
        arr = np.asarray(entry["storage"])
        shape = logical.get(key)
        if shape is not None:
            arr = arr[tuple(slice(0, s) for s in shape)]
        out[key] = arr
    Log.Info("checkpoint arrays loaded for serving: %s (%d tables)",
             directory, len(out))
    return out


def _read_logical_shapes(directory: str) -> Dict[str, List[int]]:
    meta_path = os.path.join(directory, "logical_shapes.json")
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


def _restore_dense_resharded(directory: str, dense: List[Any]) -> None:
    """World-size-changing restore: read the stored tree as plain HOST
    numpy (topology-independent — the orbax sharding-file path is
    explicitly unsafe across topologies), crop the writing world's shard
    padding via the ``logical_shapes.json`` sidecar, and re-slice each
    table's logical rows onto the live mesh through
    ``DenseTable.load_logical``. No full-table device copies: the only
    device traffic is placing each table's NEW shards once."""
    import orbax.checkpoint as ocp

    path = os.path.join(directory, "tables")
    if not os.path.isdir(path):
        Log.Fatal(
            "checkpoint %s is incomplete or corrupt: missing the 'tables' "
            "orbax tree (dense-table payload)", directory,
        )
    want = {f"table_{t.table_id}" for t in dense}
    ckptr = ocp.PyTreeCheckpointer()
    try:
        structure = ckptr.metadata(path)
        item = {k: v for k, v in structure.items() if k in want}
        missing = want - set(item)
        CHECK(not missing,
              f"checkpoint {directory} has no entries for {sorted(missing)}"
              " — the table sets of the saved and resuming runs differ")
        restore_args = jax.tree_util.tree_map(
            lambda _leaf: ocp.RestoreArgs(restore_type=np.ndarray), item
        )
        restored = ckptr.restore(
            path, item=item, restore_args=restore_args, transforms={}
        )
    except FatalError:
        raise
    except Exception as e:  # noqa: BLE001 — one clear error
        _fatal_orbax(directory, "failed to read the 'tables' orbax tree "
                     "for re-sharding", e)
    logical = _read_logical_shapes(directory)
    for t in dense:
        key = f"table_{t.table_id}"
        entry = restored[key]
        storage = np.asarray(entry["storage"])
        shape = logical.get(key, list(t.shape))
        storage = storage[tuple(slice(0, s) for s in shape)]
        state = {
            k: np.asarray(v) for k, v in (entry.get("state") or {}).items()
        }
        t.load_logical(storage, state)


def restore_tables(
    directory: str,
    tables: Optional[List[Any]] = None,
    *,
    reshard: bool = False,
) -> None:
    """Restore a checkpoint into the live (already-created) tables: creation
    order defines table ids, exactly like the reference's registration
    protocol, so shapes/updaters must match.

    ``reshard=True`` is the world-size-changing path: the checkpoint may
    have been written by a run with a different process/device count, so
    the stored PHYSICAL shard-padded arrays are re-sliced host-side onto
    the live mesh (logical values identical; see
    ``_restore_dense_resharded``). The default path restores the physical
    tree straight onto the live shardings — bit-exact and zero-copy-ish,
    but only valid when the topology matches the writer's."""
    from multiverso_tpu.obs import recorder, span

    with span("ckpt.restore", dir=os.path.basename(directory),
              reshard=reshard):
        _restore_tables_impl(directory, tables, reshard=reshard)
    recorder.record(
        "checkpoint_restored", path=directory, reshard=bool(reshard)
    )


def _restore_tables_impl(
    directory: str,
    tables: Optional[List[Any]],
    *,
    reshard: bool,
) -> None:
    import orbax.checkpoint as ocp

    from multiverso_tpu.tables.kv_table import KVTable

    directory = os.path.abspath(directory)
    _check_readable(directory)
    dense = _dense_tables(tables)
    if dense and reshard:
        _restore_dense_resharded(directory, dense)
    elif dense:
        # checkpoint_spec is the shape/dtype skeleton of checkpoint_tree
        # (host-tier numpy leaves restore as numpy, device leaves onto
        # their live sharding) — building the TARGET must never pay a
        # tiered table's flush-and-copy
        target = {f"table_{t.table_id}": t.checkpoint_spec() for t in dense}
        ckptr = ocp.StandardCheckpointer()
        try:
            restored = ckptr.restore(os.path.join(directory, "tables"), target)
        except Exception as e:  # noqa: BLE001 — one clear error
            _fatal_orbax(directory, "failed to restore the 'tables' orbax tree", e)
        for t in dense:
            t.restore_checkpoint_tree(restored[f"table_{t.table_id}"])
    all_tables = tables if tables is not None else runtime().tables
    for t in all_tables:
        if isinstance(t, KVTable):
            path = os.path.join(directory, f"kv_{t.table_id}.npz")
            if os.path.exists(path):
                t.load(path)
    Log.Info("checkpoint restored: %s (%d dense tables)", directory, len(dense))
