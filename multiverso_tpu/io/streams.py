"""URI-dispatched byte streams + buffered text reading.

TPU-native equivalent of the reference I/O layer
(ref: include/multiverso/io/io.h:63-132, src/io/io.cpp:8-21): a
``StreamFactory.GetStream(uri, mode)`` that dispatches on URI scheme
(``file://`` default; the reference's ``hdfs://`` is compile-gated behind
``MULTIVERSO_USE_HDFS`` — here it raises with the same not-built message
shape), a ``LocalStream`` fopen wrapper (ref: io/local_stream.h), and a
buffered ``TextReader`` line reader (ref: io/io.h:105-132).
"""

from __future__ import annotations

import io as _pyio
from typing import Optional

from multiverso_tpu.utils.log import CHECK, Log

__all__ = ["Stream", "LocalStream", "StreamFactory", "TextReader"]


class Stream:
    """Abstract byte stream (ref: io/io.h:63-86)."""

    def Write(self, data: bytes) -> int:
        raise NotImplementedError

    def Read(self, size: int) -> bytes:
        raise NotImplementedError

    def Good(self) -> bool:
        raise NotImplementedError

    def Flush(self) -> None:
        pass

    def Close(self) -> None:
        pass

    # context-manager sugar
    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.Close()


class LocalStream(Stream):
    """fopen wrapper (ref: io/local_stream.h, src/io/local_stream.cpp)."""

    def __init__(self, path: str, mode: str = "r"):
        CHECK(mode in ("r", "w", "a", "rb", "wb", "ab"), f"bad stream mode {mode!r}")
        if "b" not in mode:
            mode += "b"
        self._path = path
        try:
            self._f: Optional[_pyio.BufferedIOBase] = open(path, mode)
        except OSError as e:
            Log.Error("LocalStream: cannot open %s: %s", path, e)
            self._f = None

    def Write(self, data: bytes) -> int:
        CHECK(self._f is not None, f"stream {self._path} not open")
        return self._f.write(data)

    def Read(self, size: int = -1) -> bytes:
        CHECK(self._f is not None, f"stream {self._path} not open")
        return self._f.read(size)

    def Good(self) -> bool:
        return self._f is not None and not self._f.closed

    def Flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def Close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class StreamFactory:
    """URI scheme dispatch (ref: src/io/io.cpp:8-21)."""

    @staticmethod
    def GetStream(uri: str, mode: str = "r") -> Stream:
        scheme, sep, rest = uri.partition("://")
        if not sep:
            scheme, rest = "file", uri
        if scheme == "file":
            return LocalStream(rest, mode)
        if scheme == "hdfs":
            Log.Fatal("hdfs:// support is not built in (reference gates it "
                      "behind MULTIVERSO_USE_HDFS)")
        Log.Fatal("unknown stream scheme %r in %r", scheme, uri)
        raise AssertionError  # unreachable (Fatal raises)


def as_stream(uri_or_stream, mode: str) -> tuple:
    """Resolve a URI-or-Stream argument; returns (stream, owned) where
    ``owned`` means the caller must Close() it."""
    if isinstance(uri_or_stream, Stream):
        return uri_or_stream, False
    return StreamFactory.GetStream(str(uri_or_stream), mode), True


class TextReader:
    """Buffered line reader (ref: io/io.h:105-132): GetLine returns one line
    without the trailing newline, or None at EOF."""

    def __init__(self, uri: str, buf_size: int = 1 << 16):
        self._stream = StreamFactory.GetStream(uri, "r")
        self._buf = b""
        self._buf_size = buf_size
        self._eof = False

    def GetLine(self) -> Optional[str]:
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line, self._buf = self._buf[:nl], self._buf[nl + 1 :]
                return line.decode("utf-8", errors="replace")
            if self._eof:
                if self._buf:
                    line, self._buf = self._buf, b""
                    return line.decode("utf-8", errors="replace")
                return None
            chunk = self._stream.Read(self._buf_size)
            if not chunk:
                self._eof = True
            else:
                self._buf += chunk

    def Close(self) -> None:
        self._stream.Close()

    def __iter__(self):
        while True:
            line = self.GetLine()
            if line is None:
                return
            yield line
