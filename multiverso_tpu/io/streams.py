"""URI-dispatched byte streams + buffered text reading.

TPU-native equivalent of the reference I/O layer
(ref: include/multiverso/io/io.h:63-132, src/io/io.cpp:8-21): a
``StreamFactory.GetStream(uri, mode)`` that dispatches on URI scheme
(``file://`` default), a ``LocalStream`` fopen wrapper (ref:
io/local_stream.h), remote schemes (``hdfs://``, ``gs://``, ``s3://``,
...) over ``pyarrow.fs`` (the TPU-native analog of the reference's
libhdfs wrapper — ref: src/io/hdfs_stream.cpp,
include/multiverso/io/hdfs_stream.h — runtime-gated on the pyarrow
driver being loadable, where the reference compile-gates behind
``MULTIVERSO_USE_HDFS``), and a buffered ``TextReader`` line reader
(ref: io/io.h:105-132). ``StreamFactory.register_scheme`` lets
deployments plug custom backends (and tests mock remote schemes).
"""

from __future__ import annotations

import io as _pyio
from typing import Callable, Dict, Optional

from multiverso_tpu.utils.log import CHECK, Log

__all__ = [
    "Stream",
    "LocalStream",
    "ArrowFsStream",
    "StreamFactory",
    "TextReader",
]


class Stream:
    """Abstract byte stream (ref: io/io.h:63-86)."""

    def Write(self, data: bytes) -> int:
        raise NotImplementedError

    def Read(self, size: int) -> bytes:
        raise NotImplementedError

    def Good(self) -> bool:
        raise NotImplementedError

    def Flush(self) -> None:
        pass

    def Close(self) -> None:
        pass

    # context-manager sugar
    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.Close()


class LocalStream(Stream):
    """fopen wrapper (ref: io/local_stream.h, src/io/local_stream.cpp)."""

    def __init__(self, path: str, mode: str = "r"):
        CHECK(mode in ("r", "w", "a", "rb", "wb", "ab"), f"bad stream mode {mode!r}")
        if "b" not in mode:
            mode += "b"
        self._path = path
        try:
            self._f: Optional[_pyio.BufferedIOBase] = open(path, mode)
        except OSError as e:
            Log.Error("LocalStream: cannot open %s: %s", path, e)
            self._f = None

    def Write(self, data: bytes) -> int:
        CHECK(self._f is not None, f"stream {self._path} not open")
        return self._f.write(data)

    def Read(self, size: int = -1) -> bytes:
        CHECK(self._f is not None, f"stream {self._path} not open")
        return self._f.read(size)

    def Good(self) -> bool:
        return self._f is not None and not self._f.closed

    def Flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def Close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class ArrowFsStream(Stream):
    """Remote filesystem stream over ``pyarrow.fs`` — hdfs:// (libhdfs),
    gs://, s3:// and friends (ref: the reference's HDFSStream libhdfs
    wrapper, src/io/hdfs_stream.cpp:24-180: open-by-mode, Read/Write/
    Flush/Close over the C API; pyarrow's FileSystem.from_uri plays the
    hdfsConnect role here and extends the same dispatch to cloud stores).

    The scheme's native driver loads at runtime (libhdfs needs a Hadoop
    install + CLASSPATH, S3/GCS need their pyarrow extensions): a missing
    driver fails loudly at open — the moral equivalent of the reference's
    ``MULTIVERSO_USE_HDFS`` compile gate, moved to runtime so one wheel
    serves every deployment."""

    def __init__(self, uri: str, mode: str = "r"):
        CHECK(mode in ("r", "w", "a", "rb", "wb", "ab"), f"bad stream mode {mode!r}")
        self._path = uri
        self._f = None
        self._open_err: Optional[str] = None
        try:
            from pyarrow import fs as pafs
        except Exception as e:  # pragma: no cover - pyarrow is in the image
            Log.Fatal(
                "remote stream %r needs pyarrow.fs (not importable: %s) — "
                "the runtime analog of the reference's MULTIVERSO_USE_HDFS "
                "gate", uri, e,
            )
        try:
            filesystem, path = pafs.FileSystem.from_uri(uri)
            if mode.startswith("r"):
                self._f = filesystem.open_input_stream(path)
            elif mode.startswith("w"):
                self._f = filesystem.open_output_stream(path)
            else:
                self._f = filesystem.open_append_stream(path)
        except Exception as e:
            # remember the root cause: remote open failures (auth, driver,
            # network) are far more varied than local fopen ones, and the
            # caller otherwise only ever sees a later 'not open' CHECK
            self._open_err = f"{type(e).__name__}: {e}"
            Log.Error("ArrowFsStream: cannot open %s (%s): %s",
                      uri, mode, e)

    def _check_open(self) -> None:
        CHECK(
            self._f is not None,
            f"stream {self._path} not open"
            + (f" (open failed: {self._open_err})" if self._open_err else ""),
        )

    def Write(self, data: bytes) -> int:
        self._check_open()
        self._f.write(data)
        return len(data)

    def Read(self, size: int = -1) -> bytes:
        self._check_open()
        if size is None or size < 0:
            return self._f.read()  # pyarrow reads to EOF without a size
        return self._f.read(size)

    def Good(self) -> bool:
        return self._f is not None and not self._f.closed

    def Flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def Close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


#: pyarrow-routed remote schemes (hdfs via libhdfs; viewfs rides the same
#: driver — ref: hdfs_stream.cpp handles both; cloud stores via arrow's
#: S3/GCS extensions)
_ARROW_SCHEMES = ("hdfs", "viewfs", "gs", "gcs", "s3", "s3a", "abfs")


class StreamFactory:
    """URI scheme dispatch (ref: src/io/io.cpp:8-21) with a runtime
    handler registry for custom/mocked backends."""

    _handlers: Dict[str, Callable[[str, str], Stream]] = {}

    @classmethod
    def register_scheme(
        cls, scheme: str, factory: Optional[Callable[[str, str], Stream]]
    ) -> None:
        """Install (or with ``None`` remove) a handler for a URI scheme;
        handlers take (uri, mode) and win over the built-in dispatch."""
        if factory is None:
            cls._handlers.pop(scheme, None)
        else:
            cls._handlers[scheme] = factory

    @classmethod
    def GetStream(cls, uri: str, mode: str = "r") -> Stream:
        scheme, sep, rest = uri.partition("://")
        if not sep:
            scheme, rest = "file", uri
        handler = cls._handlers.get(scheme)
        if handler is not None:
            return handler(uri, mode)
        if scheme == "file":
            return LocalStream(rest, mode)
        if scheme in _ARROW_SCHEMES:
            return ArrowFsStream(uri, mode)
        Log.Fatal("unknown stream scheme %r in %r", scheme, uri)
        raise AssertionError  # unreachable (Fatal raises)


def as_stream(uri_or_stream, mode: str) -> tuple:
    """Resolve a URI-or-Stream argument; returns (stream, owned) where
    ``owned`` means the caller must Close() it."""
    if isinstance(uri_or_stream, Stream):
        return uri_or_stream, False
    return StreamFactory.GetStream(str(uri_or_stream), mode), True


class TextReader:
    """Buffered line reader (ref: io/io.h:105-132): GetLine returns one line
    without the trailing newline, or None at EOF."""

    def __init__(self, uri: str, buf_size: int = 1 << 16):
        self._stream = StreamFactory.GetStream(uri, "r")
        self._buf = b""
        self._buf_size = buf_size
        self._eof = False

    def GetLine(self) -> Optional[str]:
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line, self._buf = self._buf[:nl], self._buf[nl + 1 :]
                return line.decode("utf-8", errors="replace")
            if self._eof:
                if self._buf:
                    line, self._buf = self._buf, b""
                    return line.decode("utf-8", errors="replace")
                return None
            chunk = self._stream.Read(self._buf_size)
            if not chunk:
                self._eof = True
            else:
                self._buf += chunk

    def Close(self) -> None:
        self._stream.Close()

    def __iter__(self):
        while True:
            line = self.GetLine()
            if line is None:
                return
            yield line
