"""I/O layer: URI-dispatched streams + sharded checkpointing
(ref: include/multiverso/io/, src/io/ — SURVEY.md §2.5 I/O streams;
checkpoint semantics — SURVEY.md §5 checkpoint/resume)."""

from multiverso_tpu.io.streams import (
    ArrowFsStream,
    LocalStream,
    Stream,
    StreamFactory,
    TextReader,
)
from multiverso_tpu.io.checkpoint import restore_tables, save_tables

__all__ = [
    "ArrowFsStream",
    "LocalStream",
    "Stream",
    "StreamFactory",
    "TextReader",
    "restore_tables",
    "save_tables",
]
