"""Flight recorder: a bounded ring of recent structured events.

Every failure post-mortem so far (the XLA rendezvous deadlock, the
compilation-cache poisoning, the gloo aborts) was reconstructed by hand
from interleaved logs. The flight recorder keeps the reconstruction
ready-made: subsystems append small structured events — round
boundaries, ticket-wait p99 breaches, serving hot-swaps, breaker
transitions, GuardViolations, heartbeat gaps, quorum commits/aborts —
into one process-wide bounded deque (oldest evicted), and on any
RankFailure / containment / supervisor give-up the ring is dumped as
``flight-recorder-rank<p>.jsonl`` next to the FAILURE report. The
``PodSupervisor`` collects the dumps into its recovery log dir per
failed generation.

Recording is always on (it is a *crash* recorder — by the time you know
you need it, it is too late to arm it): one lock + deque append per
event, and events fire at round/failure granularity, never per element.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from multiverso_tpu.utils.log import Log

__all__ = ["FlightRecorder", "recorder", "DUMP_PREFIX"]

DUMP_PREFIX = "flight-recorder-rank"


class FlightRecorder:
    """Bounded ring of ``{"seq", "wall", "mono_ns", "kind", ...}`` events.

    ``wall`` is for the human reading the dump next to log lines;
    correlation with the span trace goes through ``mono_ns`` (same clock
    as the tracer). Injectable clocks keep tests deterministic."""

    def __init__(
        self,
        capacity: int = 1024,
        wall: Callable[[], float] = time.time,
        mono_ns: Callable[[], int] = time.monotonic_ns,
    ):
        self._events: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self._wall = wall
        self._mono_ns = mono_ns

    def record(self, kind: str, **fields: Any) -> None:
        ev = {
            "seq": 0, "wall": self._wall(), "mono_ns": self._mono_ns(),
            "kind": str(kind), **fields,
        }
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def stats(self) -> Dict[str, Any]:
        """Ring occupancy for /metrics: how full the crash ring is and
        how many events it has absorbed over the process lifetime."""
        with self._lock:
            return {
                "flight_occupancy": len(self._events),
                "flight_capacity": self._events.maxlen or 0,
                "flight_recorded_events": self._seq,
            }

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0

    def dump(self, path: str) -> str:
        """Write the ring as JSONL (atomic tmp+rename); oldest first."""
        events = self.snapshot()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            for ev in events:
                f.write(json.dumps(ev, default=str) + "\n")
        os.replace(tmp, path)
        return path

    def dump_for_rank(
        self, directory: str, rank: Optional[int] = None
    ) -> Optional[str]:
        """``<directory>/flight-recorder-rank<p>.jsonl`` — the name the
        supervisor's collection pass and the triage runbook look for.
        Never raises: the dump rides failure paths that must not be
        masked by a full disk."""
        if rank is None:
            try:
                import jax

                rank = int(jax.process_index())
            except Exception:  # noqa: BLE001 — recorder works without jax
                rank = 0
        path = os.path.join(directory, f"{DUMP_PREFIX}{rank}.jsonl")
        try:
            self.dump(path)
        except OSError as e:
            Log.Error("flight recorder dump to %s failed: %s", path, e)
            return None
        Log.Info("flight recorder dumped: %s (%d events)",
                 path, len(self.snapshot()))
        return path


recorder = FlightRecorder()
