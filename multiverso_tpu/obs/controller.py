"""Staleness-adaptive pipeline-depth controller (pure decision logic).

Closes the ROADMAP loop carried since PR 4: *observe the per-round
pull/train/push timers, widen depth while overlap% is below target and
the loss stays bounded*. This module holds only the decision table —
the PS round loop owns WHEN decisions are taken (drained round
boundaries) and HOW they are agreed pod-wide (an allgather-min in
``_ps_depth_decide``); the controller just maps one observation to
``widen`` / ``hold`` / ``narrow`` with a reason string.

Decision table, first match wins:

1. ``slo_backoff``  — an SLO rule is burning: narrow (hold at min
   depth — never widen into a burn). Staleness is a luxury; a
   degraded pod sheds it first.
2. ``loss_guard``   — smoothed loss exceeds the best loss seen so far
   by more than ``loss_guard_pct``: narrow (hold at min depth). The
   whole premise of bounded staleness is that loss stays near the
   synchronous trace.
3. ``target_met``   — overlap% at or above target: hold. Depth beyond
   "comms fully hidden" buys nothing and costs staleness.
4. ``no_gain``      — the previous widen did not buy at least
   ``min_gain_pct`` overlap: narrow back. Compute-bound rounds cannot
   benefit from more in-flight pulls.
5. ``overlap_low``  — below target, headroom available, comms time
   non-trivial: widen.
6. ``steady``       — otherwise hold (at max, or comms already noise).

The controller is deliberately deterministic and side-effect free
(``propose`` mutates only its own bookkeeping) so every rank computes
the same proposal from the same pod-level inputs, and so the decision
table unit tests need no clock, no JAX, no threads. ``state_dict`` /
``load_state_dict`` round-trip through checkpoint meta — after a
kill/resume the guard baseline and cooldown survive; restoring from an
older checkpoint without controller state resets safely to defaults.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["DepthController", "Decision"]

WIDEN = "widen"
HOLD = "hold"
NARROW = "narrow"


class Decision:
    """One controller verdict: the action, the agreed-on target depth
    BEFORE pod agreement (a proposal), and the reason that fired."""

    __slots__ = ("action", "depth", "reason", "observed")

    def __init__(self, action: str, depth: int, reason: str,
                 observed: Dict[str, Any]):
        self.action = action
        self.depth = depth
        self.reason = reason
        self.observed = observed

    def to_dict(self) -> Dict[str, Any]:
        return {
            "action": self.action,
            "depth": self.depth,
            "reason": self.reason,
            **self.observed,
        }

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"Decision({self.action}, depth={self.depth}, "
                f"reason={self.reason})")


class DepthController:
    """Maps one round-boundary observation to a depth proposal.

    Parameters
    ----------
    min_depth / max_depth : clamp for every proposal. ``min_depth``
        defaults to 1 — depth 0 is the bit-exact synchronous contract
        and is never entered adaptively (the sync path does not even
        run this code).
    overlap_target_pct : the "comms hidden" bar; at/above it we hold.
    loss_guard_pct : narrow when smoothed loss is more than this many
        percent above the best smoothed loss seen (the staleness guard).
    min_gain_pct : a widen must buy at least this much overlap by the
        next decision or it is rolled back.
    min_comms_ms : below this much pull+push time per round the pipe
        has nothing left to hide; don't widen into noise.
    """

    def __init__(
        self,
        min_depth: int = 1,
        max_depth: int = 4,
        overlap_target_pct: float = 60.0,
        loss_guard_pct: float = 10.0,
        min_gain_pct: float = 2.0,
        min_comms_ms: float = 0.05,
        loss_ema_alpha: float = 0.3,
    ):
        self.min_depth = int(min_depth)
        self.max_depth = int(max_depth)
        self.overlap_target_pct = float(overlap_target_pct)
        self.loss_guard_pct = float(loss_guard_pct)
        self.min_gain_pct = float(min_gain_pct)
        self.min_comms_ms = float(min_comms_ms)
        self.loss_ema_alpha = float(loss_ema_alpha)
        # mutable bookkeeping (checkpointed via state_dict)
        self.depth = self.min_depth
        self.decisions = 0
        self.widens = 0
        self.narrows = 0
        self._loss_ema: Optional[float] = None
        self._best_loss_ema: Optional[float] = None
        self._last_widen_overlap: Optional[float] = None  # overlap% at widen

    # ------------------------------------------------------------ state

    def state_dict(self) -> Dict[str, Any]:
        return {
            "depth": self.depth,
            "decisions": self.decisions,
            "widens": self.widens,
            "narrows": self.narrows,
            "loss_ema": self._loss_ema,
            "best_loss_ema": self._best_loss_ema,
            "last_widen_overlap": self._last_widen_overlap,
        }

    def load_state_dict(self, state: Optional[Dict[str, Any]]) -> None:
        """Restore from checkpoint meta; ``None``/partial state (an
        older checkpoint) resets the affected fields to safe defaults
        instead of raising — resume must never die on meta vintage."""
        state = state or {}
        self.depth = max(self.min_depth, min(
            self.max_depth, int(state.get("depth", self.min_depth))))
        self.decisions = int(state.get("decisions", 0))
        self.widens = int(state.get("widens", 0))
        self.narrows = int(state.get("narrows", 0))
        self._loss_ema = state.get("loss_ema")
        self._best_loss_ema = state.get("best_loss_ema")
        self._last_widen_overlap = state.get("last_widen_overlap")

    # --------------------------------------------------------- decision

    def observe_loss(self, loss: float) -> None:
        """Feed one loss sample (any cadence); keeps an EMA plus the
        best EMA seen, the loss-guard baseline."""
        loss = float(loss)
        if loss != loss or loss in (float("inf"), float("-inf")):
            return  # NaN/inf is the divergence watchdog's business
        a = self.loss_ema_alpha
        self._loss_ema = (loss if self._loss_ema is None
                          else a * loss + (1 - a) * self._loss_ema)
        if (self._best_loss_ema is None
                or self._loss_ema < self._best_loss_ema):
            self._best_loss_ema = self._loss_ema

    def _clamp(self, d: int) -> int:
        return max(self.min_depth, min(self.max_depth, d))

    def propose(
        self,
        overlap_pct: float,
        pull_ms: float = 0.0,
        train_ms: float = 0.0,
        push_ms: float = 0.0,
        slo_breached: bool = False,
    ) -> Decision:
        """One decision from pod-level inputs. Every rank must call
        this with identical inputs (the stats are already pod-visible
        or allgathered) so the proposals agree; the caller still runs
        the agreement collective as a belt-and-braces rendezvous."""
        observed = {
            "overlap_pct": round(float(overlap_pct), 2),
            "pull_ms": round(float(pull_ms), 3),
            "train_ms": round(float(train_ms), 3),
            "push_ms": round(float(push_ms), 3),
            "loss_ema": self._loss_ema,
            "best_loss_ema": self._best_loss_ema,
            "slo_breached": bool(slo_breached),
        }
        cur = self.depth
        widened_last = self._last_widen_overlap is not None

        # a guard firing at min depth still pins the decision to hold:
        # widening while an SLO burns (or loss regresses) would trade
        # more staleness into an already-degraded run
        if slo_breached:
            dec = Decision(
                NARROW if cur > self.min_depth else HOLD,
                self._clamp(cur - 1) if cur > self.min_depth else cur,
                "slo_backoff", observed)
        elif self._loss_regressed():
            dec = Decision(
                NARROW if cur > self.min_depth else HOLD,
                self._clamp(cur - 1) if cur > self.min_depth else cur,
                "loss_guard", observed)
        elif overlap_pct >= self.overlap_target_pct:
            dec = Decision(HOLD, cur, "target_met", observed)
        elif (widened_last
              and overlap_pct - self._last_widen_overlap < self.min_gain_pct
              and cur > self.min_depth):
            dec = Decision(NARROW, self._clamp(cur - 1), "no_gain", observed)
        elif (cur < self.max_depth
              and (pull_ms + push_ms) >= self.min_comms_ms):
            dec = Decision(WIDEN, self._clamp(cur + 1), "overlap_low",
                           observed)
        else:
            dec = Decision(HOLD, cur, "steady", observed)

        # bookkeeping for the next decision
        self.decisions += 1
        if dec.action == WIDEN:
            self.widens += 1
            self._last_widen_overlap = float(overlap_pct)
        else:
            if dec.action == NARROW:
                self.narrows += 1
            self._last_widen_overlap = None
        self.depth = dec.depth
        return dec

    def _loss_regressed(self) -> bool:
        if self._loss_ema is None or self._best_loss_ema is None:
            return False
        if self._best_loss_ema <= 0.0:
            return False  # loss scale degenerate: relative guard undefined
        return (self._loss_ema - self._best_loss_ema) / self._best_loss_ema \
            * 100.0 > self.loss_guard_pct

    def to_dict(self) -> Dict[str, Any]:
        return {
            "depth": self.depth,
            "decisions": self.decisions,
            "widens": self.widens,
            "narrows": self.narrows,
            "loss_ema": self._loss_ema,
        }
