"""Structured metrics registry: Dashboard snapshots -> Prometheus text.

The Dashboard's display sections are human strings; this module is
their machine-readable twin. ``Dashboard.add_section(name, fn,
snapshot=...)`` registers a dict-valued snapshot next to the display
callable, and the registry here:

* collects every snapshot plus the always-present module singletons
  (``failure_domain``, ``resilience``) and the Monitor/Counter core
  into named **families**;
* computes **interval deltas** between successive collections —
  ``*_rate_per_s`` for every numeric that moved monotonically up since
  the last scrape (QPS-style rates, not just lifetime totals);
* renders the whole thing as Prometheus text exposition, served at
  ``GET /metrics`` on the existing ``HealthServer``.

``registry.observe()`` is also the programmatic feed: the
staleness-adaptive depth controller consumes the same
``{families, flat, rates, interval_s}`` snapshot the scraper sees.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from multiverso_tpu.utils.log import Log

__all__ = [
    "MetricsRegistry",
    "registry",
    "render_prometheus",
    "merge_prometheus",
    "register_histogram",
    "unregister_histogram",
    "CONTENT_TYPE",
]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    out = _NAME_SANITIZE_RE.sub("_", name).strip("_")
    if out and out[0].isdigit():
        out = "_" + out
    return out or "unnamed"


def _family_of(section: str) -> str:
    """Section name -> stable family name: drop pure-numeric components
    (the ``serving.<name>.<id(self)>`` instance key must not leak an
    address into metric names), collapse consecutive repeats
    (``serving.serving`` -> ``serving``)."""
    parts = [p for p in section.split(".") if p and not p.isdigit()]
    collapsed: List[str] = []
    for p in parts:
        if not collapsed or collapsed[-1] != p:
            collapsed.append(p)
    return _sanitize("_".join(collapsed) or section)


def _flatten(d: Dict[str, Any], prefix: str = "") -> List[Tuple[str, float]]:
    """Numeric leaves of a (possibly nested) snapshot dict; bools count
    as 0/1, strings/None are skipped (they are labels, not samples).
    Keys sort by str() so a mixed-key dict (int ranks next to string
    names) cannot throw out of a scrape."""
    out: List[Tuple[str, float]] = []
    for k in sorted(d, key=str):
        v = d[k]
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.extend(_flatten(v, prefix=f"{key}_"))
        elif isinstance(v, bool):
            out.append((key, 1.0 if v else 0.0))
        elif isinstance(v, (int, float)):
            out.append((key, float(v)))
    return out


class MetricsRegistry:
    """Collects Dashboard snapshot families and keeps the previous
    collection so successive scrapes carry interval rates."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._prev: Dict[str, float] = {}
        self._prev_t: Optional[float] = None

    def families(self) -> Dict[str, Dict[str, Any]]:
        from multiverso_tpu.resilience import stats as rstats
        from multiverso_tpu.resilience.watchdog import fd_stats
        from multiverso_tpu.utils.dashboard import Dashboard

        from multiverso_tpu.obs import flight as _flight
        from multiverso_tpu.obs import tracer as _tracer

        fams: Dict[str, Dict[str, Any]] = {
            # always present, registered section or not: the operator's
            # scrape must see these families from the first request
            "failure_domain": fd_stats.to_dict(),
            "resilience": rstats.to_dict(),
            "core": Dashboard.core_metrics(),
            # the observability stack watches itself: ring drop counts
            # (is the trace lying?) and crash-ring occupancy
            "obs": {**_tracer.ring_stats(), **_flight.recorder.stats()},
        }
        for section, snap in Dashboard.snapshots().items():
            fam = _family_of(section)
            if fam in fams:
                fams[fam].update(snap)  # e.g. two serving bundles
            else:
                fams[fam] = snap
        return fams

    def observe(self) -> Dict[str, Any]:
        """One collection: ``families`` (raw snapshot dicts), ``flat``
        (``family:key -> value`` numeric view), ``rates`` (per-second
        delta for every numeric that moved monotonically up since the
        previous call), ``interval_s``. This is both the /metrics
        payload and the depth controller's observation input."""
        fams = self.families()
        flat: Dict[str, float] = {}
        for fam, d in fams.items():
            try:
                for key, val in _flatten(d):
                    flat[f"{fam}:{key}"] = val
            except Exception as e:  # noqa: BLE001 — one bad section must
                # not take the whole scrape down
                Log.Error("metrics family %s failed to flatten: %s", fam, e)
        now = self._clock()
        with self._lock:
            dt = 0.0 if self._prev_t is None else max(
                now - self._prev_t, 1e-9
            )
            rates: Dict[str, float] = {}
            if self._prev_t is not None:
                for k, v in flat.items():
                    pv = self._prev.get(k)
                    if pv is not None and v > pv:
                        rates[k] = (v - pv) / dt
            self._prev = flat
            self._prev_t = now
        return {
            "families": fams, "flat": flat, "rates": rates,
            "interval_s": dt,
        }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._prev = {}
            self._prev_t = None


registry = MetricsRegistry()


# ----------------------------------------------- histogram providers
#
# Prometheus histograms cannot ride the gauge flattener: they are one
# logical metric spread over ``_bucket{le=...}``/``_sum``/``_count``
# sample families. Providers register here keyed by owner (idempotent,
# so re-registering after a Dashboard.Reset() just works) and return a
# list of sample dicts:
#
#   {"name": "mv_serving_latency_seconds",
#    "labels": {"route": "lookup:emb"},          # optional
#    "buckets": [(le_seconds, cumulative_count), ...],  # sorted by le
#    "sum": total_seconds, "count": n}
#
# ``render_prometheus`` emits them after the gauges so burn-rate math
# and external scrapers share the real distribution, not gauge p50/p99.

_hist_lock = threading.Lock()
_hist_providers: Dict[str, Callable[[], List[Dict[str, Any]]]] = {}


def register_histogram(key: str,
                       provider: Callable[[], List[Dict[str, Any]]]) -> None:
    with _hist_lock:
        _hist_providers[key] = provider


def unregister_histogram(key: str) -> None:
    with _hist_lock:
        _hist_providers.pop(key, None)


def _label_str(labels: Dict[str, Any]) -> str:
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace('"', r"\"")
        parts.append(f'{_sanitize(str(k))}="{v}"')
    return ",".join(parts)


def _render_histograms(lines: List[str], seen: set) -> None:
    with _hist_lock:
        providers = list(_hist_providers.items())
    for key, provider in providers:
        try:
            samples = provider() or []
        except Exception as e:  # noqa: BLE001 — one broken provider must
            # not 500 the whole scrape
            Log.Error("histogram provider %s failed: %s", key, e)
            continue
        for s in samples:
            name = _sanitize(str(s.get("name") or ""))
            if not name:
                continue
            base = dict(s.get("labels") or {})
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} histogram")
            cum = 0
            for le, c in s.get("buckets") or []:
                cum = c
                lbl = _label_str({**base, "le": _fmt(float(le))})
                lines.append(f"{name}_bucket{{{lbl}}} {int(c)}")
            count = int(s.get("count") or cum)
            inf_lbl = _label_str({**base, "le": "+Inf"})
            lines.append(f"{name}_bucket{{{inf_lbl}}} {count}")
            suffix = f"{{{_label_str(base)}}}" if base else ""
            lines.append(f"{name}_sum{suffix} {repr(float(s.get('sum') or 0.0))}")
            lines.append(f"{name}_count{suffix} {count}")


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_prometheus(reg: Optional[MetricsRegistry] = None) -> str:
    """Prometheus text exposition of one ``observe()`` collection:
    gauges ``mv_<family>_<key>`` plus ``..._rate_per_s`` interval
    deltas. Duplicate names (two same-named serving bundles) keep the
    first sample — a scrape must never 500 on a name collision."""
    obs = (reg or registry).observe()
    lines: List[str] = []
    seen: set = set()
    # render from observe()'s already-flattened view: it carries the
    # per-family error guard (a broken provider is skipped there, and a
    # second _flatten here could throw past it) and halves the work
    for k in sorted(obs["flat"]):
        fam, _, key = k.partition(":")
        metric = "mv_" + _sanitize(f"{fam}_{key}")
        if metric in seen:
            continue
        seen.add(metric)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {_fmt(obs['flat'][k])}")
    for k in sorted(obs["rates"]):
        metric = "mv_" + _sanitize(k.replace(":", "_")) + "_rate_per_s"
        if metric in seen:
            continue
        seen.add(metric)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {repr(obs['rates'][k])}")
    _render_histograms(lines, seen)
    lines.append(f"mv_scrape_interval_s {repr(obs['interval_s'])}")
    return "\n".join(lines) + "\n"


# ------------------------------------------------- fleet-level aggregation

def merge_prometheus(dumps: "List[Tuple[str, str]]") -> str:
    """Join per-replica Prometheus dumps into ONE exposition.

    ``dumps`` is ``[(replica_label, exposition_text), ...]`` — what
    ``python -m multiverso_tpu.obs scrape`` fetched from each replica's
    ``GET /metrics``. Every sample line gains a ``replica="<label>"``
    label (first, so relabel rules can match on it); ``# HELP``/``# TYPE``
    comment lines are kept once per metric name (Prometheus rejects
    duplicate metadata), other comments and blanks are dropped. Pure
    text-level merge: no value math, one replica's malformed line is
    skipped, never the whole scrape.
    """
    meta_seen: set = set()
    out: List[str] = []
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S.*)$"
    )
    for label, text in dumps:
        esc = str(label).replace("\\", r"\\").replace('"', r"\"")
        for raw in text.splitlines():
            line = raw.strip()
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                # "# TYPE <name> <kind>" / "# HELP <name> <text>"
                if len(parts) >= 3 and parts[1] in ("TYPE", "HELP"):
                    key = (parts[1], parts[2])
                    if key in meta_seen:
                        continue
                    meta_seen.add(key)
                    out.append(line)
                continue
            m = sample_re.match(line)
            if m is None:
                continue  # malformed sample: skip the line, keep the scrape
            name, labels, value = m.group(1), m.group(2), m.group(3)
            inner = labels[1:-1].strip() if labels else ""
            merged = f'replica="{esc}"' + (f",{inner}" if inner else "")
            out.append(f"{name}{{{merged}}} {value}")
    return "\n".join(out) + ("\n" if out else "")
