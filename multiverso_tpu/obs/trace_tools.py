"""Pod-wide trace assembly: merge / validate / summarize rank dumps.

A per-rank ``trace-rank<p>.json`` carries raw monotonic timestamps plus
the rank's anchor (stamped at the ``multihost.initialize`` rendezvous
barrier — the one instant every rank shares). ``merge_traces`` subtracts
each rank's anchor so the pod lands on one timeline: round k's pull /
train / push spans line up across ranks, and the overlap (or its
absence) is visible per round per rank in Perfetto.

Kept jax-free and stdlib-only: the merge runs on a laptop against dumps
scp'd off a pod.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Iterable, List, Tuple

__all__ = [
    "load_trace",
    "merge_traces",
    "validate_trace",
    "span_counts",
    "resolve_inputs",
    "request_index",
    "request_tree",
    "request_summary_lines",
]


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def resolve_inputs(paths: Iterable[str]) -> List[str]:
    """Each input is a trace file or a directory of ``trace-rank*.json``."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "trace-rank*.json"))))
        else:
            out.append(p)
    return out


def merge_traces(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Align every rank's events onto one timeline (ts -> microseconds
    since that rank's anchor) and concatenate. ``pid`` stays the rank,
    so Perfetto shows one process lane per rank with its real threads."""
    events: List[dict] = []
    ranks: Dict[str, Dict[str, Any]] = {}
    for doc in docs:
        other = doc.get("otherData", {})
        rank = int(other.get("rank", 0))
        anchor_us = float(other.get("anchor_mono_us", 0.0))
        ranks[str(rank)] = {
            "anchor_wall": other.get("anchor_wall"),
            "anchor_source": other.get("anchor_source"),
            "dropped_events": other.get("dropped_events", 0),
            "unmatched_ends": other.get("unmatched_ends", 0),
        }
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) - anchor_us
            ev["pid"] = rank
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"merged": True, "ranks": ranks},
    }


def validate_trace(doc: Dict[str, Any]) -> List[str]:
    """Schema check for the Chrome-trace subset we emit (what the ci
    smoke and the dump tests gate on). Empty list = valid."""
    problems: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            problems.append(f"event {i} has no name")
        ph = ev.get("ph")
        if ph not in ("X", "B", "E", "i", "M"):
            problems.append(f"event {i} has unknown ph {ph!r}")
            continue
        if ph != "M":
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"event {i} ({ev.get('name')}) has no ts")
            if "pid" not in ev or "tid" not in ev:
                problems.append(f"event {i} ({ev.get('name')}) missing pid/tid")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(
                    f"event {i} ({ev.get('name')}) has bad dur {dur!r}"
                )
    return problems


# -------------------------------------------------- cross-process linker
#
# Request-scoped spans carry W3C-style ids in their args: ``trace_id``
# (one per logical client request), ``span_id`` (this span), and
# ``parent_id`` (the span one hop up — which lives in ANOTHER process
# for the client-attempt -> serving-request edge). After ``merge_traces``
# put every process on one timeline, these functions join the id graph
# back into one tree per request: client.request -> client.attempt ->
# serving.request -> serving.flush_item.


def request_index(doc: Dict[str, Any]) -> Dict[str, List[dict]]:
    """``trace_id -> events carrying it`` (spans and instants), each
    sorted by ts. The merged doc's view of "which requests exist"."""
    idx: Dict[str, List[dict]] = {}
    for ev in doc.get("traceEvents", []):
        args = ev.get("args") or {}
        tid = args.get("trace_id")
        if isinstance(tid, str) and tid:
            idx.setdefault(tid, []).append(ev)
    for evs in idx.values():
        evs.sort(key=lambda e: e.get("ts", 0.0))
    return idx


def request_tree(doc: Dict[str, Any], trace_id: str
                 ) -> Tuple[List[dict], List[dict]]:
    """Link one request's events by span_id/parent_id into
    ``(roots, orphans)`` — nodes are ``{"event", "children"}``; an
    orphan names a parent whose span fell off a ring (or whose process
    never dumped). Cross-process edges resolve naturally: the id graph
    doesn't care which pid a span landed in."""
    by_sid: Dict[str, dict] = {}
    items: List[Tuple[dict, Any]] = []
    for ev in request_index(doc).get(trace_id, []):
        args = ev.get("args") or {}
        node = {"event": ev, "children": []}
        items.append((node, args.get("parent_id")))
        sid = args.get("span_id")
        if isinstance(sid, str) and sid:
            by_sid[sid] = node
    roots: List[dict] = []
    orphans: List[dict] = []
    for node, parent in items:
        if parent and parent in by_sid:
            by_sid[parent]["children"].append(node)
        elif parent:
            orphans.append(node)
        else:
            roots.append(node)
    for node in by_sid.values():
        node["children"].sort(key=lambda n: n["event"].get("ts", 0.0))
    return roots, orphans


def request_summary_lines(doc: Dict[str, Any], trace_id: str) -> List[str]:
    """Human/ci-greppable rendering of one request tree: one line per
    span, indented by depth, with pid (process) and duration."""
    roots, orphans = request_tree(doc, trace_id)
    lines: List[str] = [f"trace={trace_id}"]

    def walk(node: dict, depth: int) -> None:
        ev = node["event"]
        dur = ev.get("dur")
        dur_s = f" dur_us={dur:.1f}" if isinstance(dur, (int, float)) else ""
        lines.append(
            f"{'  ' * (depth + 1)}{ev.get('name')} pid={ev.get('pid')}"
            f" ph={ev.get('ph')}{dur_s}"
        )
        for c in node["children"]:
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    for o in orphans:
        ev = o["event"]
        lines.append(
            f"  (orphan) {ev.get('name')} pid={ev.get('pid')} "
            f"missing_parent={(ev.get('args') or {}).get('parent_id')}"
        )
    return lines


def span_counts(doc: Dict[str, Any]) -> Dict[Tuple[int, str], int]:
    """(rank, span name) -> complete-span count; the ci smoke checks the
    per-rank ``ps.round.*`` counts against the round count."""
    counts: Dict[Tuple[int, str], int] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "X":
            key = (int(ev.get("pid", 0)), ev["name"])
            counts[key] = counts.get(key, 0) + 1
    return counts
