"""Pod-wide trace assembly: merge / validate / summarize rank dumps.

A per-rank ``trace-rank<p>.json`` carries raw monotonic timestamps plus
the rank's anchor (stamped at the ``multihost.initialize`` rendezvous
barrier — the one instant every rank shares). ``merge_traces`` subtracts
each rank's anchor so the pod lands on one timeline: round k's pull /
train / push spans line up across ranks, and the overlap (or its
absence) is visible per round per rank in Perfetto.

Kept jax-free and stdlib-only: the merge runs on a laptop against dumps
scp'd off a pod.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Iterable, List, Tuple

__all__ = [
    "load_trace",
    "merge_traces",
    "validate_trace",
    "span_counts",
    "resolve_inputs",
]


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def resolve_inputs(paths: Iterable[str]) -> List[str]:
    """Each input is a trace file or a directory of ``trace-rank*.json``."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "trace-rank*.json"))))
        else:
            out.append(p)
    return out


def merge_traces(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Align every rank's events onto one timeline (ts -> microseconds
    since that rank's anchor) and concatenate. ``pid`` stays the rank,
    so Perfetto shows one process lane per rank with its real threads."""
    events: List[dict] = []
    ranks: Dict[str, Dict[str, Any]] = {}
    for doc in docs:
        other = doc.get("otherData", {})
        rank = int(other.get("rank", 0))
        anchor_us = float(other.get("anchor_mono_us", 0.0))
        ranks[str(rank)] = {
            "anchor_wall": other.get("anchor_wall"),
            "anchor_source": other.get("anchor_source"),
            "dropped_events": other.get("dropped_events", 0),
            "unmatched_ends": other.get("unmatched_ends", 0),
        }
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) - anchor_us
            ev["pid"] = rank
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"merged": True, "ranks": ranks},
    }


def validate_trace(doc: Dict[str, Any]) -> List[str]:
    """Schema check for the Chrome-trace subset we emit (what the ci
    smoke and the dump tests gate on). Empty list = valid."""
    problems: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            problems.append(f"event {i} has no name")
        ph = ev.get("ph")
        if ph not in ("X", "B", "E", "i", "M"):
            problems.append(f"event {i} has unknown ph {ph!r}")
            continue
        if ph != "M":
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"event {i} ({ev.get('name')}) has no ts")
            if "pid" not in ev or "tid" not in ev:
                problems.append(f"event {i} ({ev.get('name')}) missing pid/tid")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(
                    f"event {i} ({ev.get('name')}) has bad dur {dur!r}"
                )
    return problems


def span_counts(doc: Dict[str, Any]) -> Dict[Tuple[int, str], int]:
    """(rank, span name) -> complete-span count; the ci smoke checks the
    per-rank ``ps.round.*`` counts against the round count."""
    counts: Dict[Tuple[int, str], int] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "X":
            key = (int(ev.get("pid", 0)), ev["name"])
            counts[key] = counts.get(key, 0) + 1
    return counts
