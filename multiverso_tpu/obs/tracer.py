"""Low-overhead span tracer: thread-local event rings -> Chrome trace.

The Dashboard answers "how much time, cumulatively" — the three carried
ROADMAP mysteries (the fused leg's roofline gap, the 0.14x weak-scaling
number, the staleness-adaptive depth controller's observation input) are
*timeline* questions across three threads and N ranks: did pull k+1
actually overlap train k, on every rank, every round? This module
answers those:

* ``span(name, **args)`` / ``event(name, **args)`` record
  ``(monotonic_ns, tid, name, args)`` begin/end (or instant) entries
  into a **thread-local preallocated ring** — no locks on the hot path
  (each ring has exactly one writer; readers snapshot under the GIL),
  overflow drops-oldest by construction (modular write index). Tracing
  off is one cached-bool check; no ring is touched.
* ``dump()`` renders every ring as Chrome-trace / Perfetto JSON
  (``ph: "X"`` complete events from paired begin/end, ``"i"`` instants,
  ``"B"`` for spans still open at dump time) with ``pid`` = rank and
  ``tid`` = OS thread id, so the comms worker / training thread /
  ASyncBuffer fill thread land as separate tracks.
* timestamps stay RAW monotonic microseconds; the dump carries this
  rank's **anchor** (the monotonic reading taken at the
  ``multihost.initialize`` rendezvous barrier — the one instant all
  ranks share). ``python -m multiverso_tpu.obs merge`` subtracts each
  rank's anchor to align the clocks into one pod-wide timeline.

Flags: ``-trace_dir`` arms tracing and names the per-rank dump
directory (``trace-rank<p>.json``); ``-trace_ring_events`` sizes the
per-thread ring. ``enable()`` arms ring recording programmatically
without a dump directory (the bench's ring-only overhead leg).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from multiverso_tpu.utils.configure import (
    GetFlag,
    MV_DEFINE_int,
    MV_DEFINE_string,
    mutation_count,
)
from multiverso_tpu.utils.log import Log

__all__ = [
    "span",
    "event",
    "tracing_enabled",
    "enable",
    "disable",
    "set_anchor",
    "exchange_anchor",
    "anchor",
    "dump",
    "maybe_dump_from_flags",
    "reset_for_tests",
    "new_trace_id",
    "new_span_id",
    "mint_traceparent",
    "parse_traceparent",
    "set_trace_context",
    "get_trace_context",
    "clear_trace_context",
    "ring_stats",
]

MV_DEFINE_string(
    "trace_dir", "",
    "arm the span tracer and dump each rank's Chrome-trace/Perfetto JSON "
    "to this directory as trace-rank<p>.json at the end of training (and "
    "on rank-failure containment); merge the per-rank dumps with "
    "`python -m multiverso_tpu.obs merge <dir>` (empty = tracing off)",
)
MV_DEFINE_int(
    "trace_ring_events", 65536,
    "per-thread preallocated trace ring capacity in events; overflow "
    "drops the OLDEST events (the dump records how many were dropped)",
)

# enabled is checked on every span/event — cache it against the flag
# registry's mutation counter (same pattern as guards.guards_enabled)
_enabled_cache: Optional[bool] = None
_enabled_gen = -1
_force_enabled = False


def tracing_enabled() -> bool:
    global _enabled_cache, _enabled_gen
    if _force_enabled:
        return True
    gen = mutation_count()
    if _enabled_cache is None or _enabled_gen != gen:
        _enabled_cache = bool(GetFlag("trace_dir"))
        _enabled_gen = gen
    return _enabled_cache


def enable() -> None:
    """Arm ring recording without a dump directory (ring-only mode —
    the bench overhead leg, tests)."""
    global _force_enabled
    _force_enabled = True


def disable() -> None:
    global _force_enabled
    _force_enabled = False


# ----------------------------------------------------------------- rings


class _Ring:
    """One thread's preallocated event ring. Single writer (the owning
    thread); ``slots[i % cap] = tuple`` is atomic under the GIL, so a
    dumper reading a snapshot can at worst observe a half-rotated window
    — never a torn event. Overflow overwrites the oldest slot."""

    __slots__ = ("thread_name", "ident", "cap", "slots", "idx", "gen")

    def __init__(self, thread_name: str, ident: int, cap: int, gen: int):
        self.thread_name = thread_name
        self.ident = ident
        self.cap = cap
        self.slots: List[Optional[tuple]] = [None] * cap
        self.idx = 0
        self.gen = gen

    def record(self, ph: str, ts_ns: int, name: str,
               args: Optional[Dict[str, Any]]) -> None:
        i = self.idx
        self.slots[i % self.cap] = (ts_ns, ph, name, args)
        self.idx = i + 1

    def chronological(self) -> Tuple[List[tuple], int]:
        """Snapshot -> (events oldest-first, dropped_count)."""
        idx = self.idx
        slots = list(self.slots)
        if idx <= self.cap:
            evs = [e for e in slots[:idx] if e is not None]
            return evs, 0
        start = idx % self.cap
        evs = [e for e in slots[start:] + slots[:start] if e is not None]
        return evs, idx - self.cap


_registry: List[_Ring] = []
_registry_lock = threading.Lock()
_tls = threading.local()
_generation = 0


def _ring() -> _Ring:
    r = getattr(_tls, "ring", None)
    if r is None or r.gen != _generation:
        cap = max(16, int(GetFlag("trace_ring_events")))
        t = threading.current_thread()
        ident = threading.get_ident()
        with _registry_lock:
            # recycle a DEAD thread's ring instead of growing the
            # registry: ASyncBuffer spawns one fill thread per block, and
            # a preallocated ring per block would leak ~cap slots each
            # (multi-GB over a long run). A dead thread can never write
            # again, so single-writer stays intact; its surviving events
            # keep riding the recycled ring and land on the inheriting
            # thread's track at dump time (for the serial fill threads
            # that is one continuous track — the readable rendering).
            live = {th.ident for th in threading.enumerate()}
            r = next(
                (x for x in _registry
                 if x.cap == cap and x.ident not in live),
                None,
            )
            if r is not None:
                r.ident = ident
                r.thread_name = t.name
                r.gen = _generation
            else:
                r = _Ring(t.name, ident, cap, _generation)
                _registry.append(r)
        _tls.ring = r
    return r


# ------------------------------------------------------------- span/event


class span:
    """``with span("ps.round.train", round=r):`` — records a begin/end
    pair on this thread's ring. Exceptions propagate unchanged (the end
    event still lands, so a crash dump shows where the time went)."""

    __slots__ = ("_name", "_args", "_on")

    def __init__(self, name: str, **args: Any):
        self._name = name
        self._args = args

    def __enter__(self) -> "span":
        on = tracing_enabled()
        self._on = on
        if on:
            _ring().record(
                "B", time.monotonic_ns(), self._name, self._args or None
            )
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._on:
            _ring().record("E", time.monotonic_ns(), self._name, None)
        return False


def event(name: str, **args: Any) -> None:
    """Instant event on this thread's timeline."""
    if tracing_enabled():
        _ring().record("i", time.monotonic_ns(), name, args or None)


# ---------------------------------------------------------- trace context
#
# W3C-style request context: the ServingClient mints one trace_id per
# request and one span_id per attempt, ships them as a ``traceparent``
# header, and the data plane parks them in a thread-local so the batcher
# ticket (submitted synchronously on the handler thread) can capture
# them. Spans carry trace_id/span_id/parent_id in their args; the merge
# tool's linker joins client-side and replica-side spans into one tree.

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def mint_traceparent(trace_id: str, span_id: str) -> str:
    """``00-<trace_id>-<span_id>-01`` (version 00, sampled flag set)."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a traceparent header, or
    ``None`` on anything malformed — a bad header must degrade to "no
    trace", never to a 4xx."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(2), m.group(3)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # the spec's all-zero ids are invalid
    return trace_id, span_id


def set_trace_context(trace_id: str, span_id: str) -> None:
    """Park the active request's ids on this thread (the data-plane
    handler thread) so synchronous downstream code — the batcher's
    ``submit`` — can stamp its ticket without plumbing arguments
    through every layer."""
    _tls.trace_ctx = (trace_id, span_id)


def get_trace_context() -> Optional[Tuple[str, str]]:
    return getattr(_tls, "trace_ctx", None)


def clear_trace_context() -> None:
    _tls.trace_ctx = None


# ----------------------------------------------------------------- anchor

_anchor: Dict[str, Any] = {
    "mono_ns": time.monotonic_ns(),
    "wall": time.time(),
    "source": "import",
}


def set_anchor(source: str = "local") -> None:
    """Stamp this rank's clock anchor: the monotonic reading taken at a
    moment all ranks share (the rendezvous barrier). The merge tool
    subtracts each rank's anchor to align timelines."""
    _anchor["mono_ns"] = time.monotonic_ns()
    _anchor["wall"] = time.time()
    _anchor["source"] = source


def anchor() -> Dict[str, Any]:
    return dict(_anchor)


def exchange_anchor(timeout_s: float = 60.0) -> None:
    """Cross-rank anchor exchange at ``multihost.initialize``: wait on
    the coordination service's barrier so every rank stamps its anchor
    at (approximately) the same instant, then stamp. Best-effort — with
    no KV barrier available the local stamp still anchors the dump
    (merge alignment degrades to wall-clock skew, which the merged
    trace's otherData records)."""
    try:
        from multiverso_tpu.parallel.multihost import kv_client

        client = kv_client()
        if client is not None and hasattr(client, "wait_at_barrier"):
            client.wait_at_barrier(
                "mv_trace_anchor", int(timeout_s * 1000)
            )
    except Exception as e:  # noqa: BLE001 — anchor quality is best-effort
        Log.Info("trace anchor barrier unavailable (%s); local stamp", e)
    set_anchor("multihost")


# ------------------------------------------------------------------ dump


def _pair_ring(ring_events: List[tuple]) -> Tuple[List[dict], int]:
    """B/E pairs -> 'X' complete events (ts/dur in raw monotonic us);
    unmatched ends (their begin was dropped by overflow) are discarded
    and counted; spans still open at dump time stay as 'B'."""
    out: List[dict] = []
    stack: List[tuple] = []
    unmatched = 0
    for ts_ns, ph, name, args in ring_events:
        if ph == "B":
            stack.append((ts_ns, name, args))
        elif ph == "E":
            if stack and stack[-1][1] == name:
                b_ts, b_name, b_args = stack.pop()
                ev = {
                    "name": b_name, "ph": "X", "cat": "mv",
                    "ts": b_ts / 1e3, "dur": (ts_ns - b_ts) / 1e3,
                }
                if b_args:
                    ev["args"] = b_args
                out.append(ev)
            else:
                unmatched += 1  # begin fell off the ring
        else:  # instant
            ev = {
                "name": name, "ph": "i", "cat": "mv", "ts": ts_ns / 1e3,
                "s": "t",
            }
            if args:
                ev["args"] = args
            out.append(ev)
    for b_ts, b_name, b_args in stack:  # open at dump time (crash dumps)
        ev = {"name": b_name, "ph": "B", "cat": "mv", "ts": b_ts / 1e3}
        if b_args:
            ev["args"] = b_args
        out.append(ev)
    out.sort(key=lambda e: e["ts"])
    return out, unmatched


def _infer_rank() -> int:
    # MV_TRACE_RANK wins: serving replicas and fleet clients share no
    # jax.process_index() space, and same-host processes would all dump
    # as rank 0 (pid collision in the merged trace) without an explicit
    # per-process assignment from the fleet launcher.
    env = os.environ.get("MV_TRACE_RANK")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — tracer must work without a backend
        return 0


def ring_stats() -> Dict[str, Any]:
    """Occupancy/drop counters across every ring — the /metrics view of
    "is the trace lying". Cheap: no pairing, no copies beyond the
    registry list."""
    with _registry_lock:
        rings = list(_registry)
    recorded = sum(r.idx for r in rings)
    dropped = sum(max(0, r.idx - r.cap) for r in rings)
    occupancy = sum(min(r.idx, r.cap) for r in rings)
    capacity = sum(r.cap for r in rings)
    return {
        "tracer_rings": len(rings),
        "tracer_recorded_events": recorded,
        "tracer_dropped_events": dropped,
        "tracer_ring_occupancy": occupancy,
        "tracer_ring_capacity": capacity,
        "tracer_enabled": tracing_enabled(),
    }


def dump(path: Optional[str] = None, rank: Optional[int] = None) -> Dict:
    """Render every thread's ring as one Chrome-trace JSON document;
    write it atomically when ``path`` is given. Returns the document."""
    if rank is None:
        rank = _infer_rank()
    with _registry_lock:
        rings = list(_registry)
    events: List[dict] = []
    dropped = 0
    unmatched = 0
    events.append({
        "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
        "args": {"name": f"rank{rank}"},
    })
    for r in rings:
        evs, drop = r.chronological()
        dropped += drop
        paired, open_unmatched = _pair_ring(evs)
        unmatched += open_unmatched
        for ev in paired:
            ev["pid"] = rank
            ev["tid"] = r.ident
        events.extend(paired)
        events.append({
            "name": "thread_name", "ph": "M", "pid": rank, "tid": r.ident,
            "args": {"name": r.thread_name},
        })
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "rank": rank,
            "pid": os.getpid(),
            "anchor_mono_us": _anchor["mono_ns"] / 1e3,
            "anchor_wall": _anchor["wall"],
            "anchor_source": _anchor["source"],
            "dropped_events": dropped,
            "unmatched_ends": unmatched,
        },
    }
    if path is not None:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        Log.Info("trace dumped: %s (%d events, %d dropped)",
                 path, len(events), dropped)
    return doc


def maybe_dump_from_flags(rank: Optional[int] = None) -> Optional[str]:
    """Dump ``trace-rank<p>.json`` into ``-trace_dir`` when armed."""
    d = GetFlag("trace_dir")
    if not d:
        return None
    if rank is None:
        rank = _infer_rank()
    path = os.path.join(d, f"trace-rank{rank}.json")
    try:
        dump(path, rank=rank)
    except Exception as e:  # noqa: BLE001 — a failed dump must never
        # mask the (possibly failing) training path that triggered it
        Log.Error("trace dump to %s failed: %s", path, e)
        return None
    return path


def reset_for_tests() -> None:
    """Forget every ring and programmatic arm state (test isolation).
    Live threads re-create their ring lazily on the next record."""
    global _generation, _force_enabled, _enabled_cache
    with _registry_lock:
        _generation += 1
        _registry.clear()
    _force_enabled = False
    _enabled_cache = None
    set_anchor("reset")
