"""Bounded in-process time series over ``MetricsRegistry.observe()``.

One ``observe()`` call is a point-in-time cut; SLO burn rates, the
straggler detector and the depth controller all need *windows* — "the
shed rate over the last minute", "the mean overlap% since the last
decision". This module keeps a bounded ring of scrapes per process and
answers window queries over it:

* ``ingest()`` appends one ``observe()`` collection (``flat`` numeric
  view + wall stamp) to the ring — the same feed ``GET /metrics``
  renders, so the SLO engine and an external scraper literally share
  one representation;
* ``window(key, seconds)`` aggregates a key over the trailing window
  (count/first/last/min/max/mean);
* ``delta_rate(key, seconds)`` is the counter view: (last - first) / dt
  for monotonically-published totals, clamped at 0 so a process restart
  (counter reset) reads as quiet, not as a negative burn.

Injectable clock + registry keep every consumer fake-clock testable;
capacity is bounded (oldest evicted) so a week-long replica cannot grow
an unbounded scrape history.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["TimeSeriesStore", "WindowStats", "store"]


@dataclass(frozen=True)
class WindowStats:
    """Aggregate of one key over a trailing window. ``count`` is the
    number of scrapes that carried the key; everything else is 0-valued
    when ``count`` is 0 (a missing family must read as quiet, never
    throw out of an SLO evaluation)."""

    count: int = 0
    first: float = 0.0
    last: float = 0.0
    min: float = 0.0
    max: float = 0.0
    mean: float = 0.0
    span_s: float = 0.0

    def delta_rate(self) -> float:
        """Counter view: (last - first) / span, floored at 0 (a counter
        reset across a restart must not read as a negative rate)."""
        if self.count < 2 or self.span_s <= 0.0:
            return 0.0
        return max(0.0, (self.last - self.first) / self.span_s)


class TimeSeriesStore:
    """Bounded ring of ``observe()`` flat views, one entry per scrape."""

    def __init__(
        self,
        capacity: int = 512,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
    ):
        self._capacity = int(capacity)
        self._clock = clock
        self._registry = registry
        self._lock = threading.Lock()
        self._points: deque = deque(maxlen=self._capacity)  # (t, flat)

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from multiverso_tpu.obs.metrics import registry

        return registry

    # ------------------------------------------------------------ write

    def ingest(self, observation: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
        """Append one scrape. ``observation`` defaults to a fresh
        ``registry.observe()``; passing one in lets a caller that
        already scraped (the /metrics handler, the depth controller)
        share the collection instead of double-scraping."""
        if observation is None:
            observation = self._reg().observe()
        flat = dict(observation.get("flat") or {})
        with self._lock:
            self._points.append((self._clock(), flat))
        return observation

    def reset_for_tests(self) -> None:
        with self._lock:
            self._points.clear()

    # ------------------------------------------------------------- read

    def __len__(self) -> int:
        with self._lock:
            return len(self._points)

    def keys(self) -> List[str]:
        """Keys of the newest scrape (the live metric surface)."""
        with self._lock:
            if not self._points:
                return []
            return sorted(self._points[-1][1])

    def latest(self, key: str) -> Optional[float]:
        with self._lock:
            for t, flat in reversed(self._points):
                if key in flat:
                    return float(flat[key])
        return None

    def series(self, key: str, window_s: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """``[(t, value), ...]`` oldest-first for one key, optionally
        restricted to the trailing ``window_s`` seconds."""
        cutoff = None if window_s is None else self._clock() - float(window_s)
        out: List[Tuple[float, float]] = []
        with self._lock:
            for t, flat in self._points:
                if cutoff is not None and t < cutoff:
                    continue
                if key in flat:
                    out.append((t, float(flat[key])))
        return out

    def window(self, key: str, window_s: float) -> WindowStats:
        pts = self.series(key, window_s)
        if not pts:
            return WindowStats()
        vals = [v for _t, v in pts]
        return WindowStats(
            count=len(pts),
            first=vals[0],
            last=vals[-1],
            min=min(vals),
            max=max(vals),
            mean=sum(vals) / len(vals),
            span_s=max(0.0, pts[-1][0] - pts[0][0]),
        )

    def delta_rate(self, key: str, window_s: float) -> float:
        return self.window(key, window_s).delta_rate()

    def ratio_rate(self, bad_key: str, total_key: str, window_s: float
                   ) -> Optional[float]:
        """Bad-fraction of two counters over the window:
        Δbad / Δtotal. ``None`` when the denominator did not move —
        "no traffic" is indistinguishable from "all good" and an SLO
        rule must not breach on it."""
        bad = self.window(bad_key, window_s)
        total = self.window(total_key, window_s)
        dt = total.last - total.first
        if total.count < 2 or dt <= 0.0:
            return None
        db = max(0.0, bad.last - bad.first) if bad.count >= 2 else 0.0
        return min(1.0, db / dt)


# process-wide default: the SLO engine, the depth controller and the
# scrape --watch loop all read the same history
store = TimeSeriesStore()
