"""Declarative SLO rules with multi-window burn-rate evaluation.

A rule names one metric from the ``observe()`` flat view (or a
bad/total counter pair) and an objective. Evaluation follows the
multi-window burn-rate recipe: the rule breaches only when BOTH a fast
window (detects the current spike) and a slow window (proves it is
sustained, not a scrape blip) burn faster than the threshold. Recovery
is flap-suppressed: a breached rule needs ``clear_after`` consecutive
healthy evaluations before it clears, so a metric oscillating around
the objective cannot strobe /healthz.

Verdict plumbing on a breach transition:

* a ``slo_breach`` flight-recorder event (rule, burn rates, value) —
  the crash dump shows *which objective* was burning before a breaker
  or watchdog verdict landed;
* ``http_health.set_degraded(rule, detail)`` — /healthz flips to
  ``degraded`` with the rule named, while /livez stays 200 (an SLO
  burn is a traffic signal, not a liveness signal).

The clear transition mirrors both (``slo_clear`` + ``clear_degraded``).

``StragglerDetector`` lives here too: it consumes the per-rank round
timers that ``_ps_round_meta`` piggybacks on its allgather and flags a
rank whose train/push time drifts more than ``k`` sigma above the pod
median — the precursor signal heartbeat watchdogs cannot see because
the slow rank is still alive and beating.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from multiverso_tpu.obs import flight
from multiverso_tpu.obs import timeseries as ts_mod
from multiverso_tpu.utils.configure import GetFlag, MV_DEFINE_double

__all__ = [
    "SLORule",
    "RuleState",
    "SLOEngine",
    "StragglerDetector",
    "PeriodicEvaluator",
    "default_rules",
    "engine",
    "maybe_start_from_flags",
]

MV_DEFINE_double(
    "slo_eval_interval_s", 0.0,
    "arm the in-process SLO engine: scrape observe() into the "
    "time-series ring and evaluate the burn-rate rules every this many "
    "seconds on a daemon thread (serving replicas and the training "
    "entry points honor it; 0 = off). Breaches emit slo_breach flight "
    "events and flip /healthz to degraded until the rule clears",
)

_EPS = 1e-12


@dataclass(frozen=True)
class SLORule:
    """One objective over one metric.

    kind:
      * ``gauge`` — window mean of an instantaneous value (p99 ms,
        overlap %, checkpoint age);
      * ``rate``  — delta of a monotonic counter / window span
        (events per second, e.g. tracer drops);
      * ``ratio`` — Δ``metric`` / Δ``total`` over the window (error
        fraction of served requests).

    comparison ``">"`` means "value above objective is bad" (latency,
    shed rate); ``"<"`` means "value below objective is bad"
    (availability, overlap%). Burn rate is normalised so 1.0 always
    means "exactly at objective" and larger is worse.
    """

    name: str
    metric: str
    objective: float
    kind: str = "gauge"              # gauge | rate | ratio
    comparison: str = ">"            # ">" bad-above, "<" bad-below
    total: Optional[str] = None      # denominator counter for ratio
    fast_window_s: float = 30.0
    slow_window_s: float = 300.0
    burn_threshold: float = 1.0
    clear_after: int = 3             # healthy evals before clearing
    min_points: int = 2              # scrapes required per window
    severity: str = "warn"

    def _value(self, store: "ts_mod.TimeSeriesStore", window_s: float
               ) -> Optional[float]:
        if self.kind == "ratio":
            if not self.total:
                return None
            return store.ratio_rate(self.metric, self.total, window_s)
        w = store.window(self.metric, window_s)
        if w.count < self.min_points:
            return None
        if self.kind == "rate":
            return w.delta_rate()
        return w.mean

    def burn(self, store: "ts_mod.TimeSeriesStore", window_s: float
             ) -> Optional[float]:
        """Normalised burn rate over one window, or None when the
        window has too little data to judge (counts as healthy)."""
        value = self._value(store, window_s)
        if value is None:
            return None
        if self.comparison == ">":
            if self.objective <= _EPS:
                return float("inf") if value > _EPS else 0.0
            return value / self.objective
        # "<": bad when value drops below objective
        return self.objective / max(value, _EPS)


@dataclass
class RuleState:
    breached: bool = False
    healthy_streak: int = 0
    breach_count: int = 0
    clear_count: int = 0
    last_burn_fast: Optional[float] = None
    last_burn_slow: Optional[float] = None
    last_value: Optional[float] = None


class SLOEngine:
    """Evaluates a rule set against a TimeSeriesStore.

    ``health_hook(rule_name, detail_or_None)`` is called on
    breach (detail string) and clear (None); the default hook wires
    ``serving.http_health.set_degraded``/``clear_degraded`` lazily so
    importing obs never drags the HTTP stack in.
    """

    def __init__(
        self,
        rules: Optional[Sequence[SLORule]] = None,
        store: Optional["ts_mod.TimeSeriesStore"] = None,
        recorder: Optional["flight.FlightRecorder"] = None,
        health_hook: Optional[Callable[[str, Optional[str]], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._rules: List[SLORule] = list(rules or [])
        self._store = store
        self._recorder = recorder
        self._health_hook = health_hook
        self._clock = clock
        self._lock = threading.Lock()
        self._states: Dict[str, RuleState] = {}
        self._evals = 0

    # --------------------------------------------------------- plumbing

    def _get_store(self) -> "ts_mod.TimeSeriesStore":
        return self._store if self._store is not None else ts_mod.store

    def _get_recorder(self) -> "flight.FlightRecorder":
        return self._recorder if self._recorder is not None else flight.recorder

    def _health(self, rule_name: str, detail: Optional[str]) -> None:
        hook = self._health_hook
        if hook is None:
            try:
                from multiverso_tpu.serving import http_health

                def hook(name: str, d: Optional[str]) -> None:
                    if d is None:
                        http_health.clear_degraded(f"slo:{name}")
                    else:
                        http_health.set_degraded(f"slo:{name}", d)
            except Exception:
                return
        try:
            hook(rule_name, detail)
        except Exception:
            pass

    # ------------------------------------------------------------- API

    @property
    def rules(self) -> List[SLORule]:
        return list(self._rules)

    def add_rule(self, rule: SLORule) -> None:
        with self._lock:
            self._rules = [r for r in self._rules if r.name != rule.name]
            self._rules.append(rule)

    def state(self, name: str) -> RuleState:
        with self._lock:
            return self._states.setdefault(name, RuleState())

    def breached_rules(self) -> List[str]:
        with self._lock:
            return sorted(n for n, s in self._states.items() if s.breached)

    def evaluate(self, ingest: bool = False) -> Dict[str, Any]:
        """One evaluation pass. ``ingest=True`` scrapes the registry
        into the store first (the common in-loop shape: one call does
        scrape + verdicts). Returns a summary dict for logging/tests."""
        store = self._get_store()
        if ingest:
            store.ingest()
        results: Dict[str, Any] = {}
        with self._lock:
            self._evals += 1
            evals = self._evals
            rules = list(self._rules)
        for rule in rules:
            results[rule.name] = self._eval_rule(rule, store)
        return {
            "evals": evals,
            "breached": self.breached_rules(),
            "rules": results,
        }

    def _eval_rule(self, rule: SLORule, store: "ts_mod.TimeSeriesStore"
                   ) -> Dict[str, Any]:
        burn_fast = rule.burn(store, rule.fast_window_s)
        burn_slow = rule.burn(store, rule.slow_window_s)
        burning = (
            burn_fast is not None
            and burn_slow is not None
            and burn_fast >= rule.burn_threshold
            and burn_slow >= rule.burn_threshold
        )
        value = rule._value(store, rule.fast_window_s)
        st = self.state(rule.name)
        fired = cleared = False
        with self._lock:
            st.last_burn_fast = burn_fast
            st.last_burn_slow = burn_slow
            st.last_value = value
            if burning:
                st.healthy_streak = 0
                if not st.breached:
                    st.breached = True
                    st.breach_count += 1
                    fired = True
            else:
                if st.breached:
                    st.healthy_streak += 1
                    if st.healthy_streak >= rule.clear_after:
                        st.breached = False
                        st.healthy_streak = 0
                        st.clear_count += 1
                        cleared = True
        if fired:
            self._get_recorder().record(
                "slo_breach",
                rule=rule.name,
                metric=rule.metric,
                value=value,
                objective=rule.objective,
                burn_fast=burn_fast,
                burn_slow=burn_slow,
                severity=rule.severity,
            )
            self._health(
                rule.name,
                f"{rule.metric}={value!r} objective={rule.objective}"
                f" burn_fast={burn_fast:.3g} burn_slow={burn_slow:.3g}",
            )
        if cleared:
            self._get_recorder().record("slo_clear", rule=rule.name)
            self._health(rule.name, None)
        return {
            "breached": st.breached,
            "fired": fired,
            "cleared": cleared,
            "burn_fast": burn_fast,
            "burn_slow": burn_slow,
            "value": value,
        }

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "evals": self._evals,
                "rules": len(self._rules),
                "breached": sorted(
                    n for n, s in self._states.items() if s.breached),
                "breaches_total": sum(
                    s.breach_count for s in self._states.values()),
            }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._states.clear()
            self._evals = 0


def default_rules(
    availability_objective: float = 0.01,
    p99_ms_objective: float = 250.0,
    shed_rate_objective: float = 0.05,
    overlap_pct_target: float = 30.0,
    checkpoint_age_s_objective: float = 900.0,
    trace_drop_rate_objective: float = 1.0,
    fast_window_s: float = 30.0,
    slow_window_s: float = 300.0,
) -> List[SLORule]:
    """The stock rule set over the metric names the registry publishes.

    Rules over families a process does not run (e.g. serving metrics in
    a pure-trainer process) simply never accumulate points and stay
    healthy — one rule set serves every role.
    """
    common = dict(fast_window_s=fast_window_s, slow_window_s=slow_window_s)
    return [
        # Error fraction of served requests (serving_replica errors /
        # served counters are monotonic totals).
        SLORule(
            name="availability",
            metric="serving_replica:errors",
            total="serving_replica:served",
            objective=availability_objective,
            kind="ratio",
            severity="page",
            **common,
        ),
        SLORule(
            name="latency_p99",
            metric="serving_replica:p99_ms_max",
            objective=p99_ms_objective,
            kind="gauge",
            **common,
        ),
        SLORule(
            name="shed_rate",
            metric="serving_replica:shed",
            total="serving_replica:served",
            objective=shed_rate_objective,
            kind="ratio",
            **common,
        ),
        # PS overlap%: bad when it drops BELOW target (comms no longer
        # hidden behind compute) — the depth controller's own SLO.
        SLORule(
            name="ps_overlap_pct",
            metric="ps_comms:overlap_pct",
            objective=overlap_pct_target,
            comparison="<",
            kind="gauge",
            min_points=3,
            **common,
        ),
        SLORule(
            name="checkpoint_age",
            metric="resilience:last_checkpoint_age_s",
            objective=checkpoint_age_s_objective,
            kind="gauge",
            **common,
        ),
        # Tracer ring drops/sec: sustained drops mean the trace is lying.
        SLORule(
            name="trace_drop_rate",
            metric="obs:tracer_dropped_events",
            objective=trace_drop_rate_objective,
            kind="rate",
            **common,
        ),
    ]


class StragglerDetector:
    """Flags ranks whose round timers drift above the pod median.

    Fed one matrix per pipelined round: ``timers_us[rank] = train+push
    microseconds`` (gathered by ``_ps_round_meta``'s allgather). A rank
    is a straggler when its timer exceeds ``median + k * sigma`` (sigma
    estimated from the median absolute deviation, robust to the
    straggler itself) on ``confirm_rounds`` consecutive feeds. Each
    confirmation emits one ``straggler`` flight event and notifies
    fd_stats; re-arming requires the rank to fall back under the bar.
    """

    def __init__(
        self,
        k_sigma: float = 3.0,
        confirm_rounds: int = 3,
        min_ranks: int = 3,
        min_spread_us: float = 1000.0,
        recorder: Optional["flight.FlightRecorder"] = None,
        fd_hook: Optional[Callable[[int, float, float], None]] = None,
    ):
        self._k = float(k_sigma)
        self._confirm = int(confirm_rounds)
        self._min_ranks = int(min_ranks)
        self._min_spread_us = float(min_spread_us)
        self._recorder = recorder
        self._fd_hook = fd_hook
        self._lock = threading.Lock()
        self._over: Dict[int, int] = {}      # rank -> consecutive-over count
        self._flagged: Dict[int, bool] = {}  # rank -> currently flagged
        self._events = 0

    def _get_recorder(self) -> "flight.FlightRecorder":
        return self._recorder if self._recorder is not None else flight.recorder

    def _notify_fd(self, rank: int, timer_us: float, median_us: float) -> None:
        hook = self._fd_hook
        if hook is None:
            try:
                from multiverso_tpu.resilience.watchdog import fd_stats

                hook = lambda r, t, m: fd_stats.note_straggler(r, t, m)
            except Exception:
                return
        try:
            hook(rank, timer_us, median_us)
        except Exception:
            pass

    @property
    def events(self) -> int:
        with self._lock:
            return self._events

    def flagged_ranks(self) -> List[int]:
        with self._lock:
            return sorted(r for r, f in self._flagged.items() if f)

    def feed(self, timers_us: Sequence[float], round_idx: int = -1
             ) -> List[int]:
        """Consume one round's per-rank timers; returns ranks newly
        CONFIRMED as stragglers this round (usually empty)."""
        n = len(timers_us)
        if n < self._min_ranks:
            return []
        vals = sorted(float(t) for t in timers_us)
        median = vals[n // 2] if n % 2 else 0.5 * (vals[n // 2 - 1] + vals[n // 2])
        # MAD-based sigma: robust against the straggler inflating the
        # spread estimate it is judged by. 1.4826 ≈ normal consistency.
        mad = sorted(abs(v - median) for v in vals)
        mad_v = mad[n // 2] if n % 2 else 0.5 * (mad[n // 2 - 1] + mad[n // 2])
        sigma = max(1.4826 * mad_v, self._min_spread_us / self._k)
        bar = median + self._k * sigma
        confirmed: List[int] = []
        with self._lock:
            for rank, t in enumerate(timers_us):
                if float(t) > bar:
                    self._over[rank] = self._over.get(rank, 0) + 1
                    if (self._over[rank] >= self._confirm
                            and not self._flagged.get(rank, False)):
                        self._flagged[rank] = True
                        self._events += 1
                        confirmed.append(rank)
                else:
                    self._over[rank] = 0
                    self._flagged[rank] = False
        for rank in confirmed:
            self._get_recorder().record(
                "straggler",
                rank=rank,
                round=round_idx,
                timer_us=float(timers_us[rank]),
                median_us=median,
                bar_us=bar,
                k_sigma=self._k,
            )
            self._notify_fd(rank, float(timers_us[rank]), median)
        return confirmed

    def reset_for_tests(self) -> None:
        with self._lock:
            self._over.clear()
            self._flagged.clear()
            self._events = 0


# process-wide default engine (rules attached by the app/replica wiring)
engine = SLOEngine()


class PeriodicEvaluator:
    """Daemon-thread loop: ``engine.evaluate(ingest=True)`` every
    ``interval_s``. One per process is plenty — the engine and the
    store are both process-wide singletons."""

    def __init__(self, eng: Optional[SLOEngine] = None,
                 interval_s: float = 5.0):
        self._engine = eng if eng is not None else engine
        self._interval_s = max(float(interval_s), 0.05)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PeriodicEvaluator":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="mv-slo-eval"
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self._engine.evaluate(ingest=True)
            except Exception:  # noqa: BLE001 — a broken scrape must not
                # kill the evaluator; the next tick may succeed
                pass

    def stop(self) -> None:
        self._stop.set()
        th = self._thread
        if th is not None:
            th.join(timeout=5)
            self._thread = None


def maybe_start_from_flags() -> Optional[PeriodicEvaluator]:
    """Arm the default engine when ``-slo_eval_interval_s`` > 0; the
    stock rules attach on first arm (explicitly-added rules win)."""
    interval = float(GetFlag("slo_eval_interval_s"))
    if interval <= 0.0:
        return None
    if not engine.rules:
        for rule in default_rules():
            engine.add_rule(rule)
    return PeriodicEvaluator(engine, interval).start()
