"""CLI: ``python -m multiverso_tpu.obs <merge|validate|summary|scrape>``.

* ``merge <dir-or-files...> -o pod.json`` — align per-rank dumps on the
  shared anchor and emit one pod-wide Perfetto-loadable trace (exit 2 if
  ``--expect-ranks`` is given and fewer rank dumps were found, exit 1 if
  the merged document fails validation).
* ``validate <file.json>`` — schema-check a dump (exit 1 on problems).
* ``summary <file.json>`` — per-rank complete-span counts, one
  ``rank=<p> name=<span> count=<n>`` line each (what the ci smoke
  parses).
* ``scrape <fleet-log-dir>`` — read the ``ServingFleet`` endpoint files
  (``endpoints/replica-*.json``), fetch each live replica's
  ``GET /metrics``, and emit ONE Prometheus dump with every sample
  labeled ``replica="<i>"`` — fleet-level observability from one
  command/scrape target (exit 2 if ``--expect`` replicas didn't answer).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import urllib.request

from multiverso_tpu.obs.trace_tools import (
    load_trace,
    merge_traces,
    request_index,
    request_summary_lines,
    resolve_inputs,
    span_counts,
    validate_trace,
)

_ENDPOINT_RE = re.compile(r"^replica-(\d+)\.json$")


def _scrape_fleet(log_dir: str, timeout_s: float) -> list:
    """``[(replica_index, exposition_text), ...]`` from every endpoint
    file whose replica answers ``GET /metrics``. A missing or dead
    replica is skipped (the fleet degrades; so does the scrape) — the
    caller decides whether partial coverage is an error (``--expect``)."""
    epdir = os.path.join(log_dir, "endpoints")
    found = []
    try:
        names = sorted(os.listdir(epdir))
    except OSError as e:
        raise SystemExit(f"scrape: cannot read {epdir}: {e}")
    for name in names:
        m = _ENDPOINT_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(epdir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # half-written endpoint file: replica still booting
        # prefer the dedicated health port; the data-plane URL serves
        # the same probe routes when health rides the single port
        url = None
        host, ports = doc.get("host"), doc.get("ports") or {}
        if host and ports.get("health"):
            url = f"http://{host}:{ports['health']}/metrics"
        elif doc.get("url"):
            url = doc["url"].rstrip("/") + "/metrics"
        if not url:
            continue
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                text = resp.read().decode("utf-8", "replace")
        except Exception as e:  # noqa: BLE001 — a dead replica degrades
            # the scrape, never kills it
            print(f"scrape: replica {m.group(1)} unreachable at {url}: "
                  f"{e}", file=sys.stderr)
            continue
        found.append((m.group(1), text))
    return found


_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)")


def _parse_samples(text: str) -> dict:
    """Prometheus text -> ``{metric_name: float}`` (labeled samples keep
    the bare name, last one wins — the watch loop tracks scalars like
    served/shed/p99, not labeled families)."""
    out = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            out[m.group(1)] = float(m.group(3))
        except ValueError:
            continue
    return out


def _watch_fleet(args) -> int:
    """``scrape --watch``: the ROADMAP's "nothing scrapes/joins them"
    residual as a daemon — one JSONL line per tick, each carrying every
    reachable replica's numeric samples. Ctrl-C (or --count) stops it;
    the file is append-only so a crashed watcher loses nothing."""
    import time as _time

    out_path = args.out or os.path.join(args.log_dir, "fleet-metrics.jsonl")
    ticks = 0
    try:
        while True:
            dumps = _scrape_fleet(args.log_dir, args.timeout)
            line = {
                "wall": _time.time(),
                "replicas": {idx: _parse_samples(t) for idx, t in dumps},
            }
            with open(out_path, "a") as f:
                f.write(json.dumps(line) + "\n")
            ticks += 1
            print(
                f"watch tick {ticks}: {len(dumps)} replica(s) -> {out_path}"
            )
            if args.count and ticks >= args.count:
                break
            _time.sleep(max(args.interval, 0.05))
    except KeyboardInterrupt:
        pass
    if args.expect and ticks == 0:
        return 2
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m multiverso_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="merge per-rank dumps into one trace")
    mp.add_argument("inputs", nargs="+",
                    help="trace files or directories of trace-rank*.json")
    mp.add_argument("-o", "--out", required=True)
    mp.add_argument("--expect-ranks", type=int, default=0,
                    help="fail unless at least this many rank dumps merge")
    vp = sub.add_parser("validate", help="schema-check one trace file")
    vp.add_argument("file")
    sp = sub.add_parser("summary", help="per-rank span counts")
    sp.add_argument("file")
    sp.add_argument("--request", default=None, metavar="TRACE_ID",
                    help="print ONE request's parent-linked span tree "
                    "(cross-process, from a merged trace)")
    sp.add_argument("--list-requests", action="store_true",
                    help="list every trace_id with its process coverage "
                    "(pids=) and event count")
    sc = sub.add_parser(
        "scrape", help="join a serving fleet's per-replica /metrics"
    )
    sc.add_argument("log_dir",
                    help="the ServingFleet log_dir (holds endpoints/)")
    sc.add_argument("-o", "--out", default=None,
                    help="write the merged dump here (default: stdout)")
    sc.add_argument("--timeout", type=float, default=5.0,
                    help="per-replica HTTP timeout, seconds")
    sc.add_argument("--expect", type=int, default=0,
                    help="fail unless at least this many replicas answered")
    sc.add_argument("--watch", action="store_true",
                    help="scrape repeatedly, appending one JSONL line per "
                    "tick ({wall, replicas: {idx: {metric: value}}}) to "
                    "-o (default fleet-metrics.jsonl in the log dir)")
    sc.add_argument("--interval", type=float, default=5.0,
                    help="--watch scrape period, seconds")
    sc.add_argument("--count", type=int, default=0,
                    help="--watch: stop after this many ticks (0 = forever)")
    args = ap.parse_args(argv)

    if args.cmd == "scrape":
        from multiverso_tpu.obs.metrics import merge_prometheus

        if args.watch:
            return _watch_fleet(args)
        dumps = _scrape_fleet(args.log_dir, args.timeout)
        if args.expect and len(dumps) < args.expect:
            print(
                f"scrape: expected >= {args.expect} replicas, "
                f"got {len(dumps)}", file=sys.stderr,
            )
            return 2
        merged = merge_prometheus(dumps)
        if args.out:
            with open(args.out, "w") as f:
                f.write(merged)
            print(f"scraped {len(dumps)} replica(s) -> {args.out}")
        else:
            sys.stdout.write(merged)
        return 0

    if args.cmd == "merge":
        paths = resolve_inputs(args.inputs)
        if not paths:
            print("no trace files found", file=sys.stderr)
            return 2
        docs = [load_trace(p) for p in paths]
        merged = merge_traces(docs)
        nranks = len(merged["otherData"]["ranks"])
        if args.expect_ranks and nranks < args.expect_ranks:
            print(
                f"expected >= {args.expect_ranks} ranks, merged {nranks}",
                file=sys.stderr,
            )
            return 2
        problems = validate_trace(merged)
        if problems:
            for p in problems[:20]:
                print(f"invalid: {p}", file=sys.stderr)
            return 1
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(
            f"merged {len(paths)} dump(s), {nranks} rank(s), "
            f"{len(merged['traceEvents'])} events -> {args.out}"
        )
        return 0

    doc = load_trace(args.file)
    if args.cmd == "validate":
        problems = validate_trace(doc)
        for p in problems[:50]:
            print(f"invalid: {p}", file=sys.stderr)
        print("valid" if not problems else f"{len(problems)} problem(s)")
        return 1 if problems else 0

    # summary
    if args.list_requests:
        idx = request_index(doc)
        if not idx:
            print("no request-scoped spans (trace_id args) found")
            return 0
        for tid in sorted(idx):
            evs = idx[tid]
            pids = sorted({int(ev.get("pid", 0)) for ev in evs})
            print(
                f"trace={tid} pids={','.join(map(str, pids))} "
                f"events={len(evs)}"
            )
        return 0
    if args.request:
        lines = request_summary_lines(doc, args.request)
        if len(lines) <= 1:
            print(f"trace {args.request} not found in this dump",
                  file=sys.stderr)
            return 2
        for line in lines:
            print(line)
        return 0
    for (rank, name), n in sorted(span_counts(doc).items()):
        print(f"rank={rank} name={name} count={n}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
