"""CLI: ``python -m multiverso_tpu.obs <merge|validate|summary> ...``.

* ``merge <dir-or-files...> -o pod.json`` — align per-rank dumps on the
  shared anchor and emit one pod-wide Perfetto-loadable trace (exit 2 if
  ``--expect-ranks`` is given and fewer rank dumps were found, exit 1 if
  the merged document fails validation).
* ``validate <file.json>`` — schema-check a dump (exit 1 on problems).
* ``summary <file.json>`` — per-rank complete-span counts, one
  ``rank=<p> name=<span> count=<n>`` line each (what the ci smoke
  parses).
"""

from __future__ import annotations

import argparse
import json
import sys

from multiverso_tpu.obs.trace_tools import (
    load_trace,
    merge_traces,
    resolve_inputs,
    span_counts,
    validate_trace,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m multiverso_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="merge per-rank dumps into one trace")
    mp.add_argument("inputs", nargs="+",
                    help="trace files or directories of trace-rank*.json")
    mp.add_argument("-o", "--out", required=True)
    mp.add_argument("--expect-ranks", type=int, default=0,
                    help="fail unless at least this many rank dumps merge")
    vp = sub.add_parser("validate", help="schema-check one trace file")
    vp.add_argument("file")
    sp = sub.add_parser("summary", help="per-rank span counts")
    sp.add_argument("file")
    args = ap.parse_args(argv)

    if args.cmd == "merge":
        paths = resolve_inputs(args.inputs)
        if not paths:
            print("no trace files found", file=sys.stderr)
            return 2
        docs = [load_trace(p) for p in paths]
        merged = merge_traces(docs)
        nranks = len(merged["otherData"]["ranks"])
        if args.expect_ranks and nranks < args.expect_ranks:
            print(
                f"expected >= {args.expect_ranks} ranks, merged {nranks}",
                file=sys.stderr,
            )
            return 2
        problems = validate_trace(merged)
        if problems:
            for p in problems[:20]:
                print(f"invalid: {p}", file=sys.stderr)
            return 1
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(
            f"merged {len(paths)} dump(s), {nranks} rank(s), "
            f"{len(merged['traceEvents'])} events -> {args.out}"
        )
        return 0

    doc = load_trace(args.file)
    if args.cmd == "validate":
        problems = validate_trace(doc)
        for p in problems[:50]:
            print(f"invalid: {p}", file=sys.stderr)
        print("valid" if not problems else f"{len(problems)} problem(s)")
        return 1 if problems else 0

    # summary
    for (rank, name), n in sorted(span_counts(doc).items()):
        print(f"rank={rank} name={name} count={n}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
