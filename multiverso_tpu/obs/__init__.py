"""Observability layer: span tracer, metrics registry, flight recorder.

The cross-cutting layer every subsystem attaches to once (ISSUE 9):

* :mod:`multiverso_tpu.obs.tracer` — thread-local event rings recording
  begin/end spans with no locks on the hot path; ``-trace_dir`` dumps
  per-rank Chrome-trace/Perfetto JSON and
  ``python -m multiverso_tpu.obs merge`` aligns rank clocks (via the
  anchor stamped at ``multihost.initialize``) into one pod-wide trace.
* :mod:`multiverso_tpu.obs.metrics` — dict-valued Dashboard section
  twins rendered as Prometheus text at ``GET /metrics`` on the
  ``HealthServer``, with interval rates; ``registry.observe()`` is the
  same feed the staleness-adaptive depth controller will consume.
* :mod:`multiverso_tpu.obs.flight` — a bounded ring of recent
  structured events dumped as ``flight-recorder-rank<p>.jsonl`` next to
  the FAILURE report on containment, collected by the ``PodSupervisor``.
* :mod:`multiverso_tpu.obs.timeseries` — a bounded ring of
  ``observe()`` scrapes answering window queries (the burn-rate input).
* :mod:`multiverso_tpu.obs.slo` — declarative SLO rules with
  multi-window burn-rate evaluation; breaches emit flight events and
  flip ``/healthz`` degraded. Plus the straggler detector over per-rank
  round timers.
* :mod:`multiverso_tpu.obs.controller` — the staleness-adaptive
  pipeline-depth controller's decision table (``-ps_pipeline_depth=auto``
  wiring lives in the PS round loop).
"""

from multiverso_tpu.obs import controller, flight, metrics, slo, timeseries, tracer
from multiverso_tpu.obs.flight import recorder
from multiverso_tpu.obs.tracer import event, span, tracing_enabled

__all__ = [
    "tracer",
    "metrics",
    "flight",
    "timeseries",
    "slo",
    "controller",
    "span",
    "event",
    "tracing_enabled",
    "recorder",
]
