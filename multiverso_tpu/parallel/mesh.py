"""Device-mesh construction and sharding helpers.

This is where the reference's process topology collapses into a TPU device
mesh. In the reference each MPI rank is simultaneously a *worker* and a
*server* (role ALL — ref: include/multiverso/node.h:6-27, src/zoo.cpp:23-35);
tables are sharded across servers and every worker talks to every server over
MPI/ZMQ (SURVEY.md §2.2). On TPU:

* one mesh axis, ``worker``, is the data-parallel axis — one "worker" per
  device (or per device-row of a 2-D mesh);
* table shards live in HBM along the ``shard`` axis — the "servers". By
  default there is no separate shard axis: the mesh is 1-D and tables shard
  along ``worker`` itself, which is exactly the reference's role-ALL layout
  (every node hosts a table shard *and* trains);
* Get/Add lower to XLA collectives over ICI (all_gather / reduce_scatter /
  psum) instead of point-to-point messages — the entire net/ layer of the
  reference (NetInterface, MPINetWrapper, ZMQNetWrapper, AllreduceEngine —
  SURVEY.md §2.2) has no code here: XLA owns topology and transport.

A separate ``shard`` axis (2-D mesh) gives the reference's worker!=server
configurations (``-ps_role`` splits) and is what larger models use to combine
data parallelism with sharded tables.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "WORKER_AXIS",
    "SHARD_AXIS",
    "build_mesh",
    "shard_axis_name",
    "num_workers",
    "num_shards",
    "table_sharding",
    "worker_sharding",
    "replicated_sharding",
    "query_sharding",
]

WORKER_AXIS = "worker"
SHARD_AXIS = "shard"


def build_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    num_workers: Optional[int] = None,
    num_shards: Optional[int] = None,
) -> Mesh:
    """Build the framework mesh.

    Default (no arguments): 1-D mesh over all local devices with axis
    ``worker`` — the role-ALL layout where table shards and data shards
    coincide per device. With ``num_shards > 1`` a 2-D
    ``(worker, shard)`` mesh is built; tables shard along ``shard`` and
    replicate along ``worker``.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if num_workers is None and num_shards is None:
        return Mesh(np.asarray(devices), (WORKER_AXIS,))
    if num_shards in (None, 1):
        if num_workers not in (None, n):
            raise ValueError(
                f"num_workers={num_workers} does not cover all {n} devices; "
                "pass an explicit devices list to use a subset"
            )
        return Mesh(np.asarray(devices), (WORKER_AXIS,))
    if num_workers is None:
        if n % num_shards:
            raise ValueError(f"{n} devices not divisible by num_shards={num_shards}")
        num_workers = n // num_shards
    if num_workers * num_shards != n:
        raise ValueError(
            f"num_workers({num_workers}) * num_shards({num_shards}) != devices({n})"
        )
    grid = np.asarray(devices).reshape(num_workers, num_shards)
    return Mesh(grid, (WORKER_AXIS, SHARD_AXIS))


def shard_axis_name(mesh: Mesh) -> str:
    """Axis tables shard along: ``shard`` if present else ``worker`` (role ALL)."""
    return SHARD_AXIS if SHARD_AXIS in mesh.axis_names else WORKER_AXIS


def num_workers(mesh: Mesh) -> int:
    return int(mesh.shape[WORKER_AXIS])


def num_shards(mesh: Mesh) -> int:
    return int(mesh.shape[shard_axis_name(mesh)])


def table_sharding(mesh: Mesh, ndim: int, shard_dim: int = 0) -> NamedSharding:
    """Sharding for table storage: dim ``shard_dim`` split across servers.

    ArrayTable shards its single dim contiguously (ref:
    src/table/array_table.cpp:98-108); MatrixTable shards rows (ref:
    src/table/matrix_table.cpp:24-45). Both are 'dim 0 over the shard axis'.
    """
    spec = [None] * ndim
    spec[shard_dim] = shard_axis_name(mesh)
    return NamedSharding(mesh, P(*spec))


def worker_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Per-worker data: dim 0 is the worker dim (one slice per worker)."""
    spec = [None] * ndim
    spec[0] = WORKER_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def query_sharding(mesh: Mesh, ndim: int, batch: int) -> NamedSharding:
    """Serving-query placement: split the padded query bucket's dim 0
    over the worker axis when it divides evenly (data-parallel gather /
    score matmul), else replicate — a non-divisible bucket only occurs
    for direct sub-``min_bucket`` calls where replication is free."""
    if batch % num_workers(mesh) == 0:
        spec = [None] * ndim
        spec[0] = WORKER_AXIS
        return NamedSharding(mesh, P(*spec))
    return replicated_sharding(mesh)
