"""Multi-host bootstrap: the reference's cluster-formation layer, TPU-native.

The reference forms a cluster three ways (SURVEY.md §2.2): MPI launch
(mpirun assigns ranks), a ZMQ machine file (`-machine_file` + `-port`, rank =
index of the local IP in the file — ref: include/multiverso/net/zmq_net.h:
23-109), or explicit endpoint wiring driven by the embedding application
(``MV_NetBind``/``MV_NetConnect`` — ref: include/multiverso/multiverso.h:
47-65). On TPU all three collapse into ``jax.distributed.initialize``: one
coordinator address, N processes, and XLA owns every byte moved thereafter —
ICI within a slice, DCN across slices. This module keeps the reference's
*deployment surface* (machine file, explicit endpoints, programmatic args)
as front-ends to that single rendezvous:

* ``initialize(...)``            — programmatic (coordinator, N, process_id)
* ``initialize_from_machine_file`` — the ZMQ machine-file flow: rank = line
                                   index matching a local IP, coordinator =
                                   line 0
* ``MV_NetBind/MV_NetConnect``   — the CNTK-style explicit wiring, re-mapped
                                   in api.py onto the same rendezvous

plus the mesh/data plumbing a multi-host run needs:

* ``build_multihost_mesh``  — hybrid ICI x DCN device mesh: the table shard
  axis stays *inside* a slice (collectives ride ICI; SURVEY.md §2.2 "lay out
  shardings so collectives ride ICI"), the worker/data axis spans DCN.
* ``host_local_to_global`` / ``global_to_host_local`` — per-host input
  batches -> one global sharded array and back (each host feeds its own
  readers, exactly like each reference rank reads its own data blocks).
"""

from __future__ import annotations

import socket
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.parallel import mesh as mesh_lib
from multiverso_tpu.utils.configure import MV_DEFINE_int, MV_DEFINE_string, GetFlag
from multiverso_tpu.utils.log import CHECK, Log

__all__ = [
    "initialize",
    "initialize_from_flags",
    "initialize_from_machine_file",
    "kv_client",
    "parse_machine_file",
    "local_ips",
    "build_multihost_mesh",
    "host_local_to_global",
    "global_to_host_local",
    "process_index",
    "process_count",
]

# Flag parity with the ZMQ backend (ref: zmq_net.h:20-21 declares
# -machine_file and -port for rank discovery).
MV_DEFINE_string("machine_file", "", "one host[:port] per line; line 0 is coordinator")
MV_DEFINE_int("port", 55555, "coordinator port when machine_file lines lack one")
MV_DEFINE_string("coordinator", "", "coordinator ip:port (overrides machine_file)")
MV_DEFINE_int("process_id", -1, "this process's id (-1: infer from machine_file)")
MV_DEFINE_int("num_processes", 0, "total processes (0: infer)")
# Bounded rendezvous (resilience subsystem): the reference's ZMQ handshake
# simply blocks forever on a missing peer; here every attempt is bounded
# and transient failures (a peer restarting after a host loss) get a
# jittered-backoff retry budget instead of a hang.
MV_DEFINE_int(
    "rendezvous_timeout_s", 300,
    "per-attempt cluster rendezvous timeout (bounded failure, not a hang)",
)
MV_DEFINE_int(
    "rendezvous_retries", 3,
    "extra rendezvous attempts after the first (jittered backoff between)",
)

_initialized = False


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def kv_client():
    """The cluster's distributed key-value client (the coordination
    service behind ``jax.distributed.initialize``), or ``None`` when no
    cluster is up or this jax build does not expose one.

    This is the control-plane side channel the failure-domain watchdog
    publishes liveness beacons over (``resilience.watchdog``
    ``KVHeartbeatStore``) when no shared ``-heartbeat_dir`` filesystem
    exists: write-once keys, so peers probe forward from their last
    confirmed sequence. Kept here — not in the watchdog — because the
    client's lifetime is owned by this module's rendezvous (a failed
    ``initialize`` tears it down for the retry)."""
    try:
        from jax._src import distributed as _dist

        client = _dist.global_state.client
    except Exception:  # noqa: BLE001 — jax internals moved: no client
        return None
    if client is None or not hasattr(client, "key_value_set") or not (
        hasattr(client, "key_value_try_get")
    ):
        return None
    return client


def _strip_scheme(endpoint: str) -> str:
    """'tcp://host:port' -> 'host:port'. The reference API deals in ZMQ
    endpoints; jax's gRPC rendezvous wants a bare address."""
    return endpoint.split("://", 1)[1] if "://" in endpoint else endpoint


def local_ips() -> List[str]:
    """Addresses of this host (ref: util/net_util.cpp GetLocalIPAddress —
    used by the ZMQ backend to find this rank's line in the machine file)."""
    ips = {"127.0.0.1", "localhost"}
    try:
        hostname = socket.gethostname()
        ips.add(hostname)
        for info in socket.getaddrinfo(hostname, None):
            ips.add(info[4][0])
    except OSError:
        pass
    # getaddrinfo(gethostname()) commonly resolves to loopback (127.0.1.1 on
    # Debian-family hosts); the routing trick finds the primary NIC address
    # without sending a packet.
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            ips.add(s.getsockname()[0])
        finally:
            s.close()
    except OSError:
        pass
    return sorted(ips)


def parse_machine_file(path: str, default_port: int) -> List[str]:
    """Machine file -> ['host:port', ...]. Blank lines / '#' comments skipped
    (ref: zmq_net.h machine-file reading)."""
    endpoints = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if line.count(":") > 1 or line.startswith("["):
                # Rank inference matches on the host part split at the last
                # ':', which mis-parses IPv6 — fail loudly, not wrongly.
                Log.Fatal(
                    "IPv6 endpoints are not supported in the machine file "
                    f"(got {line!r}); use IPv4 or a hostname"
                )
            endpoints.append(line if ":" in line else f"{line}:{default_port}")
    return endpoints


def _infer_process_id(endpoints: Sequence[str]) -> int:
    mine = set(local_ips())
    hosts = [_strip_scheme(ep).rsplit(":", 1)[0] for ep in endpoints]
    if len(set(hosts)) != len(hosts):
        # Multiple processes per host can't be told apart by address — every
        # one would infer the first matching index and rendezvous as rank 0.
        Log.Fatal(
            "machine file lists a host more than once (multi-process-per-host); "
            "process rank cannot be inferred from addresses — pass an explicit "
            "-process_id per process"
        )
    for i, host in enumerate(hosts):
        if host in mine:
            return i
    # Second pass: a machine file may list FQDNs/aliases that differ from
    # gethostname() — resolve each entry and match addresses.
    for i, host in enumerate(hosts):
        try:
            resolved = {info[4][0] for info in socket.getaddrinfo(host, None)}
        except OSError:
            continue
        if resolved & mine:
            return i
    Log.Fatal(
        "none of this host's addresses (%s) appear in the machine file", mine
    )
    return -1  # unreachable


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> None:
    """Run the cluster rendezvous (the reference Controller's registration
    handshake — ref: src/controller.cpp:12-104 — performed by JAX's
    distributed service). Safe to call in a single-process run: with no
    coordinator and num_processes in (None, 0, 1) it is a no-op.
    ``auto=True`` lets jax detect everything from the pod environment
    (the ``-multihost`` flag path)."""
    global _initialized
    if _initialized:
        Log.Info("multihost already initialized; skipping")
        return
    if not auto:
        if coordinator_address is None and num_processes in (None, 0, 1):
            return  # single-process: nothing to rendezvous
        if num_processes == 1:
            Log.Info("single-process cluster; skipping distributed rendezvous")
            return
    # A multi-process CPU cluster (the test rig's 2-4 process "pod") needs
    # a cross-host collectives transport: newer jaxlib defaults CPU
    # multiprocess to gloo, older versions ship it but leave the default
    # on the unimplemented stub ("Multiprocess computations aren't
    # implemented on the CPU backend"). Opt in explicitly — must happen
    # before the backend initialises, which jax.distributed.initialize
    # triggers. TPU/GPU platforms ignore the CPU setting entirely.
    platforms = (getattr(jax.config, "jax_platforms", None) or "").split(",")
    if "cpu" in platforms:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # option absent (very old/new jax): keep its default
        try:
            # gloo's TCP pairs cannot take two in-flight collectives from
            # one process: async dispatch lets computation N+1's psum race
            # computation N's ("op.preamble.length <= op.nbytes" aborts).
            # Synchronous dispatch serialises them; CPU multiprocess is a
            # test rig, so the lost overlap is irrelevant.
            jax.config.update("jax_cpu_enable_async_dispatch", False)
        except Exception:
            pass
    # num_processes=None with a coordinator: jax infers the count from the
    # TPU pod environment. The rendezvous itself is BOUNDED (per-attempt
    # timeout) and retried with jittered backoff — a worker restarting
    # into a half-formed cluster after a host loss must converge or fail
    # loudly, never hang forever (resilience subsystem; chaos flag
    # -chaos_rendezvous_failures drills the retry path deterministically).
    from multiverso_tpu.resilience.chaos import (
        rendezvous_should_fail,
        with_retries,
    )
    from multiverso_tpu.serving import http_health

    # alive-vs-ready: a rank stuck in the rendezvous is ALIVE (beacons,
    # /livez) but must not read as ready — the supervisor's wedge
    # detector and external probes key on this phase transition
    http_health.set_ready(False, phase="rendezvous")

    timeout_s = max(1, int(GetFlag("rendezvous_timeout_s")))

    def _rendezvous() -> None:
        if rendezvous_should_fail():
            raise TimeoutError("chaos: injected rendezvous failure")
        try:
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    initialization_timeout=timeout_s,
                )
            except TypeError:  # older jax: no initialization_timeout kwarg
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                )
        except Exception as e:
            # initialize() done by someone else (embedding app, launcher)
            # is the success state, not an error. This can only happen on
            # the FIRST attempt: our own failed attempts tear down below.
            low = str(e).lower()
            if isinstance(e, RuntimeError) and (
                "already initialized" in low or "called once" in low
            ):
                return
            # a timed-out connect leaves jax's global distributed client
            # assigned, and the next initialize() would then refuse with
            # "should only be called once" instead of reconnecting — tear
            # the half-initialized service down so the retry is real
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 — best effort, keep the cause
                pass
            raise

    with_retries(
        _rendezvous,
        attempts=max(1, int(GetFlag("rendezvous_retries")) + 1),
        base_delay_s=0.2,
        max_delay_s=5.0,
        seed=(process_id or 0) + 1,
        describe="multihost rendezvous",
    )
    _initialized = True
    http_health.set_ready(False, phase="initialized")
    # obs: stamp the trace-clock anchor at the rendezvous — the one
    # instant every rank shares. `python -m multiverso_tpu.obs merge`
    # subtracts each rank's anchor to align the pod's monotonic clocks
    # onto one timeline.
    from multiverso_tpu.obs import tracer as _tracer

    _tracer.exchange_anchor()
    Log.Info(
        "multihost rendezvous complete: process %d/%d, %d global device(s)",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )


def initialize_from_machine_file(
    path: str, default_port: int = 55555, process_id: Optional[int] = None
) -> Tuple[int, int]:
    """The ZMQ deployment flow: rank = index of a local IP in the file,
    coordinator = line 0 (ref: zmq_net.h:63-109 rank-by-local-IP matching).
    Returns (process_id, num_processes)."""
    endpoints = parse_machine_file(path, default_port)
    CHECK(len(endpoints) > 0, f"machine file {path} lists no hosts")
    pid = _infer_process_id(endpoints) if process_id is None else process_id
    initialize(
        coordinator_address=_strip_scheme(endpoints[0]),
        num_processes=len(endpoints),
        process_id=pid,
    )
    return pid, len(endpoints)


def initialize_from_flags() -> None:
    """Flag-driven bootstrap used by ``MV_Init``: honours ``-coordinator`` /
    ``-process_id`` / ``-num_processes``, else ``-machine_file`` + ``-port``,
    else single-process no-op."""
    coordinator = GetFlag("coordinator")
    machine_file = GetFlag("machine_file")
    pid = GetFlag("process_id")
    if coordinator:
        initialize(
            coordinator_address=_strip_scheme(coordinator),
            num_processes=GetFlag("num_processes") or None,
            process_id=None if pid < 0 else pid,
        )
    elif machine_file:
        initialize_from_machine_file(
            machine_file, GetFlag("port"), None if pid < 0 else pid
        )
    elif GetFlag("num_processes") > 1 or pid >= 0:
        # -num_processes/-process_id without a coordinator source would
        # silently train N independent single-process clusters.
        Log.Fatal(
            "-num_processes/-process_id set but no -coordinator or "
            "-machine_file given; cannot rendezvous"
        )


_bound: Optional[Tuple[int, str]] = None


def net_bind(rank: int, endpoint: str) -> None:
    """``MV_NetBind`` semantics (ref: multiverso.h:47-56 — declare this
    process's rank and endpoint before wiring the cluster). On TPU this
    records the identity used by the next ``net_connect`` rendezvous."""
    global _bound
    _bound = (int(rank), endpoint)


def net_connect(ranks: Sequence[int], endpoints: Sequence[str]) -> None:
    """``MV_NetConnect`` semantics (ref: multiverso.h:57-65 — hand the full
    cluster endpoint list to every process). On TPU the list *is* the
    cluster: rank 0's endpoint becomes the coordinator and the rendezvous
    replaces the ZMQ DEALER mesh. Requires a prior ``net_bind`` (or a
    single-entry list for single-process runs)."""
    CHECK(len(ranks) == len(endpoints), "ranks/endpoints length mismatch")
    order = sorted(range(len(ranks)), key=lambda i: ranks[i])
    eps = [endpoints[i] for i in order]
    if len(eps) <= 1:
        return
    CHECK(_bound is not None, "MV_NetConnect requires a prior MV_NetBind")
    CHECK(
        _bound[0] in set(ranks),
        f"bound rank {_bound[0]} not in MV_NetConnect ranks {list(ranks)}",
    )
    # jax process ids are dense [0, n); the reference allows arbitrary rank
    # labels, so map the bound rank to its position in sorted order.
    pid = sorted(ranks).index(_bound[0])
    initialize(
        coordinator_address=_strip_scheme(eps[0]),
        num_processes=len(eps),
        process_id=pid,
    )


def build_multihost_mesh(
    num_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(worker, shard) mesh spanning every process.

    The shard ("server") axis is laid out over devices *within* a process's
    slice so table Get/Add collectives (all-gather / reduce-scatter over
    ``shard``) ride ICI; the worker (data) axis spans processes, so only the
    gradient/model-averaging all-reduce crosses DCN. This is the TPU analog
    of the reference's every-node-is-worker-and-server layout (ref:
    src/zoo.cpp:23-35) with the table traffic kept off the slow network.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    CHECK(n % max(num_shards, 1) == 0, f"{n} devices not divisible by {num_shards}")
    per_proc = n // max(jax.process_count(), 1)
    if num_shards > 1 and per_proc and per_proc % num_shards != 0:
        # Covers both num_shards > per_proc and non-dividing cases: either
        # way some shard group straddles a process boundary.
        Log.Info(
            "num_shards=%d does not divide per-process device count %d: some "
            "table shard groups will span DCN (works, but Get/Add "
            "collectives leave ICI — prefer a num_shards that divides %d)",
            num_shards,
            per_proc,
            per_proc,
        )
    # jax.devices() orders by process then local id, so build_mesh's
    # (workers, shards) reshape with shards fastest-varying keeps each shard
    # group within one process whenever num_shards divides per_proc.
    return mesh_lib.build_mesh(
        devices=devices, num_shards=num_shards if num_shards > 1 else None
    )


def host_local_to_global(mesh: Mesh, spec: P, host_local: np.ndarray) -> jax.Array:
    """Per-host input batch -> one global sharded array.

    Each process passes its *own* slice (e.g. the data blocks its readers
    produced — the reference's per-rank data loading, ref:
    Applications/WordEmbedding/src/distributed_wordembedding.cpp:152-154);
    the result is the concatenated global array sharded by ``spec``.
    Single-process: equivalent to ``jax.device_put``.
    """
    if jax.process_count() == 1:
        return jax.device_put(np.asarray(host_local), NamedSharding(mesh, spec))
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        np.asarray(host_local), mesh, spec
    )


def global_to_host_local(global_array: jax.Array, spec: Optional[P] = None):
    """Global sharded array -> this host's local slice (numpy). The inverse
    data-plane helper, used when saving shards or inspecting local state."""
    if jax.process_count() == 1:
        return np.asarray(global_array)
    from jax.experimental import multihost_utils

    mesh = global_array.sharding.mesh  # type: ignore[union-attr]
    if spec is None:
        spec = global_array.sharding.spec  # type: ignore[union-attr]
    return multihost_utils.global_array_to_host_local_array(
        global_array, mesh, spec
    )
