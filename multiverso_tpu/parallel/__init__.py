"""Parallelism layer: mesh construction, sharding specs, collectives, sync policy.

Replaces the reference's entire communication stack (SURVEY.md §2.2 —
NetInterface / MPINetWrapper / ZMQNetWrapper / AllreduceEngine): XLA
collectives over ICI/DCN are the transport, the mesh is the topology.
"""

from multiverso_tpu.parallel import collectives, multihost
from multiverso_tpu.parallel.mesh import (
    SHARD_AXIS,
    WORKER_AXIS,
    build_mesh,
    num_shards,
    num_workers,
    replicated_sharding,
    shard_axis_name,
    table_sharding,
    worker_sharding,
)

__all__ = [
    "collectives",
    "multihost",
    "SHARD_AXIS",
    "WORKER_AXIS",
    "build_mesh",
    "num_shards",
    "num_workers",
    "replicated_sharding",
    "shard_axis_name",
    "table_sharding",
    "worker_sharding",
]
